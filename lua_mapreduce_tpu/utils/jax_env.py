"""JAX backend bootstrapping for multi-process pools.

A TPU chip is a single-tenant resource: in an elastic pool, at most one
process owns the accelerator; the rest run host-path Python (exactly the
reference's split — APRIL-ANN kernels on the one GPU box, everything else
plain Lua workers). When the configured platform fails to initialize
(plugin contention, no chip on this host), fall back to CPU instead of
dying — a worker that loses the chip race is still a perfectly good
host-path worker.
"""

from __future__ import annotations

import os
import subprocess
import sys

_checked = False


def probe_backend(timeout_s: float = 120.0) -> bool:
    """Check from a THROWAWAY subprocess whether the default JAX backend
    initializes within ``timeout_s``. A wedged accelerator tunnel hangs
    ``jax.devices()`` inside an uninterruptible C call — the only safe
    probe is one we can kill. Returns True when the backend is usable."""
    code = "import jax; jax.devices(); print('ok')"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, timeout=timeout_s)
        return out.returncode == 0 and b"ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def force_cpu_if_unavailable(timeout_s: float = 120.0) -> str:
    """If the accelerator backend cannot initialize (probed from a
    killable subprocess), pin this process to CPU. Returns the platform
    chosen. Safe whether or not jax is already imported, as long as no
    backend has been initialized yet in this process."""
    if probe_backend(timeout_s):
        return "accelerator"
    print("[jax_env] accelerator backend unreachable; running on CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def ensure_backend(fallback: str = "cpu") -> str:
    """Initialize the default JAX backend, falling back to ``fallback``
    when the preferred platform cannot start. Returns the platform name.
    Safe to call multiple times; only the first call probes."""
    global _checked
    import jax

    try:
        platform = jax.devices()[0].platform
        _checked = True
        return platform
    except RuntimeError as e:
        if not _checked:
            print(f"[jax_env] accelerator backend unavailable "
                  f"({str(e).splitlines()[0]}); falling back to "
                  f"{fallback}", file=sys.stderr)
        _checked = True
        jax.config.update("jax_platforms", fallback)
        return jax.devices()[0].platform
