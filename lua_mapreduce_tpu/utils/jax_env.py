"""JAX backend bootstrapping for multi-process pools.

A TPU chip is a single-tenant resource: in an elastic pool, at most one
process owns the accelerator; the rest run host-path Python (exactly the
reference's split — APRIL-ANN kernels on the one GPU box, everything else
plain Lua workers). When the configured platform fails to initialize
(plugin contention, no chip on this host), fall back to CPU instead of
dying — a worker that loses the chip race is still a perfectly good
host-path worker.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

_checked = False
# in-process memo: (platform key) → (verdict, monotonic stamp); entries
# expire on the same TTLs as the disk cache and are keyed on JAX_PLATFORMS
# so a post-fallback re-probe isn't answered with the accelerator verdict
_probe_memo: dict = {}

# both verdicts expire: a healthy tunnel can wedge after a positive probe
# (the hang the probe exists to prevent) and a wedged one can recover
POSITIVE_PROBE_TTL_S = 600.0
NEGATIVE_PROBE_TTL_S = 300.0


def _probe_cache_path() -> str:
    """Per-boot, per-uid cache file so an N-process pool pays the probe
    subprocess once, not N times (boot id keys it: a reboot may change
    the chip; uid keys it: the shared tempdir is other-user-writable and
    a predictable name could be pre-poisoned)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = "unknown"
    plat = os.environ.get("JAX_PLATFORMS", "default").replace(",", "_")
    base = os.environ.get("XDG_RUNTIME_DIR") or tempfile.gettempdir()
    return os.path.join(base,
                        f"lua_mr_tpu_probe_{os.getuid()}_{plat}_{boot}")


def probe_backend(timeout_s: float = 120.0, fresh: bool = False) -> bool:
    """Check from a THROWAWAY subprocess whether the default JAX backend
    initializes within ``timeout_s``. A wedged accelerator tunnel hangs
    ``jax.devices()`` inside an uninterruptible C call — the only safe
    probe is one we can kill. Results are cached in-process and on disk
    per boot with a TTL per verdict. Returns True when usable.

    ``fresh=True`` skips BOTH cache reads (still records the result):
    retry loops use it so a negative verdict cached minutes ago can't
    mask a tunnel that has since recovered."""
    key = os.environ.get("JAX_PLATFORMS", "default")
    if not fresh:
        hit = _probe_memo.get(key)
        if hit is not None:
            verdict, stamp = hit
            ttl = POSITIVE_PROBE_TTL_S if verdict else NEGATIVE_PROBE_TTL_S
            if time.monotonic() - stamp < ttl:
                return verdict
        cache = _probe_cache_path()
        try:
            st = os.stat(cache)
            if st.st_uid == os.getuid():  # ignore files planted by others
                with open(cache) as f:
                    verdict = f.read().strip()
                age = time.time() - st.st_mtime
                if verdict == "ok" and age < POSITIVE_PROBE_TTL_S:
                    return True         # not memoized: TTL must re-check
                if verdict == "fail" and age < NEGATIVE_PROBE_TTL_S:
                    return False
        except OSError:
            pass
    cache = _probe_cache_path()

    code = "import jax; jax.devices(); print('ok')"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, timeout=timeout_s)
        ok = out.returncode == 0 and b"ok" in out.stdout
    except subprocess.TimeoutExpired:
        ok = False
    _probe_memo[key] = (ok, time.monotonic())
    try:
        tmp = cache + f".{os.getpid()}"
        with open(tmp, "w") as f:
            f.write("ok" if ok else "fail")
        os.replace(tmp, cache)
    except OSError:
        pass
    return ok


def force_cpu_if_unavailable(timeout_s: float = 120.0, retries: int = 1,
                             retry_wait_s: float = 60.0) -> str:
    """If the accelerator backend cannot initialize (probed from a
    killable subprocess), pin this process to CPU. Returns the platform
    chosen. Safe whether or not jax is already imported, as long as no
    backend has been initialized yet in this process.

    ``retries > 1`` re-probes a negative verdict that many times total,
    FRESH (cache-bypassing), ``retry_wait_s`` apart — for callers like
    bench.py whose one driver-kept artifact justifies spending minutes
    to catch a tunnel that recovered after the cached negative."""
    # already pinned to CPU (test conftest, an earlier fallback, or the
    # environment)? — nothing to probe, and probing would burn the full
    # subprocess timeout against a wedged tunnel for no decision
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the env var ALONE is not enough: the axon plugin's
        # sitecustomize overrides it, so the process would still
        # initialize (and hang on) the tunnel backend — pin the config
        import jax
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    j = sys.modules.get("jax")
    if j is not None and getattr(j.config, "jax_platforms", None) == "cpu":
        return "cpu"
    for attempt in range(max(1, retries)):
        if probe_backend(timeout_s, fresh=attempt > 0):
            return "accelerator"
        if attempt + 1 < retries:
            print(f"[jax_env] accelerator probe failed "
                  f"(attempt {attempt + 1}/{retries}); retrying in "
                  f"{retry_wait_s:.0f}s", file=sys.stderr)
            time.sleep(retry_wait_s)
    print("[jax_env] accelerator backend unreachable; running on CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def ensure_backend(fallback: str = "cpu") -> str:
    """Initialize the default JAX backend, falling back to ``fallback``
    when the preferred platform cannot start. Returns the platform name.
    Safe to call multiple times; only the first call probes."""
    global _checked
    import jax

    try:
        platform = jax.devices()[0].platform
        _checked = True
        return platform
    except RuntimeError as e:
        if not _checked:
            print(f"[jax_env] accelerator backend unavailable "
                  f"({str(e).splitlines()[0]}); falling back to "
                  f"{fallback}", file=sys.stderr)
        _checked = True
        jax.config.update("jax_platforms", fallback)
        return jax.devices()[0].platform
