"""LMR_LOCKCHECK=1 runtime lock-order sanitizer.

The static pass (analysis/lockset.py) claims it knows the package's
whole locking plane: every Lock/RLock creation site and every
acquisition order two locks can nest in.  This watchdog makes that
claim falsifiable at runtime — the same static<->dynamic replay
discipline the protocol checker applies to its seeded races, pointed
at the lock plane:

- ``install()`` replaces ``threading.Lock``/``threading.RLock`` with
  site-keyed recording proxies.  Only locks created *inside the
  package* are wrapped (the creation frame decides); stdlib internals —
  Condition's hidden RLock, Event, Queue, ThreadPoolExecutor — get the
  raw factory back, so the overhead rides only on the handful of locks
  the static model actually covers.
- Each proxy keeps a thread-local held stack.  Acquiring B while
  holding A records the directed edge ``site(A) -> site(B)`` (distinct
  sites only: two instances of one creation site are one static label,
  so their mutual order is instance-ambiguous by construction — the
  static model skips those self-edges for exactly the same reason).
- ``verify(static_model)`` replays the observations against
  ``lockset.static_lock_model()``: an observed lock at a site the
  model does not know, an observed order edge the model does not
  contain, or any order between two statically-cyclic labels is a
  violation — the chaos-suite gate fails on any of them.

The clock is injectable (``install(clock=...)``): hold-duration
bookkeeping (``max_hold_s`` per site in ``report()``) must be
replay-deterministic under test like every other timing in this
package (LMR010's discipline).

Overhead discipline: the proxy adds one dict-free method hop per
acquire/release; bench.py's ``lockcheck_overhead`` detail field pins
the chaos-leg ratio <= 1.02 with byte-identical outputs.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_real_lock = threading.Lock
_real_rlock = threading.RLock

_installed = False
_clock: Callable[[], float] = time.monotonic

# recorder state, guarded by a RAW lock (never a proxy: the recorder
# must not observe itself)
_state_lock = _real_lock()
_sites: Set[str] = set()
_edges: Set[Tuple[str, str]] = set()
_acquisitions = 0
_max_hold: Dict[str, float] = {}

_tls = threading.local()           # .held: list of site keys, stack order


def _held_stack() -> List[str]:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _creation_site() -> Optional[str]:
    """``rel:line`` of the frame creating the lock when it is package
    code; None otherwise.  A creator frame inside threading.py means a
    stdlib internal (Condition's hidden RLock, Event's lock, ...) —
    those are synthesized in the static model and stay raw here."""
    f = sys._getframe(2)
    if f is None or f.f_code.co_filename.endswith("threading.py"):
        return None
    fn = os.path.abspath(f.f_code.co_filename)
    if not fn.startswith(_PKG_DIR + os.sep):
        return None
    rel = os.path.relpath(fn, _PKG_DIR).replace(os.sep, "/")
    return f"{rel}:{f.f_lineno}"


class _LockProxy:
    """A recording wrapper around one real lock. Not a subclass — the
    real types are C builtins — but covers the full with/acquire/
    release surface the package uses (LMR001 bans bare acquire outside
    try/finally, so the surface is small and audited)."""

    __slots__ = ("_lock", "site", "_t0")

    def __init__(self, lock, site: str):
        self._lock = lock
        self.site = site
        self._t0 = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._record_acquire()
        return got

    def release(self) -> None:
        self._record_release()
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _record_acquire(self) -> None:
        global _acquisitions
        st = _held_stack()
        t = _clock()
        with _state_lock:
            _acquisitions += 1
            for held in st:
                if held != self.site:
                    _edges.add((held, self.site))
        if not st or st[-1] != self.site:
            self._t0 = t
        st.append(self.site)

    def _record_release(self) -> None:
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == self.site:
                del st[i]
                break
        if self.site not in st:
            hold = _clock() - self._t0
            with _state_lock:
                if hold > _max_hold.get(self.site, 0.0):
                    _max_hold[self.site] = hold


def _make_factory(real):
    def factory(*a, **kw):
        site = _creation_site()
        lock = real(*a, **kw)
        if site is None:
            return lock                  # stdlib / test-harness lock
        with _state_lock:
            _sites.add(site)
        return _LockProxy(lock, site)
    return factory


def install(clock: Callable[[], float] = time.monotonic) -> None:
    """Patch the Lock/RLock factories and start recording. Idempotent."""
    global _installed, _clock
    if _installed:
        return
    _clock = clock
    threading.Lock = _make_factory(_real_lock)
    threading.RLock = _make_factory(_real_rlock)
    _installed = True


def uninstall() -> None:
    """Restore the real factories (observations are kept for report/
    verify; call reset() to drop them)."""
    global _installed
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    _installed = False


def reset() -> None:
    global _acquisitions
    with _state_lock:
        _sites.clear()
        _edges.clear()
        _max_hold.clear()
        _acquisitions = 0


def report() -> dict:
    """Everything observed so far: creation sites, distinct-site
    acquisition-order edges, acquisition count, per-site max hold."""
    with _state_lock:
        return {"sites": sorted(_sites),
                "edges": sorted(_edges),
                "acquisitions": _acquisitions,
                "max_hold_s": dict(sorted(_max_hold.items()))}


def verify(static_model: dict) -> List[str]:
    """Replay observations against ``lockset.static_lock_model()``.
    Returns violation strings (empty = the static model held): a lock
    the model never discovered, an acquisition order it never derived,
    or an observed order between statically-cyclic labels."""
    rep = report()
    locks: Dict[str, str] = static_model.get("locks", {})
    edges = {tuple(e) for e in static_model.get("edges", [])}
    cyclic = set(static_model.get("cyclic", []))
    viol: Set[str] = set()
    for site in rep["sites"]:
        if site not in locks:
            viol.add(f"lock created at unmodeled site {site} — the "
                     f"static pass never discovered it")
    for a, b in rep["edges"]:
        la, lb = locks.get(a), locks.get(b)
        if la is None or lb is None:
            continue                     # already reported above
        if la == lb:
            continue                     # instance-ambiguous self-pair
        if (la, lb) not in edges:
            viol.add(f"unmodeled acquisition order {la} -> {lb} "
                     f"(observed {a} -> {b}) — the static order graph "
                     f"missed this nesting")
        if la in cyclic and lb in cyclic:
            viol.add(f"observed an order between statically-cyclic "
                     f"locks {la} -> {lb} — the deadlock the static "
                     f"pass flagged is reachable")
    return sorted(viol)


def utest() -> None:
    """Self-test: package-site locks are wrapped and recorded, stdlib
    creations are not, edges replay against a model, and verify flags
    both an unknown site and an unknown order."""
    assert threading.Lock is _real_lock or not _installed
    now = [0.0]
    install(clock=lambda: now[0])
    try:
        reset()
        a = threading.Lock()
        b = threading.RLock()
        assert isinstance(a, _LockProxy) and isinstance(b, _LockProxy)
        assert a.site.startswith("utils/lockcheck.py:"), a.site
        # Condition's internal RLock is created inside threading.py:
        # raw, invisible, zero overhead
        cond = threading.Condition()
        assert not isinstance(cond._lock, _LockProxy)
        with a:
            now[0] += 0.25
            with b:
                pass
        rep = report()
        assert rep["acquisitions"] == 2
        assert rep["edges"] == [(a.site, b.site)], rep
        assert rep["max_hold_s"][a.site] >= 0.25
        model = {"locks": {a.site: "A", b.site: "B"},
                 "edges": [["A", "B"]], "cyclic": []}
        assert verify(model) == [], verify(model)
        # reversed nesting: an order the model does not contain
        with b:
            with a:
                pass
        bad = verify(model)
        assert any("unmodeled acquisition order B -> A" in v
                   for v in bad), bad
        # a lock at a site the model never saw
        reset()
        c = threading.Lock()
        with c:
            pass
        bad = verify(model)
        assert any("unmodeled site" in v for v in bad), bad
        # statically-cyclic labels observed in any order = violation
        model2 = {"locks": {a.site: "A", b.site: "B"},
                  "edges": [["A", "B"], ["B", "A"]],
                  "cyclic": ["A", "B"]}
        reset()
        with a:
            with b:
                pass
        bad = verify(model2)
        assert any("statically-cyclic" in v for v in bad), bad
        reset()
    finally:
        uninstall()
    assert threading.Lock is _real_lock
    # the real package model is self-consistent: every modeled site
    # parses and no label is cyclic (the package ships deadlock-free)
    from lua_mapreduce_tpu.analysis.lockset import static_lock_model
    model = static_lock_model()
    assert model["locks"] and not model["cyclic"]
    print("lockcheck utest ok")
