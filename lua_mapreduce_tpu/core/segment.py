"""Framed binary spill segments (``JSEG0001``) — the v2 shuffle data plane.

The reference streams intermediate map output as text lines through every
backend (utils.lua:107-120, 133-200) and v1 here kept that faithfully: one
JSON record per line, parsed one ``json.loads`` at a time. Once the shuffle
is pipelined (DESIGN §15) and the control plane batched (DESIGN §16), that
per-record encoding IS the dominant data-plane cost. Exoshuffle-CloudSort
(arXiv:2301.03734) and FaaSTube (arXiv:2411.01830) both locate shuffle
throughput in the record format + IO-granularity layer: pack records into
block-sized frames, address them with an index, and move them with few
large ranged reads instead of thousands of line reads. This module is that
layer for the intermediate store.

File layout (all integers little-endian)::

    "JSEG0001"                                    8-byte magic
    frame*                                        data region
    footer                                        JSON, utf-8
    footer_off:u64 footer_len:u32 footer_crc:u32  24-byte trailer
    "JSEG0001"

    frame := enc_len:u32 dec_len:u32 codec:u8 crc:u32  payload[enc_len]

The *decoded* frame payload is exactly v1 text — concatenated
``dump_record`` lines — so v1 ↔ v2 conversion is pure re-framing and the
frame decoder can batch-parse a whole frame with ONE ``json.loads`` (JSON
strings never contain a raw newline, so joining lines with ``,`` inside
``[...]`` is loss-free). ``crc`` guards the decoded payload (CRC-32/zlib),
``codec`` is per-frame: 0 raw, 1 zlib, 2 lz4 (gated on the ``lz4`` package
being importable; never the default). The footer carries the frame index —
``[offset, enc_len, dec_len, first_key]`` per frame, ``first_key`` being
the serialized JSON of the frame's first record key — so consumers seek
straight to the frames they need and batch consecutive frames into ~1MB
ranged reads.

Readers NEVER need negotiation: :func:`open_segment` sniffs the 8-byte
magic (a v1 text line always starts with ``[``) and
:func:`record_stream` serves both formats, so mixed fleets and old
on-disk runs keep working. Writers negotiate via the task document
(``Server(segment_format=...)``, CLI ``--segment-format``); final reduce
results stay v1 text always, keeping every golden byte-compare intact.
"""

from __future__ import annotations

import json
import logging
import struct
import zlib
from typing import Any, Iterator, List, Optional, Tuple

from lua_mapreduce_tpu.core import tuples
from lua_mapreduce_tpu.core.serialize import (dump_key, dump_record,
                                              load_record)

_log = logging.getLogger(__name__)

MAGIC = b"JSEG0001"
FRAME_BYTES = 1 << 18          # ~256KB decoded payload per frame
READAHEAD_BYTES = 1 << 20      # batch consecutive frames into ~1MB reads

CODEC_RAW = 0
CODEC_ZLIB = 1
CODEC_LZ4 = 2

_FRAME_HDR = struct.Struct("<IIBI")     # enc_len, dec_len, codec, crc
_TRAILER = struct.Struct("<QII8s")      # footer_off, footer_len, crc, magic

FORMATS = ("v1", "v2")

try:                                    # lz4 is optional, never required
    import lz4.block as _lz4            # type: ignore
except ImportError:                     # pragma: no cover - env-dependent
    _lz4 = None


def check_format(fmt: str) -> str:
    if fmt not in FORMATS:
        raise ValueError(f"unknown segment format {fmt!r}; use one of "
                         f"{FORMATS}")
    return fmt


def _encode_frame(payload: bytes, codec: str) -> Tuple[bytes, int]:
    """Compress ``payload`` per the requested codec; fall back to raw
    when compression does not shrink the frame (incompressible data must
    not grow, and the codec byte is per-frame exactly for this)."""
    if codec == "zlib":
        comp = zlib.compress(payload, 1)
        if len(comp) < len(payload):
            return comp, CODEC_ZLIB
    elif codec == "lz4":
        if _lz4 is None:
            # ValueError: a deterministic config error the fault
            # taxonomy maps as permanent — this raise crosses the retry
            # boundary through every spill build (LMR014)
            raise ValueError("segment codec 'lz4' needs the lz4 package; "
                             "use 'zlib' or 'raw'")
        comp = _lz4.compress(payload, store_size=False)
        if len(comp) < len(payload):
            return comp, CODEC_LZ4
    elif codec != "raw":
        raise ValueError(f"unknown segment codec {codec!r}")
    return payload, CODEC_RAW


def _decode_frame(data: bytes, dec_len: int, codec: int, crc: int,
                  where: str) -> bytes:
    if codec == CODEC_RAW:
        payload = data
    elif codec == CODEC_ZLIB:
        payload = zlib.decompress(data)
    elif codec == CODEC_LZ4:
        if _lz4 is None:
            raise ValueError(f"{where}: lz4-compressed frame but the lz4 "
                             "package is not importable")
        payload = _lz4.decompress(data, uncompressed_size=dec_len)
    else:
        raise ValueError(f"{where}: unknown frame codec {codec}")
    if len(payload) != dec_len:
        raise ValueError(f"{where}: frame decoded to {len(payload)} bytes, "
                         f"index says {dec_len}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise ValueError(f"{where}: frame CRC mismatch (corrupt segment)")
    return payload


class SegmentWriter:
    """Pack records into frames over a builder's raw-bytes surface.

    Same duck-type as :class:`TextWriter` (``add`` / ``add_line`` /
    ``build``), so every spill writer switches format by construction
    alone. Frames close at ~``frame_bytes`` of decoded payload; the
    footer indexes every frame; ``build`` publishes atomically through
    the underlying builder.
    """

    def __init__(self, builder, codec: str = "zlib",
                 frame_bytes: int = FRAME_BYTES):
        self._b = builder
        self._codec = codec
        self._frame_bytes = frame_bytes
        self._lines: List[str] = []
        self._size = 0
        self._first_key: Optional[str] = None   # serialized key JSON
        self._index: List[list] = []            # [off, enc, dec, first_key]
        self._off = len(MAGIC)
        self._records = 0
        self._decoded_bytes = 0
        # producer-known key metadata: True while every record key is a
        # plain str. Carried in the footer; it licenses the C-speed
        # heapq merge in core/merge.py (native tuple comparison IS
        # key_lt's order within the str rank) — a property v1 text can
        # never promise without a full scan.
        self._str_keys = True
        self._b.write_bytes(MAGIC)

    def add(self, key: Any, values: Any) -> None:
        self.add_line(key, dump_record(key, values))

    def add_line(self, key: Any, line: str) -> None:
        """Append a pre-serialized record line (no trailing newline) —
        the push buffer's re-serialization-free path (engine/push.py
        holds lines, not records): ``key`` still feeds the footer's
        first-key index and the str_keys merge promise."""
        if type(key) is not str:
            self._str_keys = False
        if self._first_key is None:
            self._first_key = dump_key(key)
        self._lines.append(line)
        self._size += len(line) + 1
        if self._size >= self._frame_bytes:
            self._close_frame()

    def _close_frame(self) -> None:
        if not self._lines:
            return
        payload = ("\n".join(self._lines) + "\n").encode("utf-8")
        self._records += len(self._lines)
        self._decoded_bytes += len(payload)
        data, codec = _encode_frame(payload, self._codec)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._b.write_bytes(_FRAME_HDR.pack(len(data), len(payload),
                                            codec, crc))
        self._b.write_bytes(data)
        self._index.append([self._off, len(data), len(payload),
                            self._first_key])
        self._off += _FRAME_HDR.size + len(data)
        self._lines, self._size, self._first_key = [], 0, None

    @property
    def compressed_frames(self) -> int:
        """How many closed frames actually shrank under the codec —
        the adaptive-codec signal (engine/push.py): a writer whose
        frames keep falling back to raw is paying compression CPU for
        nothing, which a GB-scale incompressible sort cannot afford."""
        return sum(1 for _off, enc, dec, _k in self._index if enc < dec)

    def build(self, name: str) -> None:
        self._close_frame()
        footer = json.dumps({
            "v": 1,
            "frames": self._index,
            "records": self._records,
            "decoded_bytes": self._decoded_bytes,
            "str_keys": self._str_keys,
        }, separators=(",", ":")).encode("utf-8")
        self._b.write_bytes(footer)
        self._b.write_bytes(_TRAILER.pack(self._off, len(footer),
                                          zlib.crc32(footer) & 0xFFFFFFFF,
                                          MAGIC))
        self._b.build(name)

    def close(self) -> None:
        self._b.close()

    def __enter__(self) -> "SegmentWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class TextWriter:
    """v1 record writer: one JSON line per record through a plain
    builder — byte-identical to the historical spill format."""

    def __init__(self, builder):
        self._b = builder

    def add(self, key: Any, values: Any) -> None:
        self._b.write(dump_record(key, values) + "\n")

    def add_line(self, key: Any, line: str) -> None:
        """Pre-serialized-line twin of ``add`` (SegmentWriter parity —
        push writers switch format by construction alone)."""
        self._b.write(line + "\n")

    def build(self, name: str) -> None:
        self._b.build(name)

    def close(self) -> None:
        self._b.close()

    def __enter__(self) -> "TextWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def writer_for(store, segment_format: str = "v1", codec: str = "zlib"):
    """Spill writer over a fresh builder of ``store`` in the negotiated
    format. The ONE switch point every spill producer goes through."""
    check_format(segment_format)
    if segment_format == "v2":
        return SegmentWriter(store.builder(), codec=codec)
    return TextWriter(store.builder())


# parsed-footer cache: the incremental inbox merge (engine/push.py,
# DESIGN §24) opens the same frame files repeatedly — pre-merge input
# probes, reduce pull-integrity plus merge, failover re-opens — and
# every SegmentReader construction paid the trailer + footer ranged
# reads again. The parsed footer is cached per (name, size) ON the
# innermost store object (lifetime tied to the store: no cross-store
# collisions, no stale id reuse), bounded FIFO. Safe under the engine's
# deterministic-overwrite contract (duplicate publishes write identical
# bytes — job.py's stated assumption), and the size key evicts any
# honest rewrite that changed length.
_FOOTER_CACHE_CAP = 1024
FOOTER_READS_SAVED = 0          # regression-test observability


def _footer_cache(store) -> Optional[dict]:
    from lua_mapreduce_tpu.faults.wrappers import unwrap
    host = unwrap(store)
    cache = getattr(host, "_jseg_footers", None)
    if cache is None:
        try:
            cache = host._jseg_footers = {}
        except Exception:       # slotted third-party store: skip caching
            return None
    return cache


def purge_footer_cache(store) -> None:
    """Drop every cached footer of ``store`` — the iteration-rollover
    hook: loop tasks REUSE run/fragment names with different contents,
    and fixed-width records (a sort keyspace) can reproduce the exact
    byte size, so the (name, size) key alone cannot catch the rewrite.
    Both engines call this from their iteration-start cleanup
    (Server._clean_runs, LocalExecutor.run_one_iteration)."""
    from lua_mapreduce_tpu.faults.wrappers import unwrap
    cache = getattr(unwrap(store), "_jseg_footers", None)
    if cache:
        cache.clear()


class SegmentReader:
    """Lazy frame decoder over a store's ranged-read surface.

    The footer index is read once per FILE, not per reader: two small
    ranged reads (trailer, then footer) on first open, a per-store
    parsed-footer cache hit on every re-open (see ``_footer_cache``).
    ``iter_records`` walks frames in order, batching consecutive frames
    into ~``readahead`` ranged reads and batch-parsing each frame with
    one ``json.loads``. Nothing beyond one read batch is ever resident.
    """

    def __init__(self, store, name: str, head: Optional[bytes] = None):
        self._store = store
        self._name = name
        self._whole: Optional[bytes] = None   # degradation cache, see _ranged
        size = self._size = store.size(name)
        if size < len(MAGIC) + _TRAILER.size:
            raise ValueError(f"{name}: segment too short ({size} bytes)")
        if head is None:
            head = store.read_range(name, 0, len(MAGIC))
        if head[:len(MAGIC)] != MAGIC:
            raise ValueError(f"{name}: not a JSEG0001 segment")
        cache = _footer_cache(store)
        meta = cache.get((name, size)) if cache is not None else None
        if meta is not None:
            global FOOTER_READS_SAVED
            FOOTER_READS_SAVED += 2        # trailer + footer skipped
        else:
            trailer = self._ranged(size - _TRAILER.size, _TRAILER.size)
            foot_off, foot_len, foot_crc, magic = _TRAILER.unpack(trailer)
            if magic != MAGIC:
                raise ValueError(f"{name}: segment trailer magic mismatch "
                                 "(truncated or corrupt)")
            footer = self._ranged(foot_off, foot_len)
            if zlib.crc32(footer) & 0xFFFFFFFF != foot_crc:
                raise ValueError(f"{name}: segment footer CRC mismatch")
            meta = json.loads(footer)
            if cache is not None:
                try:
                    if len(cache) >= _FOOTER_CACHE_CAP:
                        cache.pop(next(iter(cache)))    # FIFO bound
                except (KeyError, StopIteration):
                    pass        # concurrent evictor won the race: fine
                cache[(name, size)] = meta
        self.frames: List[list] = meta["frames"]   # [off, enc, dec, key]
        self.records: int = meta.get("records", 0)
        self.decoded_bytes: int = meta.get("decoded_bytes", 0)
        # producer promise: every key is a plain str (absent/False when
        # unknown) — consumers may then merge with native comparisons
        self.str_keys: bool = bool(meta.get("str_keys", False))

    # -- frame access -------------------------------------------------------

    def _ranged(self, off: int, length: int) -> bytes:
        """A ranged read with the degradation rung of DESIGN §19: when a
        ranged read fails with a TRANSIENT store fault that outlived the
        retry layer's budget, fall back to ONE whole-file read and serve
        every remaining range from memory — the same shape as the native
        merge's Python fallback and the premerge poison-to-raw-runs
        ladder. Permanent and non-storage errors propagate untouched."""
        if self._whole is not None:
            return self._whole[off:off + length]
        try:
            return self._store.read_range(self._name, off, length)
        except Exception as exc:
            from lua_mapreduce_tpu.faults.errors import classify_exception
            # the backend's own classify hook when it has one (it knows
            # its SDK's error shapes); the central table for duck-typed
            # third-party stores
            classify = getattr(self._store, "classify", classify_exception)
            if classify(exc) is not True:
                raise
            from lua_mapreduce_tpu.faults.retry import COUNTERS
            self._whole = self._store.read_range(self._name, 0, self._size)
            COUNTERS.bump("degraded_reads")
            _log.warning("%s: ranged reads failing (%s) — degraded to a "
                         "whole-file read (%d bytes)", self._name,
                         type(exc).__name__, self._size)
            return self._whole[off:off + length]

    def frame_payload(self, idx: int, blob: Optional[bytes] = None,
                      blob_off: int = 0) -> bytes:
        """Decoded text payload of frame ``idx`` (from ``blob`` when the
        caller already holds a read batch covering it)."""
        off, enc, dec, _ = self.frames[idx]
        if blob is None:
            blob = self._ranged(off, _FRAME_HDR.size + enc)
            blob_off = off
        base = off - blob_off
        enc_len, dec_len, codec, crc = _FRAME_HDR.unpack_from(blob, base)
        if enc_len != enc or dec_len != dec:
            raise ValueError(f"{self._name}: frame {idx} header disagrees "
                             "with footer index (corrupt segment)")
        data = blob[base + _FRAME_HDR.size:base + _FRAME_HDR.size + enc_len]
        return _decode_frame(data, dec_len, codec, crc,
                             f"{self._name} frame {idx}")

    def _read_batches(self, readahead: int) -> Iterator[Tuple[int, int,
                                                              bytes]]:
        """(first_frame_idx, n_frames, blob) over ~readahead-sized ranged
        reads of consecutive frames."""
        i, n = 0, len(self.frames)
        while i < n:
            j, total = i, 0
            while j < n and (j == i or total +
                             _FRAME_HDR.size + self.frames[j][1] <= readahead):
                total += _FRAME_HDR.size + self.frames[j][1]
                j += 1
            off = self.frames[i][0]
            yield i, j - i, self._ranged(off, total)
            i = j

    # -- record access ------------------------------------------------------

    def iter_records(self, readahead: int = READAHEAD_BYTES
                     ) -> Iterator[Tuple[Any, List[Any]]]:
        intern = tuples.intern
        for first, count, blob in self._read_batches(readahead):
            blob_off = self.frames[first][0]
            for idx in range(first, first + count):
                payload = self.frame_payload(idx, blob, blob_off)
                # frame-level batch decode: ONE json.loads per frame.
                # JSON strings carry newlines only as the two-character
                # escape \n, so splicing lines with "," is loss-free.
                recs = json.loads(b"[" + payload[:-1].replace(b"\n", b",")
                                  + b"]")
                for rec in recs:
                    key = rec[0]
                    if type(key) is list:
                        key = intern(key)
                    yield key, rec[1]

    def iter_lines(self, readahead: int = READAHEAD_BYTES) -> Iterator[str]:
        """The segment's records as v1 text lines (with newline) — the
        re-framing surface for v2 → v1 conversion and text-shim reads."""
        for first, count, blob in self._read_batches(readahead):
            blob_off = self.frames[first][0]
            for idx in range(first, first + count):
                payload = self.frame_payload(idx, blob, blob_off)
                # split on \n ONLY — str.splitlines would also split on
                # U+2028/U+2029, which JSON strings may carry raw under
                # ensure_ascii=False; record separators are always \n
                parts = payload.decode("utf-8").split("\n")
                for part in parts[:-1]:
                    yield part + "\n"
                if parts[-1]:
                    yield parts[-1]


def open_segment(store, name: str) -> Optional[SegmentReader]:
    """SegmentReader for ``name``, or None when it is not a v2 segment —
    v1 text (first byte is ``[``), or a store without the raw-bytes
    surface (duck-typed fakes). Detection is per FILE, so mixed-format
    namespaces (old runs, v1-only workers in the fleet) always read."""
    read_range = getattr(store, "read_range", None)
    if read_range is None or getattr(store, "size", None) is None:
        return None
    try:
        head = read_range(name, 0, len(MAGIC))
    except (OSError, KeyError):
        # missing-file shapes of the bundled backends (sharedfs/objectfs
        # FileNotFoundError, memfs KeyError): let the caller's text path
        # surface its own not-found error. Anything else (a transient
        # store failure on a real segment) must PROPAGATE — degrading to
        # the text reader would mask it behind a decode error
        return None
    if head[:len(MAGIC)] != MAGIC:
        return None
    return SegmentReader(store, name, head=head)


def record_stream(store, name: str) -> Iterator[Tuple[Any, List[Any]]]:
    """(key, values) stream over ``name`` in WHICHEVER format it carries
    — the one reader every merge/premerge consumer uses."""
    reader = open_segment(store, name)
    if reader is not None:
        return reader.iter_records()
    return _text_records(store, name)


def _text_records(store, name: str) -> Iterator[Tuple[Any, List[Any]]]:
    for line in store.lines(name):
        line = line.strip()
        if line:
            yield load_record(line)


def utest() -> None:
    """Self-test: frame packing, codec fallback, batch decode, ranged
    index, text round-trip, and the sniffing reader."""
    from lua_mapreduce_tpu.store.memfs import MemStore

    store = MemStore()
    recs = [(f"k{i:04d}", [i, str(i), [i, i + 1]]) for i in range(500)]

    with writer_for(store, "v2", codec="zlib") as w:
        for k, v in recs:
            w.add(k, v)
        w.build("seg.P0.M1")

    r = open_segment(store, "seg.P0.M1")
    assert r is not None and r.records == 500
    assert list(r.iter_records()) == recs
    assert [k for k, _ in (load_record(l) for l in r.iter_lines())] == \
        [k for k, _ in recs]
    assert r.frames[0][3] == '"k0000"'       # first-key index

    # v1 writer + the format-agnostic stream
    with writer_for(store, "v1") as w1:
        for k, v in recs[:3]:
            w1.add(k, v)
        w1.build("txt.P0.M2")
    assert open_segment(store, "txt.P0.M2") is None
    assert list(record_stream(store, "txt.P0.M2")) == recs[:3]
    assert list(record_stream(store, "seg.P0.M1")) == recs

    # incompressible payload falls back to raw frames, tiny readahead
    # exercises multi-batch ranged reads
    import random
    rng = random.Random(0)
    noisy = [("k%04d" % i,
              ["".join(rng.choice("abcdefghijklmnopqrstuvwxyz0123456789")
                       for _ in range(40))]) for i in range(64)]
    with SegmentWriter(store.builder(), codec="zlib", frame_bytes=512) as w:
        for k, v in noisy:
            w.add(k, v)
        w.build("noisy")
    r = open_segment(store, "noisy")
    assert len(r.frames) > 1
    assert list(r.iter_records(readahead=600)) == noisy

    # corruption is detected loudly
    raw = store._files["seg.P0.M1"]
    flip = len(MAGIC) + _FRAME_HDR.size + 4
    store._files["bad"] = (raw[:flip] +
                           bytes([raw[flip] ^ 0xFF]) + raw[flip + 1:])
    try:
        list(open_segment(store, "bad").iter_records())
    except (ValueError, zlib.error):
        pass
    else:                      # pragma: no cover
        raise AssertionError("corrupt frame must not decode silently")

    try:
        check_format("v3")
    except ValueError:
        pass
    else:                      # pragma: no cover
        raise AssertionError("unknown format must be rejected")
