"""Core data model: interned tuples, heap, serialization, k-way merge.

Analog of the reference's L0 layer (SURVEY.md §1): mapreduce/utils.lua,
mapreduce/heap.lua, mapreduce/tuple.lua.
"""
