"""Engine tuning constants and status enums.

Parity with reference mapreduce/utils.lua:27-55 (constants) and
utils.lua:33-46 (STATUS / TASK_STATUS enums). Values keep the reference's
semantics; a few are retuned for a single-controller Python/JAX runtime
(polling a local job store is far cheaper than polling MongoDB).
"""

import enum

# --- tuning constants (reference utils.lua:27-55) -------------------------
# Reference constants with no role in this runtime (rw timeouts, hostname/ip
# defaults, the Lua scratch dir) are deliberately NOT carried over — only
# constants the engine actually consults live here.

DEFAULT_SLEEP = 0.1               # utils.lua:29 is 1s; local store polls cheaper

MAX_PENDING_INSERTS = 50_000      # utils.lua:50 — batched control-plane writes
MAX_JOB_RETRIES = 3               # utils.lua:51 — BROKEN→FAILED threshold
MAX_WORKER_RETRIES = 3            # utils.lua:52 — worker gives up after 3 errors
MAX_MAP_RESULT = 5_000            # utils.lua:53 — in-map combiner threshold
MAX_TASKFN_VALUE_SIZE = 16 * 1024 # utils.lua:54 — serialized task-value cap
MAX_IDLE_COUNT = 5                # utils.lua:55 — map-affinity steal threshold


class Status(enum.IntEnum):
    """Per-job status machine (reference utils.lua:33-40).

    WAITING → RUNNING → FINISHED → WRITTEN, with BROKEN (re-claimable) and
    FAILED (given up after MAX_JOB_RETRIES) side states.
    """

    WAITING = 0
    RUNNING = 1
    BROKEN = 2
    FINISHED = 3
    WRITTEN = 4
    FAILED = 5


class TaskStatus(str, enum.Enum):
    """Global task phase (reference utils.lua:42-46)."""

    WAIT = "WAIT"
    MAP = "MAP"
    REDUCE = "REDUCE"
    FINISHED = "FINISHED"
