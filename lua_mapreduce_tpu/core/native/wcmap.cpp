// Native wordcount map: tokenize + count + partition + serialize in one
// C++ pass.
//
// The reference's performance rests on native code for the data path
// (luamongo/mongo-cxx serialization + transport, SURVEY.md §2.4); this is
// the same idea applied to the map side of the Europarl-scale wordcount,
// where pure-Python tokenize/emit/serialize dominates the benchmark's map
// cluster time. A task module OPTS IN by declaring `mapfn.native_map`
// (see core/native_wcmap.py); the engine golden-diffs this path against
// the Python mapfn it replaces (tests/test_native_wcmap.py).
//
// Contract replicated exactly:
// - tokens split on the ASCII slice of Python str.split()'s whitespace
//   (space, \t-\r, \x1c-\x1f); files containing ANY non-ASCII byte
//   return rc=2 (fall back) because Python also splits on Unicode
//   whitespace (NBSP etc.) and byte-level tokenization could diverge
// - partition = (sum of the first `hash_prefix` BYTES of the word) % n
//   (examples partitionfn, reference partitionfn.lua:1-16 byte-sum role)
// - per partition, records sorted by key byte-order (== Python's sort for
//   single-rank str keys, serialize.sorted_keys fast path)
// - record lines byte-identical to serialize.dump_record:
//   ["<json-escaped word>",[<count>]]\n  (ensure_ascii=False escaping)
// - output written tmp + rename per partition (fs.lua:80-115 atomicity);
//   empty partitions produce no file
//
// C ABI: wc_map_file(input, out_tmp_paths, out_final_paths, n_reducers,
// hash_prefix) -> 0 ok, 1 I/O error, 2 fall back to Python.

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

bool is_space(unsigned char c) {
    // ASCII slice of Python str.split() whitespace: ' ', \t \n \v \f \r,
    // and the file/group/record/unit separators \x1c-\x1f
    return c == ' ' || (c >= '\t' && c <= '\r') ||
           (c >= 0x1c && c <= 0x1f);
}

bool all_ascii(const std::string& s) {
    for (unsigned char c : s)
        if (c >= 0x80) return false;
    return true;
}

// json.dumps(ensure_ascii=False) string escaping
void append_escaped(std::string& out, const std::string& w) {
    for (unsigned char c : w) {
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\b': out += "\\b"; break;
            case '\f': out += "\\f"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            default:
                if (c < 0x20) {
                    char buf[8];
                    snprintf(buf, sizeof buf, "\\u%04x", c);
                    out += buf;
                } else {
                    out += static_cast<char>(c);
                }
        }
    }
}

}  // namespace

extern "C" int wc_map_file(const char* input_path,
                           const char** out_tmp_paths,
                           const char** out_final_paths,
                           int n_reducers, int hash_prefix) {
    std::ifstream in(input_path, std::ios::binary);
    if (!in.is_open()) return 1;
    std::string data((std::istreambuf_iterator<char>(in)),
                     std::istreambuf_iterator<char>());
    if (!in.good() && !in.eof()) return 1;
    if (!all_ascii(data)) return 2;    // Unicode whitespace → Python path

    std::unordered_map<std::string, long long> counts;
    counts.reserve(1 << 16);
    size_t i = 0, n = data.size();
    while (i < n) {
        while (i < n && is_space(data[i])) ++i;
        size_t start = i;
        while (i < n && !is_space(data[i])) ++i;
        if (i > start)
            ++counts[data.substr(start, i - start)];
    }

    using Entry = std::pair<const std::string*, long long>;
    std::vector<std::vector<Entry>> parts(static_cast<size_t>(n_reducers));
    std::unordered_map<std::string, long long>::const_iterator it;
    for (it = counts.begin(); it != counts.end(); ++it) {
        const std::string& w = it->first;
        unsigned long h = 0;
        size_t lim = std::min(w.size(), static_cast<size_t>(hash_prefix));
        for (size_t j = 0; j < lim; ++j)
            h += static_cast<unsigned char>(w[j]);
        parts[h % n_reducers].emplace_back(&w, it->second);
    }

    for (int p = 0; p < n_reducers; ++p) {
        if (parts[p].empty()) continue;
        std::sort(parts[p].begin(), parts[p].end(),
                  [](const Entry& a, const Entry& b) {
                      return *a.first < *b.first;
                  });
        std::ofstream out(out_tmp_paths[p],
                          std::ios::binary | std::ios::trunc);
        if (!out.is_open()) return 1;
        std::string buf;
        buf.reserve(1 << 20);
        for (const Entry& e : parts[p]) {
            buf += "[\"";
            append_escaped(buf, *e.first);
            buf += "\",[";
            buf += std::to_string(e.second);
            buf += "]]\n";
            if (buf.size() > (1 << 20)) {
                out.write(buf.data(), static_cast<std::streamsize>(buf.size()));
                buf.clear();
            }
        }
        out.write(buf.data(), static_cast<std::streamsize>(buf.size()));
        out.flush();
        if (!out.good()) return 1;
        out.close();
        // fsync before rename — the Python builder's durability
        // discipline (store/sharedfs.py flush+fsync+replace): without it
        // a crash can durably publish a truncated run under its final
        // name and the reducer would silently merge it
        int fd = ::open(out_tmp_paths[p], O_RDONLY);
        if (fd < 0) return 1;
        if (::fsync(fd) != 0) { ::close(fd); return 1; }
        ::close(fd);
        if (std::rename(out_tmp_paths[p], out_final_paths[p]) != 0) return 1;
    }
    return 0;
}
