// Native k-way shuffle merge over sorted JSON-line run files.
//
// The reference's bulk-data path is native C++ (luamongo + mongo-cxx-driver
// GridFS chunk streaming, SURVEY.md §2.4); this is the new framework's
// native piece for the same role on the host shuffle: merge every mapper's
// sorted run for one partition into a single run whose equal-key value
// lists are concatenated — the merge_iterator contract (reference
// utils.lua:206-271) executed in one C++ pass instead of a Python heap
// loop, so the reduce phase streams one pre-merged file.
//
// Record format: one JSON array per line, [key, [v1, v2, ...]] (see
// core/serialize.py). Keys are compared with EXACTLY serialize.key_lt's
// total order: type rank (bool < number < string < array < null), then
// value — numbers int-exact when both sides are integral, strings by
// Unicode code point (== UTF-8 byte order after unescaping), arrays
// lexicographic then by length. Values are never parsed: their raw JSON
// spans are spliced into the output line untouched.
//
// C ABI (ctypes): smerge_files(inputs, n, output) -> 0 ok, 1 I/O error,
// 2 parse error. The output file is written directly; the Python caller
// owns tmp+rename atomicity (the fs.lua:80-115 discipline).
//
// smerge_fold_sum(inputs, n, output) additionally FOLDS each merged
// group: when a task's reducefn is declared ``native_reduce = "sum"``
// (associative+commutative integer sum — the wordcount/grad-count
// shape), the merge emits ["key",[<sum>]] directly, fusing the reduce
// into the merge pass. Any non-integer value or int64 overflow returns
// rc=2 so the Python reducefn (arbitrary precision) stays the truth.
//
// Input files may independently be v1 JSON-line text OR v2 "JSEG0001"
// framed binary segments (core/segment.py, DESIGN §17): the run cursor
// sniffs the 8-byte magic and decodes frames LAZILY — one frame
// (~256KB decoded, CRC-checked) at a time, raw or zlib-compressed
// (zlib only when built with -DLMR_HAVE_ZLIB -lz; a compressed frame
// without it returns rc=2 so the Python reader stays the truth).
// Output stays v1 text: readers sniff per file, so a text spill merged
// from binary segments is always valid.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <fstream>
#include <queue>
#include <string>
#include <vector>

#ifdef LMR_HAVE_ZLIB
#include <zlib.h>
#endif

namespace {

struct Key {
    int rank = 4;               // bool=0, num=1, str=2, arr=3, null=4
    bool bval = false;
    bool is_int = false;
    bool neg = false;           // sign of an integral key
    std::string digits;         // |value| digit string of an integral key
    double dval = 0.0;
    std::string sval;           // UTF-8 bytes, unescaped
    std::vector<Key> arr;
};

// ---- minimal JSON parsing (keys only; values stay raw) --------------------

void skip_ws(const char*& p) {
    while (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n') ++p;
}

bool parse_hex4(const char*& p, unsigned& out) {
    out = 0;
    for (int i = 0; i < 4; ++i) {
        char c = *p++;
        out <<= 4;
        if (c >= '0' && c <= '9') out |= (unsigned)(c - '0');
        else if (c >= 'a' && c <= 'f') out |= (unsigned)(c - 'a' + 10);
        else if (c >= 'A' && c <= 'F') out |= (unsigned)(c - 'A' + 10);
        else return false;
    }
    return true;
}

void append_utf8(std::string& s, unsigned cp) {
    if (cp < 0x80) {
        s += (char)cp;
    } else if (cp < 0x800) {
        s += (char)(0xC0 | (cp >> 6));
        s += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
        s += (char)(0xE0 | (cp >> 12));
        s += (char)(0x80 | ((cp >> 6) & 0x3F));
        s += (char)(0x80 | (cp & 0x3F));
    } else {
        s += (char)(0xF0 | (cp >> 18));
        s += (char)(0x80 | ((cp >> 12) & 0x3F));
        s += (char)(0x80 | ((cp >> 6) & 0x3F));
        s += (char)(0x80 | (cp & 0x3F));
    }
}

bool parse_string(const char*& p, std::string& out) {
    if (*p != '"') return false;
    ++p;
    while (*p && *p != '"') {
        if (*p == '\\') {
            ++p;
            switch (*p) {
                case '"': out += '"'; ++p; break;
                case '\\': out += '\\'; ++p; break;
                case '/': out += '/'; ++p; break;
                case 'b': out += '\b'; ++p; break;
                case 'f': out += '\f'; ++p; break;
                case 'n': out += '\n'; ++p; break;
                case 'r': out += '\r'; ++p; break;
                case 't': out += '\t'; ++p; break;
                case 'u': {
                    ++p;
                    unsigned cp;
                    if (!parse_hex4(p, cp)) return false;
                    if (cp >= 0xD800 && cp <= 0xDBFF && p[0] == '\\' &&
                        p[1] == 'u') {
                        p += 2;
                        unsigned lo;
                        if (!parse_hex4(p, lo)) return false;
                        cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                    }
                    append_utf8(out, cp);
                    break;
                }
                default: return false;
            }
        } else {
            out += *p++;
        }
    }
    if (*p != '"') return false;
    ++p;
    return true;
}

bool parse_key(const char*& p, Key& k) {
    skip_ws(p);
    if (*p == 't') {
        if (strncmp(p, "true", 4)) return false;
        p += 4; k.rank = 0; k.bval = true; return true;
    }
    if (*p == 'f') {
        if (strncmp(p, "false", 5)) return false;
        p += 5; k.rank = 0; k.bval = false; return true;
    }
    if (*p == 'n') {
        if (strncmp(p, "null", 4)) return false;
        p += 4; k.rank = 4; return true;
    }
    if (*p == '"') {
        k.rank = 2;
        return parse_string(p, k.sval);
    }
    if (*p == '[') {
        ++p;
        k.rank = 3;
        skip_ws(p);
        if (*p == ']') { ++p; return true; }
        while (true) {
            k.arr.emplace_back();
            if (!parse_key(p, k.arr.back())) return false;
            skip_ws(p);
            if (*p == ',') { ++p; continue; }
            if (*p == ']') { ++p; return true; }
            return false;
        }
    }
    if (*p == '-' || (*p >= '0' && *p <= '9')) {
        const char* start = p;
        bool integral = true;
        if (*p == '-') ++p;
        while (*p >= '0' && *p <= '9') ++p;
        if (*p == '.' || *p == 'e' || *p == 'E') {
            integral = false;
            if (*p == '.') { ++p; while (*p >= '0' && *p <= '9') ++p; }
            if (*p == 'e' || *p == 'E') {
                ++p;
                if (*p == '+' || *p == '-') ++p;
                while (*p >= '0' && *p <= '9') ++p;
            }
        }
        std::string num(start, (size_t)(p - start));
        k.rank = 1;
        k.dval = strtod(num.c_str(), nullptr);
        if (integral) {
            // exact arbitrary-precision compare via the digit string —
            // Python ints never round through double (two 2**64-scale
            // keys differing by 1 must NOT merge)
            k.is_int = true;
            k.neg = num[0] == '-';
            k.digits = k.neg ? num.substr(1) : num;
            if (k.digits == "0") k.neg = false;         // -0 == 0
        }
        return true;
    }
    return false;
}

// Exact compare of an arbitrary-precision integer key (neg, |digits|)
// against a double key parsed from a non-integral literal. Python
// compares int-vs-float exactly, so rounding the int through double
// (lossy past 2^53) would silently merge keys Python keeps distinct.
int int_vs_double_cmp(bool neg, const std::string& digits, double d) {
    if (d == HUGE_VAL) return -1;               // any int < +inf
    if (d == -HUGE_VAL) return 1;               // any int > -inf
    static const char* TWO53 = "9007199254740992";  // 2^53, 16 digits
    if (digits.size() < 16 ||
        (digits.size() == 16 && digits.compare(TWO53) <= 0)) {
        // |int| <= 2^53: double holds it exactly
        double iv = strtod(digits.c_str(), nullptr);
        if (neg) iv = -iv;
        return iv < d ? -1 : (iv > d ? 1 : 0);
    }
    bool dneg = std::signbit(d);
    double ad = dneg ? -d : d;
    if (ad < 9007199254740992.0)
        // |d| < 2^53 < |int| → the int's magnitude wins; sign decides
        return neg ? -1 : 1;
    // |d| >= 2^53: d is integral-valued; %.0f prints its exact decimal
    // (binary→decimal of an integer-valued double is exact, <= 309 digits)
    char buf[352];
    snprintf(buf, sizeof buf, "%.0f", ad);
    if (neg != dneg) return neg ? -1 : 1;
    size_t blen = strlen(buf);
    int mag;
    if (digits.size() != blen) {
        mag = digits.size() < blen ? -1 : 1;
    } else {
        int c = digits.compare(buf);
        mag = c < 0 ? -1 : (c > 0 ? 1 : 0);
    }
    return neg ? -mag : mag;
}

// key_lt: -1 / 0 / +1 matching serialize.key_lt's total order
int key_cmp(const Key& a, const Key& b) {
    if (a.rank != b.rank) return a.rank < b.rank ? -1 : 1;
    switch (a.rank) {
        case 0:
            if (a.bval == b.bval) return 0;
            return a.bval ? 1 : -1;         // false < true
        case 1:
            if (a.is_int && b.is_int) {
                if (a.neg != b.neg) return a.neg ? -1 : 1;
                int mag;
                if (a.digits.size() != b.digits.size())
                    mag = a.digits.size() < b.digits.size() ? -1 : 1;
                else {
                    int c = a.digits.compare(b.digits);
                    mag = c < 0 ? -1 : (c > 0 ? 1 : 0);
                }
                return a.neg ? -mag : mag;
            }
            if (a.is_int) return int_vs_double_cmp(a.neg, a.digits, b.dval);
            if (b.is_int) return -int_vs_double_cmp(b.neg, b.digits, a.dval);
            return a.dval < b.dval ? -1 : (a.dval > b.dval ? 1 : 0);
        case 2: {
            int c = a.sval.compare(b.sval);  // UTF-8 bytes == code points
            return c < 0 ? -1 : (c > 0 ? 1 : 0);
        }
        case 3: {
            size_t n = a.arr.size() < b.arr.size() ? a.arr.size()
                                                   : b.arr.size();
            for (size_t i = 0; i < n; ++i) {
                int c = key_cmp(a.arr[i], b.arr[i]);
                if (c) return c;
            }
            if (a.arr.size() != b.arr.size())
                return a.arr.size() < b.arr.size() ? -1 : 1;
            return 0;
        }
        default:
            return 0;                       // null == null
    }
}

// find the end of a balanced JSON value starting at p (string-aware);
// returns nullptr on malformed input
const char* span_end(const char* p) {
    skip_ws(p);
    if (*p == '"') {
        ++p;
        while (*p && *p != '"') {
            if (*p == '\\' && p[1]) ++p;
            ++p;
        }
        return *p == '"' ? p + 1 : nullptr;
    }
    if (*p == '[' || *p == '{') {
        char open = *p, close = (*p == '[') ? ']' : '}';
        int depth = 0;
        while (*p) {
            if (*p == '"') {
                ++p;
                while (*p && *p != '"') {
                    if (*p == '\\' && p[1]) ++p;
                    ++p;
                }
                if (!*p) return nullptr;
            } else if (*p == open) {
                ++depth;
            } else if (*p == close) {
                if (--depth == 0) return p + 1;
            }
            ++p;
        }
        return nullptr;
    }
    while (*p && *p != ',' && *p != ']' && *p != '}' && *p != ' ' &&
           *p != '\t' && *p != '\r' && *p != '\n')
        ++p;
    return p;
}

// ---- JSEG0001 segment decoding --------------------------------------------

const char SEG_MAGIC[8] = {'J', 'S', 'E', 'G', '0', '0', '0', '1'};

// CRC-32 (zlib polynomial) over the DECODED frame payload — implemented
// locally so raw-codec segments verify even in a zlib-less build
uint32_t crc32_ieee(const unsigned char* p, size_t n) {
    static uint32_t table[256];
    static bool init = false;
    if (!init) {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int k = 0; k < 8; ++k)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            table[i] = c;
        }
        init = true;
    }
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < n; ++i)
        c = table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

uint32_t le32(const unsigned char* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

uint64_t le64(const unsigned char* p) {
    return (uint64_t)le32(p) | ((uint64_t)le32(p + 4) << 32);
}

// ---- run-file cursor ------------------------------------------------------

struct Run {
    std::ifstream f;
    std::string line;
    Key key;
    std::string key_raw;        // raw JSON of the key (spliced to output)
    std::string vals_raw;       // raw contents INSIDE the values [ ... ]
    bool ok = false;

    // segment state (v2 inputs); text inputs keep seg=false
    bool seg = false;
    uint64_t frame_off = 0;     // next frame header offset
    uint64_t frames_end = 0;    // first byte past the data region
    std::string dbuf;           // decoded-but-unconsumed payload bytes
    size_t dpos = 0;

    // Sniff the open file: position it for text getline, or arm the
    // frame decoder. Returns 0 ok, 1 open failure, 2 malformed segment.
    int arm() {
        if (!f.is_open()) return 1;
        char head[8];
        f.read(head, 8);
        if (f.gcount() == 8 && memcmp(head, SEG_MAGIC, 8) == 0) {
            f.clear();
            f.seekg(0, std::ios::end);
            uint64_t size = (uint64_t)f.tellg();
            if (size < 32) return 2;             // magic + 24-byte trailer
            unsigned char tr[24];
            f.seekg((std::streamoff)(size - 24));
            f.read(reinterpret_cast<char*>(tr), 24);
            if (f.gcount() != 24 || memcmp(tr + 16, SEG_MAGIC, 8) != 0)
                return 2;
            frames_end = le64(tr);
            if (frames_end < 8 || frames_end > size) return 2;
            seg = true;
            frame_off = 8;
            f.clear();
            f.seekg(8);
            return 0;
        }
        f.clear();
        f.seekg(0);
        return 0;
    }

    // Decode the next frame into dbuf. 0 ok, 1 no more frames, 2 error.
    int load_frame() {
        if (frame_off >= frames_end) return 1;
        unsigned char hdr[13];
        f.seekg((std::streamoff)frame_off);
        f.read(reinterpret_cast<char*>(hdr), 13);
        if (f.gcount() != 13) return 2;
        uint32_t enc = le32(hdr), dec = le32(hdr + 4);
        unsigned codec = hdr[8];
        uint32_t crc = le32(hdr + 9);
        if (frame_off + 13 + enc > frames_end) return 2;
        std::string data(enc, '\0');
        f.read(&data[0], (std::streamsize)enc);
        if ((uint32_t)f.gcount() != enc) return 2;
        std::string payload;
        if (codec == 0) {
            payload.swap(data);
        } else if (codec == 1) {
#ifdef LMR_HAVE_ZLIB
            payload.resize(dec);
            uLongf dlen = dec;
            if (uncompress(reinterpret_cast<Bytef*>(&payload[0]), &dlen,
                           reinterpret_cast<const Bytef*>(data.data()),
                           enc) != Z_OK || dlen != dec)
                return 2;
#else
            return 2;           // compressed frame, zlib-less build
#endif
        } else {
            return 2;           // lz4 (and anything newer): Python owns it
        }
        if (payload.size() != dec ||
            crc32_ieee(reinterpret_cast<const unsigned char*>(
                           payload.data()), payload.size()) != crc)
            return 2;
        // keep any half-consumed tail (payloads end in '\n', so this is
        // defensive only) and swap the decoded frame in
        dbuf.erase(0, dpos);
        dbuf += payload;
        dpos = 0;
        frame_off += 13 + (uint64_t)enc;
        return 0;
    }

    // 0 = line loaded, 1 = eof, 2 = error — the getline analog that
    // serves both formats (frames decode lazily, one at a time)
    int next_line() {
        if (!seg)
            return std::getline(f, line) ? 0 : 1;
        while (true) {
            size_t nl = dbuf.find('\n', dpos);
            if (nl != std::string::npos) {
                line.assign(dbuf, dpos, nl - dpos);
                dpos = nl + 1;
                return 0;
            }
            int st = load_frame();
            if (st == 2) return 2;
            if (st == 1) {
                if (dpos < dbuf.size()) {        // unterminated tail
                    line.assign(dbuf, dpos, std::string::npos);
                    dpos = dbuf.size();
                    return 0;
                }
                return 1;
            }
        }
    }

    // 0 = record loaded, 1 = eof, 2 = parse error
    int advance() {
        int st;
        while ((st = next_line()) == 0) {
            size_t b = line.find_first_not_of(" \t\r\n");
            if (b == std::string::npos) continue;       // skip blank lines
            const char* p = line.c_str();
            skip_ws(p);
            if (*p != '[') return 2;
            ++p;
            skip_ws(p);
            const char* kstart = p;
            key = Key();
            if (!parse_key(p, key)) return 2;
            key_raw.assign(kstart, (size_t)(p - kstart));
            skip_ws(p);
            if (*p != ',') return 2;
            ++p;
            skip_ws(p);
            if (*p != '[') return 2;
            const char* vend = span_end(p);
            if (!vend) return 2;
            vals_raw.assign(p + 1, (size_t)(vend - p - 2));  // inside [ ]
            ok = true;
            return 0;
        }
        ok = false;
        return st;              // 1 eof, 2 frame/decode error
    }
};

struct HeapCmp {
    const std::vector<Run*>* runs;
    bool operator()(int a, int b) const {
        // std::priority_queue is a max-heap; invert for min-key order
        return key_cmp((*runs)[a]->key, (*runs)[b]->key) > 0;
    }
};

// Accumulate every integer token inside a raw values span ("1,-2,3")
// into total. Returns false (→ rc=2 fallback) on any non-integer token
// or int64 overflow — Python's arbitrary-precision sum owns those.
bool fold_span_sum(const std::string& span, long long& total) {
    const char* p = span.c_str();
    while (true) {
        skip_ws(p);
        if (!*p) return true;
        bool neg = false;
        if (*p == '-') { neg = true; ++p; }
        if (*p < '0' || *p > '9') return false;
        long long v = 0;
        while (*p >= '0' && *p <= '9') {
            if (__builtin_mul_overflow(v, 10LL, &v) ||
                __builtin_add_overflow(v, (long long)(*p - '0'), &v))
                return false;
            ++p;
        }
        if (*p == '.' || *p == 'e' || *p == 'E') return false;  // float
        if (neg) v = -v;
        if (__builtin_add_overflow(total, v, &total)) return false;
        skip_ws(p);
        if (*p == ',') { ++p; continue; }
        if (!*p) return true;
        return false;                   // strings/arrays/objects
    }
}

int smerge_core(const char** inputs, int n_inputs, const char* output,
                int fold_sum);

}  // namespace

extern "C" int smerge_files(const char** inputs, int n_inputs,
                            const char* output) {
    return smerge_core(inputs, n_inputs, output, 0);
}

extern "C" int smerge_fold_sum(const char** inputs, int n_inputs,
                               const char* output) {
    return smerge_core(inputs, n_inputs, output, 1);
}

namespace {

int smerge_core(const char** inputs, int n_inputs, const char* output,
                int fold_sum) {
    std::vector<Run*> runs;
    runs.reserve((size_t)n_inputs);
    for (int i = 0; i < n_inputs; ++i) {
        Run* r = new Run();
        r->f.open(inputs[i], std::ios::binary);   // segments are binary;
        runs.push_back(r);                        // getline is \n-framed
    }
    int rc = 0;
    {
        std::priority_queue<int, std::vector<int>, HeapCmp> heap(
            HeapCmp{&runs});
        for (int i = 0; i < n_inputs && rc == 0; ++i) {
            rc = runs[(size_t)i]->arm();          // sniff v1 text vs v2 seg
            if (rc) break;
            int st = runs[(size_t)i]->advance();
            if (st == 0) heap.push(i);
            else if (st == 2) rc = 2;
        }
        std::ofstream out;
        if (rc == 0) {
            out.open(output, std::ios::trunc);
            if (!out.is_open()) rc = 1;
        }
        while (rc == 0 && !heap.empty()) {
            int first = heap.top();
            heap.pop();
            std::vector<int> drained{first};
            while (!heap.empty() &&
                   key_cmp(runs[(size_t)heap.top()]->key,
                           runs[(size_t)first]->key) == 0) {
                drained.push_back(heap.top());
                heap.pop();
            }
            // concatenate in run-file order (deterministic reduce
            // inputs, matching core/merge.py's contract)
            std::sort(drained.begin(), drained.end());
            if (fold_sum) {
                long long total = 0;
                for (int j : drained) {
                    if (!fold_span_sum(runs[(size_t)j]->vals_raw, total)) {
                        rc = 2;
                        break;
                    }
                }
                if (rc) break;
                out << '[' << runs[(size_t)first]->key_raw << ",["
                    << total << "]]\n";
            } else {
                std::string merged;
                for (int j : drained) {
                    if (runs[(size_t)j]->vals_raw.empty()) continue;
                    if (!merged.empty()) merged += ',';
                    merged += runs[(size_t)j]->vals_raw;
                }
                out << '[' << runs[(size_t)first]->key_raw << ",["
                    << merged << "]]\n";
            }
            for (int j : drained) {
                int st = runs[(size_t)j]->advance();
                if (st == 0) heap.push(j);
                else if (st == 2) { rc = 2; break; }
            }
        }
        if (rc == 0) {
            out.flush();
            if (!out.good()) rc = 1;
        }
    }
    for (Run* r : runs) delete r;
    return rc;
}

}  // namespace
