"""Streaming k-way merge of sorted run files.

Analog of reference mapreduce/utils.lua:206-271 ``merge_iterator``: given a
storage backend and a list of sorted run files (one per mapper, all for the
same partition), heap-merge them and yield ``(key, values)`` with the value
lists of equal keys concatenated across files — without materializing more
than one record per file in memory (the reference streams GridFS chunks the
same way, utils.lua:133-200).
"""

from __future__ import annotations

from typing import Any, Iterator, List, Sequence, Tuple

from lua_mapreduce_tpu.core.heap import Heap
from lua_mapreduce_tpu.core.serialize import key_lt


def merge_iterator(store, filenames: Sequence[str]) -> Iterator[Tuple[Any, List[Any]]]:
    """Yield merged (key, values) pairs across sorted run files.

    ``store`` is any object with ``lines(name) -> Iterator[str]`` (the fs
    layer, SURVEY.md §1 L1). Mirrors utils.lua:206-271: ``take_next`` parses
    one record per file (218-230); ``merge_min_keys`` concatenates the value
    lists sharing the minimum key (232-247).

    Run files are read through ``segment.record_stream``, so every input
    may independently be v1 text or a v2 framed segment (DESIGN §17) —
    the merge is the mixed-fleet compatibility point. When EVERY input is
    a segment whose footer promises all-str keys, the merge switches to
    native-comparison heapq (:func:`_merge_str_keyed`) — within the str
    rank, ``key_lt`` IS plain ``<``, so the order (and the equal-key
    run-order concatenation) is byte-identical, at C compare speed
    instead of a Python lambda per heap hop. v1 text cannot make that
    promise without a full scan, which is exactly why the format carries
    it.
    """
    from lua_mapreduce_tpu.core.segment import _text_records, open_segment

    iters = []
    all_str = bool(filenames)
    for name in filenames:
        rdr = open_segment(store, name)
        if rdr is None:
            # already sniffed: go straight to the text reader (a second
            # record_stream sniff would re-read shim-backed stores)
            all_str = False
            iters.append(_text_records(store, name))
        else:
            all_str = all_str and rdr.str_keys
            iters.append(rdr.iter_records())
    if all_str:
        return _merge_str_keyed(iters)
    return _merge_generic(iters)


def _merge_generic(iters: List[Iterator[Tuple[Any, List[Any]]]]
                   ) -> Iterator[Tuple[Any, List[Any]]]:
    """The heterogeneous-key merge: a key_lt-ordered heap (mixed type
    ranks, tuples, bignums — the full canonical order)."""
    heap: Heap = Heap(lt=lambda a, b: key_lt(a[0], b[0]))
    for idx, it in enumerate(iters):
        rec = next(it, None)
        if rec is not None:
            heap.push((rec[0], rec[1], idx))

    while not heap.empty():
        key, values, idx = heap.pop()
        # drain every file whose head shares this key; concatenate in
        # RUN-FILE ORDER (not heap pop order) so reduce inputs are
        # deterministic and identical to the native C++ merge's output
        drained = [(idx, values)]
        while not heap.empty() and not key_lt(key, heap.top()[0]):
            _, more, jdx = heap.pop()
            drained.append((jdx, more))
        merged: List[Any] = []
        for jdx, more in sorted(drained):
            merged.extend(more)
            nxt = next(iters[jdx], None)
            if nxt is not None:
                heap.push((nxt[0], nxt[1], jdx))
        yield key, merged


def _merge_str_keyed(iters: List[Iterator[Tuple[Any, List[Any]]]]
                     ) -> Iterator[Tuple[Any, List[Any]]]:
    """All-str-key merge on ``heapq`` with native tuple comparison.

    ``(key, idx)`` ordering reproduces the generic path exactly: within
    the str rank key_lt is ``<``, and equal keys pop in ascending run
    index — the same run-file-order concatenation ``sorted(drained)``
    produces. ``idx`` is unique per heap entry, so the values list is
    never compared.
    """
    import heapq

    heap: List[Any] = []
    for idx, it in enumerate(iters):
        rec = next(it, None)
        if rec is not None:
            heap.append((rec[0], idx, rec[1]))
    heapq.heapify(heap)
    push, pop = heapq.heappush, heapq.heappop
    while heap:
        key, idx, merged = pop(heap)
        drained = [idx]
        # drain CURRENT heads sharing the key (exactly the generic
        # drain set), then refill — a same-key successor within one run
        # must surface as its own group, as in the generic path. Equal
        # keys pop in ascending run index, so extending in pop order IS
        # the run-file-order concatenation; the values list is freshly
        # parsed per record, so in-place extend aliases nothing.
        while heap and heap[0][0] == key:
            _, jdx, more = pop(heap)
            merged.extend(more)
            drained.append(jdx)
        for jdx in drained:
            nxt = next(iters[jdx], None)
            if nxt is not None:
                push(heap, (nxt[0], jdx, nxt[1]))
        yield key, merged


def utest() -> None:
    """Self-test: merge three sorted runs with overlapping keys."""
    from lua_mapreduce_tpu.core.serialize import dump_record

    class _MemStore:
        def __init__(self, files):
            self.files = files

        def lines(self, name):
            return iter(self.files[name])

    store = _MemStore({
        "a": [dump_record("apple", [1]), dump_record("cat", [1, 1])],
        "b": [dump_record("apple", [2]), dump_record("bee", [5])],
        "c": [dump_record("cat", [3])],
    })
    out = list(merge_iterator(store, ["a", "b", "c"]))
    assert out == [("apple", [1, 2]), ("bee", [5]), ("cat", [1, 1, 3])], out
