"""Streaming k-way merge of sorted run files.

Analog of reference mapreduce/utils.lua:206-271 ``merge_iterator``: given a
storage backend and a list of sorted run files (one per mapper, all for the
same partition), heap-merge them and yield ``(key, values)`` with the value
lists of equal keys concatenated across files — without materializing more
than one record per file in memory (the reference streams GridFS chunks the
same way, utils.lua:133-200).
"""

from __future__ import annotations

from typing import Any, Iterator, List, Sequence, Tuple

from lua_mapreduce_tpu.core.heap import Heap
from lua_mapreduce_tpu.core.serialize import key_lt, load_record


def merge_iterator(store, filenames: Sequence[str]) -> Iterator[Tuple[Any, List[Any]]]:
    """Yield merged (key, values) pairs across sorted run files.

    ``store`` is any object with ``lines(name) -> Iterator[str]`` (the fs
    layer, SURVEY.md §1 L1). Mirrors utils.lua:206-271: ``take_next`` parses
    one record per file (218-230); ``merge_min_keys`` concatenates the value
    lists sharing the minimum key (232-247).
    """
    heap: Heap = Heap(lt=lambda a, b: key_lt(a[0], b[0]))
    iters = []
    for idx, name in enumerate(filenames):
        it = store.lines(name)
        iters.append(it)
        rec = _take_next(it)
        if rec is not None:
            heap.push((rec[0], rec[1], idx))

    while not heap.empty():
        key, values, idx = heap.pop()
        # drain every file whose head shares this key; concatenate in
        # RUN-FILE ORDER (not heap pop order) so reduce inputs are
        # deterministic and identical to the native C++ merge's output
        drained = [(idx, values)]
        while not heap.empty() and not key_lt(key, heap.top()[0]):
            _, more, jdx = heap.pop()
            drained.append((jdx, more))
        merged: List[Any] = []
        for jdx, more in sorted(drained):
            merged.extend(more)
            nxt = _take_next(iters[jdx])
            if nxt is not None:
                heap.push((nxt[0], nxt[1], jdx))
        yield key, merged


def _take_next(it) -> Tuple[Any, List[Any]] | None:
    """Parse the next record line from a file iterator (utils.lua:218-230)."""
    for line in it:
        line = line.strip()
        if line:
            return load_record(line)
    return None


def utest() -> None:
    """Self-test: merge three sorted runs with overlapping keys."""
    from lua_mapreduce_tpu.core.serialize import dump_record

    class _MemStore:
        def __init__(self, files):
            self.files = files

        def lines(self, name):
            return iter(self.files[name])

    store = _MemStore({
        "a": [dump_record("apple", [1]), dump_record("cat", [1, 1])],
        "b": [dump_record("apple", [2]), dump_record("bee", [5])],
        "c": [dump_record("cat", [3])],
    })
    out = list(merge_iterator(store, ["a", "b", "c"]))
    assert out == [("apple", [1, 2]), ("bee", [5]), ("cat", [1, 1, 3])], out
