"""ctypes facade over the native C++ wordcount map (wcmap.cpp).

A task module opts in by tagging its mapfn:

    def mapfn(key, value, emit): ...              # the Python truth
    mapfn.native_map = {"kind": "wordcount_file",
                        "num_reducers": 15, "hash_prefix": 4}

The declaration is a PROMISE that the Python mapfn+partitionfn compute
exactly: whitespace-split word counts of the file at ``value``,
partitioned by byte-sum of the word's first ``hash_prefix`` bytes mod
``num_reducers`` (the reference examples' partition scheme,
partitionfn.lua:1-16). The engine (engine/job.py) then routes the map
job through one C++ pass — tokenize, count, partition, sort, serialize,
atomic per-partition publish — when the store is a local-path backend,
and falls back to the Python path otherwise (same discipline as
core/native_merge.py; golden-diffed in tests/test_native_wcmap.py).
"""

from __future__ import annotations

import ctypes
import os
import uuid
from typing import Optional

from lua_mapreduce_tpu.core.native_build import load_native

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "wcmap.cpp")
_SO = os.path.join(_NATIVE_DIR, "libwcmap.so")


def _load() -> Optional[ctypes.CDLL]:
    lib = load_native(_SRC, _SO)
    if lib is not None and not hasattr(lib.wc_map_file, "_configured"):
        lib.wc_map_file.restype = ctypes.c_int
        lib.wc_map_file.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int, ctypes.c_int]
        lib.wc_map_file._configured = True
    return lib


def native_available() -> bool:
    return _load() is not None


def run_native_map(store, spec_native: dict, input_path: str,
                   result_ns: str, job_id: str) -> bool:
    """Execute one wordcount map job natively. Returns False when the
    native path can't serve it (caller runs the Python mapfn instead):
    non-local store, missing input, no toolchain, or rc=2 (non-ASCII
    input whose tokenization Python must own)."""
    from lua_mapreduce_tpu.engine.job import map_output_name

    local_path = getattr(store, "local_path", None)
    base_dir = getattr(store, "path", None)
    if local_path is None or base_dir is None or not native_available():
        return False
    if not os.path.isfile(input_path):
        return False
    n_red = int(spec_native["num_reducers"])
    prefix = int(spec_native.get("hash_prefix", 4))
    if n_red <= 0 or prefix < 0:
        # C++ would SIGFPE on % 0 — let the Python path raise cleanly
        return False

    # Publish discipline mirrors the Python path exactly: UNIQUE tmp
    # names (a stale-requeued twin of this job running concurrently must
    # not interleave writes with ours) and replace-only rename — a
    # published run file is never deleted, only atomically superseded.
    attempt = uuid.uuid4().hex[:8]
    finals, tmps = [], []
    for p in range(n_red):
        name = map_output_name(result_ns, p, job_id)
        finals.append(local_path(name))
        tmps.append(os.path.join(
            base_dir, f".tmp.wcmap.{job_id}.{os.getpid()}.{attempt}.{p}"))

    lib = _load()
    tmp_arr = (ctypes.c_char_p * n_red)(*[t.encode() for t in tmps])
    fin_arr = (ctypes.c_char_p * n_red)(*[f.encode() for f in finals])
    rc = lib.wc_map_file(input_path.encode(), tmp_arr, fin_arr,
                         n_red, prefix)
    for t in tmps:                      # rc!=0 can leave tmp files behind
        try:
            os.remove(t)
        except FileNotFoundError:
            pass
    if rc == 1:
        raise OSError(f"native wordcount map I/O error on {input_path}")
    return rc == 0
