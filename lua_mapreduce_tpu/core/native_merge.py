"""ctypes facade over the native C++ shuffle merge.

Build/load discipline is the shared one (core/native_build.py): the
native path is an OPTIMIZATION of core/merge.py's merge_iterator, never a
requirement — both produce identical merged groups (tests golden-diff
them), so a box without g++ just runs the Python heap merge, and ANY
native failure (including records only the Python parser understands)
falls back the same way.

The native merge applies when every run file is a local POSIX path (the
SharedStore backend exposes ``local_path``); other backends keep the
streaming Python path, exactly how the reference routes gridfs/sshfs
through different iterators (fs.lua:185-208). Tradeoff: the C++ pass
materializes the merged partition as one file (written next to the run
files, same real filesystem — NOT the system tmpfs) before the reduce
fold starts, buying a single-pass merge at the cost of the Python path's
record-at-a-time streaming; partitions too big for that are what the
fallback is for.
"""

from __future__ import annotations

import ctypes
import os
import tempfile
from typing import Iterator, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.core.native_build import load_native
from lua_mapreduce_tpu.core.serialize import load_record

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")
_SRC = os.path.join(_NATIVE_DIR, "shufflemerge.cpp")
_SO = os.path.join(_NATIVE_DIR, "libshufflemerge.so")


def _load() -> Optional[ctypes.CDLL]:
    # zlib is an optional capability: with it the C++ pass decodes
    # compressed v2 segment frames; without it those runs fall back to
    # the Python reader (rc=2), raw-codec segments still decode natively
    lib = load_native(_SRC, _SO, extra_flags=("-DLMR_HAVE_ZLIB", "-lz"))
    if lib is not None and not hasattr(lib.smerge_files, "_configured"):
        for fn in (lib.smerge_files, lib.smerge_fold_sum):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                           ctypes.c_char_p]
        lib.smerge_files._configured = True
    return lib


def native_available() -> bool:
    return _load() is not None


def merge_paths(paths: Sequence[str], out_path: str) -> None:
    """Merge sorted run files at local ``paths`` into ``out_path``
    (equal-key value lists concatenated in run order). Raises on
    failure."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native shuffle merge unavailable")
    arr = (ctypes.c_char_p * len(paths))(
        *[p.encode() for p in paths])
    rc = lib.smerge_files(arr, len(paths), out_path.encode())
    if rc == 1:
        raise OSError(f"native merge I/O error over {list(paths)}")
    if rc:
        raise ValueError(f"native merge parse error over {list(paths)}")


def _local_run_paths(store, filenames: Sequence[str]
                     ) -> Optional[List[str]]:
    """Shared gate for both native entry points: every run must be a
    local POSIX path and the toolchain must have built. None = caller
    falls back to the Python path."""
    local_path = getattr(store, "local_path", None)
    if local_path is None or not native_available():
        return None
    paths = []
    for name in filenames:
        p = local_path(name)
        if not os.path.exists(p):
            return None
        paths.append(p)
    return paths


def native_merge_reduce_sum(store, filenames: Sequence[str],
                            result_store, result_file: str) -> bool:
    """Fused merge+reduce: fold every merged group with an int64 sum IN
    the C++ pass and publish the partition result file directly — the
    whole reduce job in one native pass, for reducers declared
    ``native_reduce = "sum"`` (run_reduce_job gates on the ACI flags
    too). Returns False when the native path can't serve it (non-local
    stores, toolchain, non-integer values, int64 overflow) — the caller
    falls back to the Python merge+fold, which is the semantic truth.
    """
    dst_path = getattr(result_store, "local_path", None)
    dst_dir = getattr(result_store, "path", None)
    paths = _local_run_paths(store, filenames)
    if paths is None or dst_path is None or dst_dir is None:
        return False

    lib = _load()
    fd, tmp = tempfile.mkstemp(prefix=".tmp.redsum.", suffix=".jsonl",
                               dir=dst_dir)
    os.close(fd)
    arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
    rc = lib.smerge_fold_sum(arr, len(paths), tmp.encode())
    if rc:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        # rc=1 I/O (e.g. a run deleted by a duplicate job between the
        # exists() precheck and the C++ open) and rc=2 shape fallback
        # both route to the Python fold — ANY native failure falls back,
        # the module's contract
        return False
    # builder durability discipline: fsync before the atomic publish
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst_path(result_file))
    return True


def native_premerge(store, filenames: Sequence[str], out_name: str) -> bool:
    """Whole pre-merge job in one native pass: merge sorted runs at local
    paths and publish the consolidated spill run atomically under
    ``out_name`` — no Python parse/re-dump round trip. Returns False when
    the native path can't serve it (non-local store, no toolchain, parser
    rejects a record); the caller falls back to the streaming Python
    merge, which is the semantic truth."""
    dst_path = getattr(store, "local_path", None)
    dst_dir = getattr(store, "path", None)
    paths = _local_run_paths(store, filenames)
    if paths is None or dst_path is None or dst_dir is None:
        return False
    fd, tmp = tempfile.mkstemp(prefix=".tmp.spill.", suffix=".jsonl",
                               dir=dst_dir)
    os.close(fd)
    try:
        merge_paths(paths, tmp)
    except (OSError, ValueError, RuntimeError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    # builder durability discipline: fsync before the atomic publish
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst_path(out_name))
    return True


def native_merge_records(store, filenames: Sequence[str]
                         ) -> Optional[Iterator[Tuple[object, List[object]]]]:
    """merge_iterator-compatible stream via the native pass, or ``None``
    when the native path can't serve these runs — wrong store type, no
    toolchain, or records the C++ parser rejects (e.g. NaN keys, which
    json.dumps emits as bare ``NaN``). The merge runs EAGERLY here so
    every failure mode surfaces as None (caller falls back) rather than
    as an exception mid-reduce."""
    paths = _local_run_paths(store, filenames)
    if paths is None:
        return None

    out_dir = getattr(store, "path", None) or tempfile.gettempdir()
    fd, out = tempfile.mkstemp(prefix=".tmp.merge.", suffix=".jsonl",
                               dir=out_dir)
    os.close(fd)
    try:
        merge_paths(paths, out)
    except (OSError, ValueError, RuntimeError):
        try:
            os.unlink(out)
        except OSError:
            pass
        return None

    # Unlink eagerly: POSIX keeps the open fd readable, and the
    # partition-sized temp must not leak into the spill dir if the reduce
    # fold raises (or the worker dies) before exhausting the stream.
    f = open(out)
    try:
        os.unlink(out)
    except OSError:
        pass

    def stream() -> Iterator[Tuple[object, List[object]]]:
        with f:
            for line in f:
                line = line.strip()
                if line:
                    yield load_record(line)

    return stream()
