"""Interned (hash-consed) immutable tuples.

Analog of reference mapreduce/tuple.lua: immutable tuples, interned so that
structurally-equal tuples are the *same object* (pointer equality), usable as
emit keys/values. The reference builds this from scratch in Lua (weak bucket
table of 2^18 entries, Jenkins one-at-a-time hash, proxy metatables —
tuple.lua:77-81, 121-140, 167-215). In Python, ``tuple`` is already immutable
and hashable, so the new capability here is *interning* plus recursive
construction (tuple.lua:230-247) and stats introspection (tuple.lua:332-343).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable


class Tuple(tuple):
    """An interned immutable tuple. Use :func:`intern` to construct."""

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "Tuple" + super().__repr__()


# CPython tuples (and their subclasses) cannot carry weak references, so the
# reference's weak-bucket design (tuple.lua:77-81) maps to a *bounded* strong
# table: up to 2^18 entries (the reference's bucket count); on overflow the
# table is dropped and re-fills. Eviction only costs identity (a later intern
# of an equal tuple makes a fresh object) — equality and hashing are value
# based either way.
_MAX_ENTRIES = 2 ** 18
_lock = threading.Lock()
_table: Dict[tuple, Tuple] = {}


def intern(value: Iterable[Any]) -> Tuple:
    """Return the canonical interned Tuple for ``value``.

    Nested lists/tuples are interned recursively (reference tuple.lua:230-247).
    Structurally equal inputs return the identical object::

        intern([1, [2, 3]]) is intern((1, (2, 3)))  # True
    """
    items = tuple(
        intern(v) if isinstance(v, (list, tuple)) else v for v in value
    )
    with _lock:
        cached = _table.get(items)
        if cached is not None:
            return cached
        if len(_table) >= _MAX_ENTRIES:
            _table.clear()
        t = Tuple(items)
        _table[items] = t
        return t


def stats() -> dict:
    """Live intern-table statistics (reference tuple.lua:332-343)."""
    with _lock:
        return {"size": len(_table)}


def utest() -> None:
    """Self-test (reference tuple.lua:309-328)."""
    a = intern((1, 2, 3))
    b = intern([1, 2, 3])
    assert a is b
    assert a == (1, 2, 3)
    c = intern((1, (2, 3)))
    d = intern([1, [2, 3]])
    assert c is d
    assert c[1] is intern((2, 3))
    assert hash(a) == hash(b)
    assert {a: "x"}[b] == "x"
    # immutability: tuples reject item assignment by construction
    try:
        a[0] = 99  # type: ignore[index]
    except TypeError:
        pass
    else:  # pragma: no cover
        raise AssertionError("Tuple must be immutable")
    assert stats()["size"] >= 2
