"""Binary min-heap with a custom comparator.

Analog of reference mapreduce/heap.lua:29-93 — used by the k-way merge
iterator. Python's ``heapq`` does not take a comparator, and the merge needs
one (heterogeneous record keys), so this is a small explicit implementation
with the same API: push / pop / top / empty / size.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class Heap:
    """Binary min-heap ordered by ``lt`` (defaults to ``<``)."""

    def __init__(self, lt: Optional[Callable[[Any, Any], bool]] = None):
        self._lt = lt if lt is not None else (lambda a, b: a < b)
        self._data: List[Any] = []

    def size(self) -> int:
        return len(self._data)

    def empty(self) -> bool:
        return not self._data

    def top(self) -> Any:
        """Smallest element without removing it (reference heap.lua:29-31)."""
        if not self._data:
            raise IndexError("top of empty heap")
        return self._data[0]

    def push(self, value: Any) -> None:
        """Insert and sift up (reference heap.lua:55-70)."""
        data, lt = self._data, self._lt
        data.append(value)
        i = len(data) - 1
        while i > 0:
            parent = (i - 1) // 2
            if lt(data[i], data[parent]):
                data[i], data[parent] = data[parent], data[i]
                i = parent
            else:
                break

    def pop(self) -> Any:
        """Remove and return the smallest element (reference heap.lua:33-53)."""
        data, lt = self._data, self._lt
        if not data:
            raise IndexError("pop from empty heap")
        top = data[0]
        last = data.pop()
        n = len(data)
        if n:
            data[0] = last
            i = 0
            while True:
                left, right = 2 * i + 1, 2 * i + 2
                smallest = i
                if left < n and lt(data[left], data[smallest]):
                    smallest = left
                if right < n and lt(data[right], data[smallest]):
                    smallest = right
                if smallest == i:
                    break
                data[i], data[smallest] = data[smallest], data[i]
                i = smallest
        return top


def utest() -> None:
    """Self-test (reference heap.lua:99-118)."""
    import random

    h = Heap()
    values = [random.random() for _ in range(1000)]
    for v in values:
        h.push(v)
    assert h.size() == len(values)
    out = [h.pop() for _ in range(h.size())]
    assert out == sorted(values)
    assert h.empty()

    # custom comparator: max-heap
    h2 = Heap(lt=lambda a, b: a > b)
    for v in (3, 1, 4, 1, 5):
        h2.push(v)
    assert h2.pop() == 5
    assert h2.top() == 4
