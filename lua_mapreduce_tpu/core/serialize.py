"""Record serialization and canonical key ordering.

Analog of reference mapreduce/utils.lua:100-128: the reference writes
Lua-loadable lines ``return key,{v1,v2,...}\\n`` (utils.lua:107-120) and reads
them back with ``load(line)()`` (utils.lua:222-224). Executing data as code is
a Lua idiom, not a Python one — records here are single-line JSON arrays
``[key, [values...]]``, which are safe to load, language-neutral, and
streamable line-by-line through any storage backend.

Also provides the canonical sort order for heterogeneous keys
(utils.lua:123-128 sorts mixed-type keys by type then value) used by the map
output sort and the k-way merge.
"""

from __future__ import annotations

import functools
import json
import re
from math import isfinite
from typing import Any, Iterable, List, Tuple as PyTuple

from lua_mapreduce_tpu.core import tuples

# chars a JSON string can't carry raw (ensure_ascii=False keeps unicode raw)
_NEEDS_ESCAPE = re.compile(r'[\\"\x00-\x1f]')


def dump_record(key: Any, values: Iterable[Any]) -> str:
    """One record as a single JSON line (no trailing newline).

    Fast path: escape-free str key + int/escape-free-str values formats
    the line directly — json.dumps per record was the top cost of a
    wordcount map job (~1/3 of its wall time). Byte-identical to the
    json.dumps output for the covered shapes (type checks are exact, so
    bool — a JSON-incompatible repr — never slips through as int).
    """
    # fast path requires a re-iterable container: a half-consumed generator
    # could not fall back to json.dumps without losing values
    if (type(key) is str and isinstance(values, (list, tuple))
            and not _NEEDS_ESCAPE.search(key)):
        parts = []
        for v in values:
            tv = type(v)
            if tv is int:
                parts.append(str(v))
            elif tv is str and not _NEEDS_ESCAPE.search(v):
                parts.append(f'"{v}"')
            elif tv is float and isfinite(v):
                # json.dumps emits float.__repr__ for finite floats, so
                # repr() is byte-identical; inf/nan fall back to the slow
                # path (json spells them Infinity/NaN, repr does not)
                parts.append(repr(v))
            else:
                break
        else:
            return f'["{key}",[{",".join(parts)}]]'
    return json.dumps([_plain(key), [_plain(v) for v in values]],
                      separators=(",", ":"), ensure_ascii=False)


def dump_key(key: Any) -> str:
    """Serialized JSON of a record KEY alone — byte-identical to the key
    portion of :func:`dump_record`'s output. Segment footers index frames
    by their first key in this form (core/segment.py)."""
    return json.dumps(_plain(key), separators=(",", ":"),
                      ensure_ascii=False)


def load_record(line: str) -> PyTuple[Any, List[Any]]:
    """Inverse of :func:`dump_record`. List-shaped keys come back interned."""
    key, values = json.loads(line)
    if isinstance(key, list):
        key = tuples.intern(key)
    return key, values


def _plain(v: Any) -> Any:
    """Strip Tuple subclass so json serializes it as an array."""
    if isinstance(v, tuple):
        return [_plain(x) for x in v]
    return v


def _needs_plain(v: Any) -> bool:
    """Does ``v`` contain anything :func:`to_plain` would convert?
    The identity probe that keeps the hot store-plane emit path
    allocation-free: plain scalars and containers of them answer False
    without any rebuilding."""
    if v is None or type(v) in (bool, int, float, str):
        return False
    if isinstance(v, dict):
        return any(_needs_plain(x) for x in v.values())
    if isinstance(v, (list, tuple)):
        return any(_needs_plain(x) for x in v)
    return True


def to_plain(v: Any) -> Any:
    """Normalize an emitted value to the plain-Python record surface.

    IDENTITY — the original object, no copies — for everything the
    engine historically carried: None/bool/int/float/str and containers
    of them (emit is the engine's hottest loop; a deep rebuild per
    record would tax every store-plane map job). Array-likes (numpy
    ndarrays/scalars, concrete jax arrays — anything exposing
    ``tolist``) convert to nested Python lists / scalars, which is
    byte-identical to the user having called ``.tolist()`` before
    emitting; containers holding them are rebuilt (tuples as lists).
    This is the ONE conversion point both execution planes share: the
    store plane applies it at emit, at combiner output, and at reduce
    output (engine/job.py), the in-graph engine applies it to fetched
    device results (engine/ingraph.py) — so a task written against jnp
    arrays serializes to the same record bytes on either plane.

    A JAX TRACER reaching this path raises jax's own concretization
    error (``tolist`` on a tracer): in-graph user code leaked a traced
    value onto the host path, and silently stringifying it would
    corrupt records — loud is correct.
    """
    if not _needs_plain(v):
        return v
    if isinstance(v, dict):
        return {k: to_plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [to_plain(x) for x in v]
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        return to_plain(tolist())
    return v


def serialized_size(value: Any) -> int:
    """Byte size of a value's serialized form — used for the taskfn value cap
    (reference server.lua:263-267, MAX_TASKFN_VALUE_SIZE)."""
    return len(json.dumps(_plain(value), separators=(",", ":")).encode())


# --- canonical ordering for heterogeneous keys -----------------------------

_TYPE_RANK = {bool: 0, int: 1, float: 1, str: 2, tuple: 3, type(None): 4}


def type_rank(v: Any) -> int:
    for t, r in _TYPE_RANK.items():
        if isinstance(v, t):
            return r
    return 5


def key_lt(a: Any, b: Any) -> bool:
    """Total order over mixed-type keys: by type rank, then value.

    Mirrors the reference's mixed-type key sort (utils.lua:123-128) which
    compares ``tostring`` forms across types; here types are ranked and
    values compared natively within a rank (tuples: elementwise recursive,
    matching tuple.lua:183-201 lexicographic __lt).
    """
    ra, rb = type_rank(a), type_rank(b)
    if ra != rb:
        return ra < rb
    if isinstance(a, tuple):
        for x, y in zip(a, b):
            if key_lt(x, y):
                return True
            if key_lt(y, x):
                return False
        return len(a) < len(b)
    if a is None:
        return False
    return a < b


def sorted_keys(keys: Iterable[Any]) -> List[Any]:
    """Sort heterogeneous keys canonically (reference utils.lua:123-128).

    Fast path: each key maps to a canonical sortable form — scalars to
    (rank, value), tuples RECURSIVELY to (rank, tuple-of-forms) — whose
    native tuple comparison is exactly key_lt's order (rank decides
    cross-type, value decides within-rank, elementwise-then-length for
    tuples; bool-vs-int inside tuples stays rank-separated, where a
    naive (rank, key) form would compare True==1 numerically). This is
    ~40x cheaper than a cmp_to_key comparator, which was 80% of a
    wordcount map job's wall time. Unrankable key types (rank 5, never
    produced by the record format) fall back to the exact comparator.
    """
    keys = list(keys)
    if all(type(k) is str for k in keys):
        return sorted(keys)    # single-rank: native order == key_lt order
    try:
        return sorted(keys, key=_canon_key)
    except TypeError:
        return sorted(keys, key=functools.cmp_to_key(
            lambda a, b: -1 if key_lt(a, b) else (1 if key_lt(b, a) else 0)))


def _canon_key(k: Any):
    r = type_rank(k)
    if isinstance(k, tuple):
        return (r, tuple(_canon_key(e) for e in k))
    if k is None:
        return (r, 0)       # all Nones equal; never compare None itself
    if r == 5:
        raise TypeError(f"unrankable key type {type(k).__name__}")
    return (r, k)


def assert_serializable(value: Any, path: str = "value") -> None:
    """Validate a value is record-serializable (reference utils.lua:313-333
    ``assert_check`` enforces JSON-compatible emit values)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return
    if isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            assert_serializable(v, f"{path}[{i}]")
        return
    if isinstance(value, dict):
        for k, v in value.items():
            if not isinstance(k, str):
                raise TypeError(f"{path}: dict keys must be str, got {type(k)}")
            assert_serializable(v, f"{path}.{k}")
        return
    raise TypeError(f"{path}: unserializable type {type(value).__name__}")


def utest() -> None:
    """Self-test (reference utils.lua:340-406 exercises serialization)."""
    line = dump_record("word", [1, 2, 3])
    assert load_record(line) == ("word", [1, 2, 3])

    # float fast path: byte-identical to json.dumps; specials fall back
    for vals in ([1.5, -0.0, 2.5e-8], [1, "a", 3.25], [float("inf")],
                 [float("nan")]):
        assert dump_record("k", vals) == json.dumps(
            ["k", vals], separators=(",", ":"), ensure_ascii=False)
    assert dump_key(("a", 1)) == '["a",1]'

    k, vs = load_record(dump_record(tuples.intern((1, "a")), [[2, 3]]))
    assert k is tuples.intern((1, "a"))
    assert vs == [[2, 3]]

    assert key_lt(1, "a") and not key_lt("a", 1)
    assert key_lt("a", "b")
    assert key_lt((1, 2), (1, 3)) and key_lt((1,), (1, 2))
    assert sorted_keys(["b", 2, "a", 1]) == [1, 2, "a", "b"]

    # to_plain: IDENTITY (same object) for plain shapes, tolist for
    # array-likes, container rebuild only when a leaf converted
    plain = {"a": [1, 2.5, "x"], "b": None}
    assert to_plain(plain) is plain
    assert to_plain(tuples.intern((1, 2))) is tuples.intern((1, 2))
    import numpy as _np
    assert to_plain(_np.int32(3)) == 3 and type(to_plain(_np.int32(3))) is int
    assert to_plain(_np.arange(3)) == [0, 1, 2]
    assert to_plain({"g": _np.float32(1.5)}) == {"g": 1.5}
    assert to_plain((1, _np.int32(2))) == [1, 2]

    assert serialized_size("xx") == 4  # '"xx"'
    try:
        assert_serializable({1: "bad"})  # type: ignore[dict-item]
    except TypeError:
        pass
    else:  # pragma: no cover
        raise AssertionError("non-str dict key must be rejected")
