"""Shared build/load scaffolding for native C++ components.

One discipline for every native piece (coord/native/jobstore.cpp,
core/native/shufflemerge.cpp): compile on first use with the host
toolchain, cache the .so keyed on a SOURCE HASH (git checkout gives
source and a stale binary identical mtimes, which would mask layout
changes), load via ctypes, and report failure as None — native code is
always an optimization with a pure-Python fallback, never a requirement.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_cache: Dict[str, Optional[ctypes.CDLL]] = {}   # so_path → lib or None


def _src_digest(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build(src: str, so: str, extra_flags=()) -> Optional[str]:
    digest_file = so + ".src.sha256"
    digest = _src_digest(src)
    variants = (tuple(extra_flags), ()) if extra_flags else ((),)
    # the digest file records WHICH flag variant built the cached .so
    # ("<sha> <flags>"); a cache built with the degraded bare variant is
    # retried with the preferred flags once per process, so installing
    # the optional library (e.g. zlib) upgrades the .so instead of the
    # old fallback being served forever
    cached_flags = None
    if os.path.exists(so):
        try:
            with open(digest_file) as f:
                rec = f.read().strip().split(None, 1)
            if rec and rec[0] == digest:
                cached_flags = tuple((rec[1] if len(rec) > 1 else "").split())
                if cached_flags == variants[0]:
                    return so
        except OSError:
            pass
    # extra_flags are OPTIONAL capabilities (e.g. -DLMR_HAVE_ZLIB -lz for
    # compressed-segment decode): try with them first, retry bare when
    # the host lacks the library — the source gates the capability on
    # the macro, so the bare build degrades features, not correctness
    for flags in variants:
        if flags == cached_flags:
            return so           # this variant is exactly the cached .so
        try:
            # compile to a tmp name + atomic rename: a concurrent builder
            # in another process must never load a half-written .so
            tmp = f"{so}.tmp.{os.getpid()}"
            subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-o", tmp,
                            src, *flags],
                           check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
            with open(digest_file, "w") as f:
                f.write(f"{digest} {' '.join(flags)}".rstrip())
            return so
        except (OSError, subprocess.SubprocessError):
            continue
    return None


def load_native(src: str, so: str,
                extra_flags=()) -> Optional[ctypes.CDLL]:
    """Build (if stale/absent) and load ``src`` as ``so``; None on any
    failure. Caches per-process: one compile attempt per .so path.

    ``LMR_DISABLE_NATIVE=1`` is the global kill switch: every native
    fast path loads through here, so starting a process with it set
    forces the pure-Python semantics — the first tool to reach for when
    debugging a suspected native/Python divergence in production. NB:
    components that cached a loaded library at construction (e.g. a
    NativeJobIndex built earlier in this process) keep their handle;
    the switch governs loads AFTER it is set, so set it at process
    start."""
    if os.environ.get("LMR_DISABLE_NATIVE") == "1":
        return None
    with _lock:
        if so in _cache:
            return _cache[so]
        path = _build(src, so, extra_flags)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                lib = None
        _cache[so] = lib
        return lib
