"""Shared build/load scaffolding for native C++ components.

One discipline for every native piece (coord/native/jobstore.cpp,
core/native/shufflemerge.cpp): compile on first use with the host
toolchain, cache the .so keyed on a SOURCE HASH (git checkout gives
source and a stale binary identical mtimes, which would mask layout
changes), load via ctypes, and report failure as None — native code is
always an optimization with a pure-Python fallback, never a requirement.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, Optional

_lock = threading.Lock()
_cache: Dict[str, Optional[ctypes.CDLL]] = {}   # so_path → lib or None


def _src_digest(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _build(src: str, so: str) -> Optional[str]:
    digest_file = so + ".src.sha256"
    digest = _src_digest(src)
    if os.path.exists(so):
        try:
            with open(digest_file) as f:
                if f.read().strip() == digest:
                    return so
        except OSError:
            pass
    try:
        # compile to a tmp name + atomic rename: a concurrent builder in
        # another process must never load a half-written .so
        tmp = f"{so}.tmp.{os.getpid()}"
        subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-o", tmp, src],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        with open(digest_file, "w") as f:
            f.write(digest)
        return so
    except (OSError, subprocess.SubprocessError):
        return None


def load_native(src: str, so: str) -> Optional[ctypes.CDLL]:
    """Build (if stale/absent) and load ``src`` as ``so``; None on any
    failure. Caches per-process: one compile attempt per .so path.

    ``LMR_DISABLE_NATIVE=1`` is the global kill switch: every native
    fast path loads through here, so starting a process with it set
    forces the pure-Python semantics — the first tool to reach for when
    debugging a suspected native/Python divergence in production. NB:
    components that cached a loaded library at construction (e.g. a
    NativeJobIndex built earlier in this process) keep their handle;
    the switch governs loads AFTER it is set, so set it at process
    start."""
    if os.environ.get("LMR_DISABLE_NATIVE") == "1":
        return None
    with _lock:
        if so in _cache:
            return _cache[so]
        path = _build(src, so)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                lib = None
        _cache[so] = lib
        return lib
