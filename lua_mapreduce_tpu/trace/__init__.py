"""lmr-trace — store-native distributed tracing (DESIGN §22).

Three pieces:

- ``span``     — :class:`Tracer` (buffered spans on an injectable
  clock, deterministic ids, store-file flush under the ``_trace.``
  prefix) and the process-global ``install_tracer``/``active_tracer``
  plumbing (``--trace`` / ``LMR_TRACE``);
- ``wrappers`` — :class:`TracingStore` / :class:`TracingJobStore`,
  stacked inside the retry layer by faults/wrappers.py's wiring points
  so every retry attempt, failover read, and degraded read is a child
  span of the consuming job body;
- ``collect``  — :class:`TraceCollection` (lifecycle chains + the
  completeness oracle, per-op latency histograms, phase waterfall,
  span-measured pre-merge overlap, Chrome trace-event export) and
  ``validate_chrome``; rendered by ``python -m lua_mapreduce_tpu.trace``.
"""

from lua_mapreduce_tpu.trace.collect import (TraceCollection, read_spans,
                                             validate_chrome)
from lua_mapreduce_tpu.trace.span import (TRACE_NS, Tracer, active_tracer,
                                          install_tracer, span_id,
                                          trace_generation)

__all__ = [
    "TRACE_NS", "Tracer", "active_tracer", "install_tracer", "span_id",
    "trace_generation", "TraceCollection", "read_spans", "validate_chrome",
]


def utest() -> None:
    """Run the subsystem's module self-tests."""
    from lua_mapreduce_tpu.trace import collect, span, wrappers
    for mod in (span, wrappers, collect):
        mod.utest()
