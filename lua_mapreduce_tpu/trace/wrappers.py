"""TracingStore / TracingJobStore — per-op span wrappers (DESIGN §22).

Siblings of faults/wrappers.py's retry layer, stacked INSIDE it by the
shared wiring points (``wrap_store`` / ``wrap_jobstore``)::

    RetryingStore( TracingStore( FaultyStore( real ) ) )     — data plane
    RetryingJobStore( TracingJobStore( FaultyJobStore( real ) ) ) — coord

The ordering is the point: sitting under the retry layer and over the
injection layer means EVERY retry attempt — including one that dies on
an injected fault — records its own span (tagged with the error class),
parented to whatever job-body span is open on the thread. Failover
reads (faults/replicate.py wraps the full stack) and degraded whole-file
reads (core/segment.py re-enters through the same stack) appear the
same way: extra child spans under the consuming body, which is exactly
the "why was this reduce slow" answer the phase aggregates can't give.

Every wrapper records into the process tracer; when no tracer is active
the wiring points simply skip this layer, so tracing-off runs carry
zero overhead and zero behavioral difference.
"""

from __future__ import annotations

from typing import Iterator, List

from lua_mapreduce_tpu.faults.plan import RPC_OPS
from lua_mapreduce_tpu.store.base import FileBuilder, Store
from lua_mapreduce_tpu.trace.span import Tracer


class _TracingBuilder(FileBuilder):
    """Passthrough builder whose ``build`` — the spill-publish moment —
    records a span. Writes are not individually traced: a build span
    plus the byte count says everything a timeline needs without a
    span per 256KB frame."""

    def __init__(self, store: "TracingStore"):
        self._store = store
        self._inner = store._inner.builder()
        self._bytes = 0

    def write(self, data: str) -> None:
        self._bytes += len(data)
        self._inner.write(data)

    def write_bytes(self, data: bytes) -> None:
        self._bytes += len(data)
        self._inner.write_bytes(data)

    def build(self, name: str) -> None:
        tr = self._store._tracer
        t0 = tr.clock()
        try:
            self._inner.build(name)
        except BaseException as exc:
            tr.op("store.build", t0, file=name, bytes=self._bytes,
                  error=type(exc).__name__)
            raise
        tr.op("store.build", t0, file=name, bytes=self._bytes)

    def close(self) -> None:
        self._inner.close()


class TracingStore(Store):
    """Span per data-plane op. Unknown attributes (``local_path``,
    memfs test hooks) forward to the wrapped store so native fast paths
    keep working — ops that bypass the portable plane are covered by
    the enclosing job-body span instead of an op span."""

    def __init__(self, inner: Store, tracer: Tracer):
        self._inner = inner
        self._tracer = tracer
        # mirror the inner backend's publish ambiguity: the retrying
        # builder reads it off its direct inner layer (this one)
        self.publish_ambiguous = getattr(inner, "publish_ambiguous", True)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def _op(self, op: str, name: str, fn):
        tr = self._tracer
        t0 = tr.clock()
        try:
            out = fn()
        except BaseException as exc:
            tr.op(f"store.{op}", t0, file=name, error=type(exc).__name__)
            raise
        tr.op(f"store.{op}", t0, file=name)
        return out

    def builder(self) -> FileBuilder:
        return _TracingBuilder(self)

    def lines(self, name: str) -> Iterator[str]:
        # the span covers the CONSUMPTION window (open through last
        # record), which is the cost a merge actually pays — an
        # open-only span would read as free for a 100MB stream
        tr = self._tracer
        t0 = tr.clock()
        err = None
        try:
            yield from self._inner.lines(name)
        except GeneratorExit:
            raise       # consumer stopped reading early (one-record
            #             manifest peeks) — a normal close, not a fault
        except BaseException as exc:
            err = type(exc).__name__
            raise
        finally:
            if err is None:
                tr.op("store.lines", t0, file=name)
            else:
                tr.op("store.lines", t0, file=name, error=err)

    def read_range(self, name: str, offset: int, length: int) -> bytes:
        return self._op("read_range", name,
                        lambda: self._inner.read_range(name, offset, length))

    def size(self, name: str) -> int:
        return self._op("size", name, lambda: self._inner.size(name))

    def list(self, pattern: str) -> List[str]:
        return self._op("list", pattern, lambda: self._inner.list(pattern))

    def exists(self, name: str) -> bool:
        return self._op("exists", name, lambda: self._inner.exists(name))

    def remove(self, name: str) -> None:
        return self._op("remove", name, lambda: self._inner.remove(name))

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)


# --------------------------------------------------------------------------
# coord plane
# --------------------------------------------------------------------------


class TracingJobStore:
    """Span per coord RPC, plus derived PER-JOB lifecycle spans.

    The RPC wrapper sees exactly what the protocol decided — which jobs
    a claim leased, which commits landed, which status CASes took — so
    the per-job claim/commit/release/broken spans that the lifecycle
    chain (claim → body → commit) is assembled from are emitted HERE,
    from ground truth, instead of being reconstructed from engine-side
    bookkeeping that a lost race would falsify. A loser's commit_batch
    returns no ids → no commit span → exactly one commit span per
    committed job, by construction (the first-commit-wins CAS is the
    arbiter, DESIGN §21).
    """

    def __init__(self, inner, tracer: Tracer):
        self._inner = inner
        self._tracer = tracer

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def classify(self, exc: BaseException):
        return self._inner.classify(exc)

    # -- per-op wrappers (generated below, faults/wrappers.py style) -------

    def _post_claim_batch(self, sp, args, out):
        for doc in out:
            self._tracer.add(
                "claim", sp["t0"], sp["t1"], ns=args[0],
                job_id=doc.get("_id"),
                attempt=int(doc.get("repetitions") or 0),
                parent=sp["sid"])
            # the DISPATCH span (lmr-sched, DESIGN §23): insert→claim
            # per job, from the payload's insert stamp to this claim's
            # close — the latency the watch/notify layer exists to
            # shrink, reported natively by the collector's per-op
            # histograms. Guarded against clock mismatch: a virtual-
            # clock tracer cannot be compared to the doc's wall stamp.
            ct = doc.get("creation_time")
            if isinstance(ct, (int, float)) and ct <= sp["t1"]:
                self._tracer.add(
                    "dispatch", float(ct), sp["t1"], ns=args[0],
                    job_id=doc.get("_id"),
                    attempt=int(doc.get("repetitions") or 0),
                    parent=sp["sid"])

    def _post_claim_spec(self, sp, args, out):
        if out is not None:
            self._tracer.add(
                "claim", sp["t0"], sp["t1"], ns=args[0],
                job_id=out.get("_id"),
                attempt=int(out.get("repetitions") or 0),
                parent=sp["sid"], speculative=True)

    def _post_commit_batch(self, sp, args, out):
        for jid in out:
            self._tracer.add("commit", sp["t0"], sp["t1"], ns=args[0],
                             job_id=jid, attempt=-1, parent=sp["sid"])

    def _post_set_job_status(self, sp, args, out):
        if not out or len(args) < 3:
            return
        status = args[2]
        label = getattr(status, "name", str(status)).lower()
        self._tracer.add(f"status.{label}", sp["t0"], sp["t1"], ns=args[0],
                         job_id=args[1], attempt=-1, parent=sp["sid"])

    def _post_speculate(self, sp, args, out):
        if out:
            self._tracer.add("speculate", sp["t0"], sp["t1"], ns=args[0],
                             job_id=args[1], attempt=-1, parent=sp["sid"])

    def _post_cancel_spec(self, sp, args, out):
        if out:
            self._tracer.add("spec_cancel", sp["t0"], sp["t1"], ns=args[0],
                             job_id=args[1], attempt=-1, parent=sp["sid"])

    _POST = {"claim_batch": _post_claim_batch,
             "claim_spec": _post_claim_spec,
             "commit_batch": _post_commit_batch,
             "set_job_status": _post_set_job_status,
             "speculate": _post_speculate,
             "cancel_spec": _post_cancel_spec}


def _make_rpc_wrappers():
    """Generate the wrapped RPC methods once at import (the
    faults/wrappers.py pattern — a hand-written wall would drift).
    ``claim`` is included alongside the RPC_OPS set: the single-claim
    compatibility surface must not silently bypass tracing."""
    def tracing(op):
        post = TracingJobStore._POST.get(op)

        def call(self, *args, **kw):
            tr = self._tracer
            ns = args[0] if args and isinstance(args[0], str) else None
            t0 = tr.clock()
            try:
                out = getattr(self._inner, op)(*args, **kw)
            except BaseException as exc:
                tr.op(f"coord.{op}", t0, ns=ns, error=type(exc).__name__)
                raise
            sp = tr.op(f"coord.{op}", t0, ns=ns)
            if post is not None:
                post(self, sp, args, out)
            return out
        call.__name__ = op
        return call

    for op in sorted(RPC_OPS | {"claim"}):
        setattr(TracingJobStore, op, tracing(op))


_make_rpc_wrappers()


def utest() -> None:
    """Self-test: op spans, per-attempt spans under the retry stack,
    derived per-job lifecycle spans, first-commit-wins span uniqueness."""
    import random

    from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job
    from lua_mapreduce_tpu.core.constants import Status
    from lua_mapreduce_tpu.faults.plan import FaultPlan
    from lua_mapreduce_tpu.faults.retry import RetryPolicy
    from lua_mapreduce_tpu.faults.wrappers import FaultyStore, RetryingStore
    from lua_mapreduce_tpu.store.memfs import MemStore

    tr = Tracer()
    tr.set_actor("w-utest")

    # data plane: the retry stack replays through the tracing layer, so
    # a transient burst shows one span PER ATTEMPT — failed attempts
    # tagged with the injected error class
    plan = FaultPlan(3, transient=1.0, max_per_key=2, sleep=lambda s: None)
    policy = RetryPolicy(retries=3, base_ms=1, sleep=lambda s: None,
                         rng=random.Random(0))
    raw = MemStore()
    with raw.builder() as b:
        b.write("k 1\n")
        b.build("f")
    store = RetryingStore(TracingStore(FaultyStore(raw, plan), tr), policy)
    assert store.read_range("f", 0, 3) == b"k 1"
    spans = tr.drain()
    reads = [s for s in spans if s["name"] == "store.read_range"]
    assert len(reads) == 3          # 2 injected failures + the success
    assert [("error" in s.get("attrs", {})) for s in reads] == \
        [True, True, False]

    # builder span carries the byte count
    with TracingStore(raw, tr).builder() as b:
        b.write("abc\n")
        b.build("g")
    (bs,) = [s for s in tr.drain() if s["name"] == "store.build"]
    assert bs["attrs"] == {"file": "g", "bytes": 4}

    # a consumer abandoning a lines() stream early (manifest peeks) is
    # a normal close: the span records WITHOUT an error tag
    gen = TracingStore(raw, tr).lines("f")
    assert next(gen) == "k 1\n"
    gen.close()
    (ln,) = [s for s in tr.drain() if s["name"] == "store.lines"]
    assert "error" not in ln.get("attrs", {})

    # coord plane: claim/commit derive per-job spans from ground truth
    js = MemJobStore()
    wrapped = TracingJobStore(js, tr)
    wrapped.insert_jobs("map_jobs", [make_job("k", 1), make_job("k2", 2)])
    got = wrapped.claim_batch("map_jobs", "w-utest", 2)
    assert len(got) == 2
    t = {"started": 0.0, "finished": 0.0, "written": 0.0, "cpu": 0.0,
         "real": 0.0}
    assert wrapped.commit_batch("map_jobs", "w-utest",
                                [(0, t), (1, t)]) == [0, 1]
    # a second (loser) commit lands nothing -> NO extra commit spans
    assert wrapped.commit_batch("map_jobs", "other", [(0, t)]) == []
    assert wrapped.set_job_status("map_jobs", 0, Status.WRITTEN,
                                  expect=(Status.RUNNING,)) is False
    spans = tr.drain()
    names = [s["name"] for s in spans]
    assert names.count("claim") == 2
    assert names.count("commit") == 2
    # every claimed doc derives a dispatch span (insert→claim) whose
    # window opens at the job's insert stamp
    dispatches = [s for s in spans if s["name"] == "dispatch"]
    assert len(dispatches) == 2
    assert all(s["t1"] >= s["t0"] for s in dispatches)
    claims = {s["job"]: s for s in spans if s["name"] == "claim"}
    assert set(claims) == {0, 1} and claims[0]["ns"] == "map_jobs"
    rpc = [s for s in spans if s["name"] == "coord.claim_batch"]
    assert claims[0]["parent"] == rpc[0]["sid"]
    # passthrough of non-RPC surfaces
    assert wrapped.round_counts()["claim"] >= 1
