"""lmr-trace CLI: inspect a run's flushed spans.

    python -m lua_mapreduce_tpu.trace STORAGE_SPEC [--top K]
        [--export chrome.json] [--format json]

STORAGE_SPEC is the task's storage ("shared:DIR" / "object:DIR" /
"mem:TAG" for an in-process store) — the same spec the server and
workers ran with; spans live there as ``_trace.*`` files. Default
output: the phase waterfall, per-op latency histograms (p50/p95/p99),
the pre-merge overlap measured from real spans, and the top-k slowest
jobs. ``--export`` writes Chrome trace-event JSON loadable in Perfetto
(ui.perfetto.dev) or chrome://tracing; ``--format json`` emits the
whole report as one machine-readable document.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lua_mapreduce_tpu.trace",
        description="Assemble and render lmr-trace spans from a store.")
    p.add_argument("storage", help="backend[:path] spec the traced task "
                                   "ran with (spans live as _trace.* "
                                   "files there)")
    p.add_argument("--top", type=int, default=10,
                   help="how many slowest jobs to list (default 10)")
    p.add_argument("--export", metavar="FILE", default=None,
                   help="write Chrome trace-event JSON (Perfetto / "
                        "chrome://tracing) to FILE")
    p.add_argument("--format", choices=("text", "json"), default="text")
    return p


def report(col) -> dict:
    """The full machine-readable report (the text renderer and the
    ``--format json`` output share it)."""
    return {"spans": len(col.spans),
            "phases": col.phase_waterfall(),
            "premerge_overlap": col.premerge_overlap(),
            "ops": col.op_stats(),
            "speculation": col.speculation_outcomes(),
            # engine per iteration + the lowering decision chain
            # (DESIGN §26): a silent in-graph→store fallback must be
            # visible in the default report, not only in raw spans
            "engines": {str(it): eng for it, eng
                        in col.engines_by_iteration().items()},
            "lowering": col.lowering_decisions(),
            # controller decision chain (lmr-autotune, DESIGN §29):
            # every applied knob change with its evidence payload
            "autotune": col.autotune_decisions()}


def _bar(frac: float, width: int = 32) -> str:
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def render_text(col, top: int) -> str:
    rep = report(col)
    out = [f"lmr-trace: {rep['spans']} spans"]
    rows = rep["phases"]
    if rows:
        t_lo = min(r["t0"] for r in rows)
        t_hi = max(r["t1"] for r in rows)
        width = max(t_hi - t_lo, 1e-9)
        out.append("\nphase waterfall (wall-aligned):")
        for r in rows:
            lead = int(round((r["t0"] - t_lo) / width * 32))
            span_w = max(1, int(round(r["window_s"] / width * 32)))
            bar = " " * lead + "=" * min(span_w, 32 - lead)
            out.append(f"  {r['phase']:>10} |{bar:<32}| "
                       f"{r['window_s']:8.3f}s window  "
                       f"{r['busy_s']:8.3f}s busy  {r['jobs']} jobs")
    if rep["engines"]:
        parts = [f"it{it}={eng}" for it, eng in rep["engines"].items()]
        out.append("\nengine per iteration: " + "  ".join(parts))
    for d in rep["lowering"]:
        if d["span"] == "lowering":
            out.append(f"lowering: engine={d.get('engine')} "
                       f"(requested={d.get('requested')}, "
                       f"verdict={d.get('verdict')}) — "
                       f"{d.get('reason', '')}")
        elif d["span"].startswith("lowering."):
            # per-stage hybrid verdict (DESIGN §28)
            out.append(f"lowering: stage {d.get('stage')} -> "
                       f"{d.get('engine')} "
                       f"(compiled={d.get('compiled')})")
        elif d["span"] == "hybrid.fallback":
            out.append(f"lowering: HYBRID FALLBACK it{d['it']} "
                       f"stage={d.get('stage')} — {d.get('reason', '')}")
        else:
            out.append(f"lowering: RUNTIME FALLBACK it{d['it']} — "
                       f"{d.get('reason', '')}")
    if rep["premerge_overlap"] is not None:
        out.append(f"\npre-merge overlap (from spans): "
                   f"{rep['premerge_overlap']:.2%} "
                   f"[{_bar(rep['premerge_overlap'])}]")
    if rep["ops"]:
        out.append("\nper-op latency (ms):")
        out.append(f"  {'op':<24} {'count':>7} {'p50':>9} {'p95':>9} "
                   f"{'p99':>9} {'max':>9} {'total_s':>9}")
        for name, st in rep["ops"].items():
            out.append(f"  {name:<24} {st['count']:>7} {st['p50_ms']:>9.3f} "
                       f"{st['p95_ms']:>9.3f} {st['p99_ms']:>9.3f} "
                       f"{st['max_ms']:>9.3f} {st['total_s']:>9.3f}")
    slow = col.slowest_jobs(top)
    if slow:
        out.append(f"\ntop {len(slow)} slowest jobs (total body time):")
        for r in slow:
            out.append(f"  {r['ns']}/{r['job']}: {r['body_s']:.3f}s over "
                       f"{r['executions']} execution(s) by "
                       f"{', '.join(r['workers'])}")
    for o in rep["speculation"]:
        out.append(f"\nspeculation: {o['ns']}/{o['job']} won by "
                   f"{o['winner']} (losers: "
                   f"{', '.join(o['losers']) or 'none'}; "
                   f"cancelled={o['cancelled']})")
    for d in rep["autotune"]:
        out.append(f"autotune: it{d['it']} {d['knob']} "
                   f"{d.get('old')} -> {d.get('new')} "
                   f"({d.get('metric')}={d.get('observed')}, "
                   f"threshold {d.get('threshold')})")
    return "\n".join(out)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from lua_mapreduce_tpu.store.router import get_storage_from
    from lua_mapreduce_tpu.trace.collect import TraceCollection
    col = TraceCollection.from_store(get_storage_from(args.storage))
    if not col.spans:
        print("no _trace.* spans found — was the run traced? "
              "(--trace / LMR_TRACE=1)", file=sys.stderr)
        return 1
    if args.export:
        doc = col.to_chrome()
        with open(args.export, "w") as f:
            json.dump(doc, f)
        print(f"wrote {len(doc['traceEvents'])} trace events to "
              f"{args.export} (load in ui.perfetto.dev)", file=sys.stderr)
    if args.format == "json":
        print(json.dumps(report(col), indent=2))
    else:
        print(render_text(col, args.top))
    return 0


if __name__ == "__main__":
    try:
        code = main()
    except BrokenPipeError:
        # pipe-safety (`... | head`): the reader closing early is a
        # normal exit, not a traceback. Re-point stdout at devnull so
        # the interpreter's shutdown flush cannot re-raise.
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    raise SystemExit(code)
