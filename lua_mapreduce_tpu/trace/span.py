"""Span layer — lmr-trace's core (DESIGN §22).

The observability gap this closes: utils/stats.py folds per-job
lifecycle timestamps into phase aggregates (the reference's model),
which says how long a phase took but never WHY — claim latency vs job
body vs spill publish vs commit is invisible, and every counter added
since PR 5 (store_retries, failover_reads, spec_wins ...) is an opaque
total with no timeline behind it. A :class:`Span` is one named interval
with causal context: the op or job it covers, the worker that ran it,
the namespace/job/attempt it belongs to, and its parent span — the
Dapper shape, sized for this engine.

Design constraints, in order:

- **Determinism.** Span ids derive from ``(worker, ns, job, attempt,
  name, occurrence)`` — no RNG, no wall-clock in the id — so a chaos
  test can COMPUTE the id a failure should have produced and assert
  the errors-stream link resolves (tests/test_trace.py). The clock is
  injectable (the faults/retry.py convention) so virtual-clock tests
  replay exact timelines; lint rule LMR010 keeps every timing read in
  this package on it.
- **Zero data-plane changes.** Spans buffer in-process and flush as
  ordinary store files under the ``_trace.`` name prefix (the
  errors-stream pattern: append-only telemetry, drained by whoever
  collects). Flushes write through the UNWRAPPED innermost store —
  below retry, injection, and the tracing wrappers themselves — so
  telemetry can neither perturb a FaultPlan's schedules nor trace its
  own writes.
- **Invisible when off.** ``active_tracer()`` is None unless a tracer
  is installed (``--trace``) or ``LMR_TRACE`` is set; every engine hook
  is a None-check, and the wrapper layers are simply not stacked —
  tracing-off runs are byte-identical to the unwired seed (the golden
  matrix twin test).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import re
import threading
import time
from typing import Callable, Dict, List, Optional

TRACE_NS = "_trace"            # store-name prefix every flush publishes under

_SAFE_ACTOR = re.compile(r"[^A-Za-z0-9_.\-]")


def span_id(worker, ns, job_id, attempt, name: str, occ: int = 0) -> str:
    """Deterministic 16-hex-char span id. Pure function of the span's
    causal coordinates — chaos tests recompute it to assert an error
    entry links to the span that was live when the fault fired."""
    key = f"{worker}|{ns}|{job_id}|{attempt}|{name}|{occ}"
    return hashlib.blake2b(key.encode("utf-8"), digest_size=8).hexdigest()


class Tracer:
    """Buffered span recorder on an injectable clock.

    One instance serves a whole process (the FaultCounters visibility
    contract): worker threads, the server loop, and the local executor
    all record into one buffer, each under its own thread-local actor
    name. ``flush`` publishes the buffer as one ``_trace.<actor>.<seq>``
    JSON-lines file through a store.
    """

    FLUSH_THRESHOLD = 512       # spans buffered before a soft flush fires

    def __init__(self, clock: Callable[[], float] = time.time,
                 annotate: bool = False):
        self._clock = clock
        self.annotate = annotate     # bridge span names into the JAX
        #                              device profile (utils/profiling)
        self._lock = threading.Lock()
        self._buf: List[dict] = []
        self._occ: Dict[tuple, int] = {}
        self._flush_seq: Dict[str, int] = {}
        self._tls = threading.local()
        self._iteration = 0     # stamped onto every span ("it"): job
        #                         ids restart per iteration, so the
        #                         collector needs it to keep chains
        #                         from conflating across iterations

    # -- actor / context ----------------------------------------------------

    def set_actor(self, name: Optional[str]) -> None:
        """Declare the calling thread's identity (worker name, "server",
        "local"); span ``worker`` fields default to it."""
        self._tls.actor = name

    def actor(self) -> str:
        return getattr(self._tls, "actor", None) or "proc"

    def set_iteration(self, iteration: int) -> None:
        """Declare the task iteration subsequent spans belong to (the
        engines call this per iteration / per task-doc poll). Plain
        int store — GIL-atomic, and a one-poll skew on a racing thread
        only mislabels spans at the boundary of an already-rolled-over
        namespace."""
        self._iteration = int(iteration)

    def current(self) -> Optional[dict]:
        """The innermost open span on this thread (parent for new ones)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def clock(self) -> float:
        return self._clock()

    # -- recording ----------------------------------------------------------

    def _mint(self, name: str, worker, ns, job_id, attempt) -> str:
        key = (worker, ns, job_id, attempt, name)
        with self._lock:
            occ = self._occ.get(key, 0)
            self._occ[key] = occ + 1
        return span_id(worker, ns, job_id, attempt, name, occ)

    def _record(self, span: dict) -> None:
        with self._lock:
            self._buf.append(span)

    def _inherit(self, ns, job_id, attempt, parent):
        """Fill unset context from the thread's current open span — the
        ONE inheritance rule (``add`` and ``span`` share it, so a new
        context field cannot drift between op spans and body spans)."""
        cur = self.current()
        if cur is not None:
            if ns is None:
                ns = cur.get("ns")
            if job_id is None:
                job_id = cur.get("job")
            if attempt is None:
                attempt = cur.get("attempt")
            if parent is None:
                parent = cur.get("sid")
        return ns, job_id, attempt, parent

    def add(self, name: str, t0: float, t1: float, *, ns=None, job_id=None,
            attempt=None, parent: Optional[str] = None, worker=None,
            **attrs) -> dict:
        """Record one closed span with explicit times. Context not given
        explicitly is inherited from the thread's current open span."""
        ns, job_id, attempt, parent = self._inherit(ns, job_id, attempt,
                                                    parent)
        worker = worker if worker is not None else self.actor()
        span = {"sid": self._mint(name, worker, ns, job_id, attempt),
                "parent": parent, "name": name, "worker": worker,
                "ns": ns, "job": job_id, "attempt": attempt,
                "it": self._iteration, "t0": t0, "t1": t1}
        if attrs:
            span["attrs"] = attrs
        self._record(span)
        return span

    def op(self, name: str, t0: float, **attrs) -> dict:
        """Record an op span that started at ``t0`` and ends NOW —
        the wrapper layers' one-liner."""
        return self.add(name, t0, self._clock(), **attrs)

    @contextlib.contextmanager
    def span(self, name: str, *, ns=None, job_id=None, attempt=None,
             worker=None, **attrs):
        """Open a span around a ``with`` body. The yielded dict already
        carries its deterministic ``sid`` (error paths link to it before
        the span closes); ``t1`` is stamped on exit, and a body that
        raises gets an ``error`` attr instead of losing the span."""
        ns, job_id, attempt, parent = self._inherit(ns, job_id, attempt,
                                                    None)
        worker = worker if worker is not None else self.actor()
        span = {"sid": self._mint(name, worker, ns, job_id, attempt),
                "parent": parent, "name": name,
                "worker": worker, "ns": ns, "job": job_id,
                "attempt": attempt, "it": self._iteration,
                "t0": self._clock(), "t1": None}
        if attrs:
            span["attrs"] = dict(attrs)
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(span)
        ann = None
        if self.annotate:
            # host↔device correlation: the same span name shows up in
            # the XLA profile's host rows (utils/profiling.annotate),
            # so a Perfetto timeline and a TensorBoard profile line up.
            # Enter/exit are guarded: a torn-down profiler session must
            # neither sink the job body (a non-StoreError here would be
            # charged as user code) nor leak the pushed stack entry.
            from lua_mapreduce_tpu.utils.profiling import maybe_annotate
            try:
                ann = maybe_annotate(name)
                ann.__enter__()
            except Exception:
                ann = None      # best-effort bridge: drop, never sink
        try:
            yield span
        except BaseException as exc:
            span.setdefault("attrs", {})["error"] = type(exc).__name__
            raise
        finally:
            stack.pop()
            span["t1"] = self._clock()
            self._record(span)
            if ann is not None:
                try:
                    ann.__exit__(None, None, None)
                except Exception:
                    pass        # span already recorded; bridge only

    # -- flush --------------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    def drain(self) -> List[dict]:
        """Take the buffer without touching a store (tests, collectors
        running in-process)."""
        with self._lock:
            out, self._buf = self._buf, []
            return out

    def flush(self, store, force: bool = True) -> Optional[str]:
        """Publish buffered spans through ``store`` as one JSON-lines
        file ``_trace.<actor>.<seq>``. ``force=False`` is the engines'
        soft cadence: nothing happens below FLUSH_THRESHOLD spans.

        Writes go through the UNWRAPPED innermost store: telemetry must
        not consume FaultPlan occurrences (tracing-on chaos twins stay
        schedule-identical to tracing-off), must not pay retry sleeps,
        and must not trace itself through a TracingStore."""
        with self._lock:
            if not self._buf or (not force
                                 and len(self._buf) < self.FLUSH_THRESHOLD):
                return None
            spans, self._buf = self._buf, []
        from lua_mapreduce_tpu.faults.wrappers import unwrap
        raw = unwrap(store)
        actor = _SAFE_ACTOR.sub("_", self.actor())
        with self._lock:
            seq = self._flush_seq.get(actor, 0)
        name = f"{TRACE_NS}.{actor}.{seq:06d}"
        try:
            # collision probe: a RESTARTED process (resumed server,
            # respawned worker under a fixed --name) starts its counter
            # at 0 again, and builds are atomic OVERWRITING publishes —
            # skipping past existing files keeps the pre-crash
            # timeline instead of silently destroying it
            while raw.exists(name):
                seq += 1
                name = f"{TRACE_NS}.{actor}.{seq:06d}"
            with raw.builder() as b:
                for s in spans:
                    b.write(json.dumps(s, separators=(",", ":"),
                                       default=str) + "\n")
                b.build(name)
        except Exception:
            with self._lock:    # keep the spans; the caller may retry
                self._buf[:0] = spans
            raise
        with self._lock:
            self._flush_seq[actor] = seq + 1
        return name


# --------------------------------------------------------------------------
# process-global install (the faults/wrappers install_fault_plan pattern)
# --------------------------------------------------------------------------

_lock = threading.Lock()
_installed: Optional[Tracer] = None
_generation = 0
_env_tracer: Optional[Tracer] = None

_FALSEY = ("", "0", "off", "false", "no")


def install_tracer(tracer: Optional[Tracer]) -> None:
    """Install (or clear, with None) the process-wide tracer. New store
    and jobstore wrappers built by the router/engines pick it up."""
    global _installed, _generation
    with _lock:
        _installed = tracer
        _generation += 1


def active_tracer() -> Optional[Tracer]:
    """The installed tracer, else one created from ``LMR_TRACE`` (the
    subprocess-fleet channel), else None. The env tracer is memoized —
    one process, one tracer — and deactivates when the variable is
    unset, mirroring the FaultPlan env plumbing."""
    global _env_tracer
    with _lock:
        if _installed is not None:
            return _installed
    import os
    val = (os.environ.get("LMR_TRACE") or "").strip().lower()
    if val in _FALSEY:
        return None
    with _lock:
        if _env_tracer is None:
            _env_tracer = Tracer()
        return _env_tracer


def trace_generation() -> tuple:
    """Wiring-token component: changes whenever the tracing wrapper
    configuration would change (router mem:tag memoization)."""
    import os
    with _lock:
        gen = _generation
    return (gen, os.environ.get("LMR_TRACE") or "")


def utest() -> None:
    """Self-test: deterministic ids, context inheritance, error attrs,
    flush/read round-trip, install plumbing."""
    from lua_mapreduce_tpu.store.memfs import MemStore

    clock_now = [100.0]
    tr = Tracer(clock=lambda: clock_now[0])
    tr.set_actor("w1")
    with tr.span("map.body", ns="map_jobs", job_id=3, attempt=0) as sp:
        clock_now[0] = 101.0
        tr.op("store.build", 100.5, file="result.P0.M3")
    assert sp["t0"] == 100.0 and sp["t1"] == 101.0
    assert sp["sid"] == span_id("w1", "map_jobs", 3, 0, "map.body", 0)
    spans = {s["name"]: s for s in tr.drain()}
    child = spans["store.build"]
    assert child["parent"] == sp["sid"]          # causal link
    assert child["ns"] == "map_jobs" and child["job"] == 3   # inherited
    assert child["attrs"]["file"] == "result.P0.M3"

    # same coordinates twice -> distinct ids via the occurrence counter
    with tr.span("map.body", ns="map_jobs", job_id=3, attempt=0) as sp2:
        pass
    assert sp2["sid"] == span_id("w1", "map_jobs", 3, 0, "map.body", 1)
    assert sp2["sid"] != sp["sid"]

    # a raising body still records its span, tagged with the error
    try:
        with tr.span("reduce.body", ns="red_jobs", job_id=0, attempt=1):
            raise ValueError("boom")
    except ValueError:
        pass
    drained = tr.drain()
    assert drained[-1]["attrs"]["error"] == "ValueError"
    assert drained[-1]["t1"] == clock_now[0]

    # flush/read round-trip through a real store
    store = MemStore()
    tr.op("coord.claim_batch", 99.0, ns="map_jobs")
    name = tr.flush(store)
    assert name and name.startswith(TRACE_NS + ".w1.")
    got = [json.loads(ln) for ln in store.lines(name)]
    assert got[0]["name"] == "coord.claim_batch"
    assert tr.flush(store) is None               # buffer empty
    tr.op("x", 0.0)
    assert tr.flush(store, force=False) is None  # below threshold
    assert tr.pending() == 1

    # restart-collision probe: a FRESH tracer under the same actor
    # (resumed server, respawned worker) must not overwrite the
    # pre-crash flush file — builds are atomic overwriting publishes
    tr_restarted = Tracer(clock=lambda: 200.0)
    tr_restarted.set_actor("w1")
    tr_restarted.op("coord.get_task", 199.0)
    name2 = tr_restarted.flush(store)
    assert name2 != name
    kept = [json.loads(ln) for ln in store.lines(name)]
    assert kept[0]["name"] == "coord.claim_batch"   # survived intact

    # iteration stamping: job ids restart per iteration, so spans
    # carry which iteration they belong to
    tr.set_iteration(3)
    tr.op("y", 1.0)
    assert tr.drain()[-1]["it"] == 3

    # install / active / generation plumbing
    t0 = trace_generation()
    install_tracer(tr)
    try:
        assert active_tracer() is tr
        assert trace_generation() != t0
    finally:
        install_tracer(None)
    import os
    assert (os.environ.get("LMR_TRACE") or active_tracer() is None)
