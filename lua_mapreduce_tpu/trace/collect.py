"""Trace collection: assemble flushed spans into answers (DESIGN §22).

The span layer records and flushes; this module turns the ``_trace.*``
files in a store back into the three artifacts the tentpole promises:

- **per-job lifecycle chains** — claim → body → spill publish → commit
  for every job, with speculation clones, infra releases, and retry
  attempts attached — and a completeness check chaos tests assert
  against (every committed job must have an unbroken chain);
- **per-op latency histograms** — p50/p95/p99/max for every store and
  coord RPC op that ran;
- **Chrome trace-event JSON** — loadable in Perfetto / chrome://tracing
  (and ui.perfetto.dev), one track per worker, so the whole cluster's
  timeline is scrubbable next to a JAX device profile.

Pure functions over span dicts — no engine imports, no clock reads —
so the collector runs identically in-process (tests), from the CLI
(``python -m lua_mapreduce_tpu.trace``), and against a store another
fleet wrote.
"""

from __future__ import annotations

import json
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.trace.span import TRACE_NS

_BODY_SUFFIX = ".body"


def read_spans(store) -> List[dict]:
    """Parse every ``_trace.*`` file in ``store`` (reads go through the
    unwrapped innermost store, like the flushes that wrote them)."""
    from lua_mapreduce_tpu.faults.wrappers import unwrap
    raw = unwrap(store)
    spans: List[dict] = []
    for name in raw.list(f"{TRACE_NS}.*"):
        for line in raw.lines(name):
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    return spans


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an unsorted sequence (q in [0, 100]):
    the smallest value with at least q% of the sample at or below it —
    rank ceil(q/100 · N). (Not round(x + .5): Python rounds half to
    even, so that form overshoots the rank whenever q/100 · N is
    integral — p50 of two samples must be the FIRST, not the second.)"""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = max(0, min(len(vs) - 1, math.ceil(q / 100.0 * len(vs)) - 1))
    return vs[idx]


class TraceCollection:
    """One run's spans, indexed for the three artifact shapes."""

    def __init__(self, spans: Iterable[dict]):
        self.spans = [s for s in spans if s.get("t1") is not None]
        self.by_sid = {s["sid"]: s for s in self.spans}
        # grouped per (iteration, ns, job): namespaces are dropped and
        # re-inserted per task iteration with job ids restarting at 0,
        # so an iteration-blind (ns, job) key would conflate every
        # iteration's reuse of id 0 into one bogus chain ("10 commit
        # spans"). Spans predating the "it" field read as iteration 0.
        self.by_job: Dict[Tuple, List[dict]] = {}
        for s in self.spans:
            if s.get("ns") is not None and s.get("job") is not None:
                self.by_job.setdefault(
                    (s.get("it", 0), s["ns"], s["job"]), []).append(s)
        for group in self.by_job.values():
            group.sort(key=lambda s: (s["t0"], s["t1"]))
        self.last_iteration = max((s.get("it", 0) for s in self.spans),
                                  default=0)

    @classmethod
    def from_store(cls, store) -> "TraceCollection":
        return cls(read_spans(store))

    # -- per-op latency histograms ------------------------------------------

    def op_stats(self) -> Dict[str, dict]:
        """{op name: {count, p50_ms, p95_ms, p99_ms, max_ms, total_s}}
        for every ``store.*`` / ``coord.*`` op span, plus ``dispatch``
        (insert→claim per job, lmr-sched DESIGN §23) so job-dispatch
        latency reports in the same histogram table as the RPCs."""
        buckets: Dict[str, List[float]] = {}
        for s in self.spans:
            name = s["name"]
            if name.startswith(("store.", "coord.")) or name == "dispatch":
                buckets.setdefault(name, []).append(s["t1"] - s["t0"])
        out = {}
        for name, durs in sorted(buckets.items()):
            ms = [d * 1000.0 for d in durs]
            out[name] = {"count": len(ms),
                         "p50_ms": round(percentile(ms, 50), 3),
                         "p95_ms": round(percentile(ms, 95), 3),
                         "p99_ms": round(percentile(ms, 99), 3),
                         "max_ms": round(max(ms), 3),
                         "total_s": round(sum(durs), 4)}
        return out

    def dispatch_stats(self) -> Optional[dict]:
        """The ``dispatch`` histogram row (insert→claim per job) — the
        control plane's dispatch-latency p50/p99, or None for a run
        with no dispatch spans (untraced claims, virtual-clock runs)."""
        return self.op_stats().get("dispatch")

    # -- per-job lifecycle chains -------------------------------------------

    def job_chain(self, ns, job_id, iteration: Optional[int] = None
                  ) -> dict:
        """One job's lifecycle: its claim/body/commit spans plus the
        release/broken/speculation markers, sorted by time.
        ``iteration=None`` picks the LATEST iteration that saw this
        (ns, job) — matching the job-store state a caller just read."""
        if iteration is None:
            its = [it for (it, n, j) in self.by_job
                   if n == ns and j == job_id]
            iteration = max(its) if its else 0
        group = self.by_job.get((iteration, ns, job_id), [])
        return {
            "ns": ns, "job": job_id, "iteration": iteration,
            "claims": [s for s in group if s["name"] == "claim"],
            "bodies": [s for s in group
                       if s["name"].endswith(_BODY_SUFFIX)],
            "commits": [s for s in group if s["name"] == "commit"],
            "releases": [s for s in group
                         if s["name"] == "status.waiting"],
            "broken": [s for s in group if s["name"] == "status.broken"],
            "spec_claims": [s for s in group if s["name"] == "claim"
                            and s.get("attrs", {}).get("speculative")],
            "spec_cancels": [s for s in group
                             if s["name"] == "spec_cancel"],
            "spans": group,
        }

    def check_complete(self, committed: Sequence[Tuple]) -> List[str]:
        """Verify every (ns, job_id) in ``committed`` has an unbroken
        claim → body → commit chain; returns human-readable problems
        (empty = complete). A chain is unbroken when the job has at
        least one claim, at least one body that STARTED no earlier than
        some claim, exactly one commit, and the commit closes no
        earlier than that body started — duplicate executions (retries,
        speculation) legitimately add extra claim/body spans, never
        extra commits."""
        eps = 1e-6
        problems = []
        for ns, jid in committed:
            ch = self.job_chain(ns, jid)
            if not ch["claims"]:
                problems.append(f"{ns}/{jid}: no claim span")
                continue
            if not ch["bodies"]:
                problems.append(f"{ns}/{jid}: no body span")
                continue
            if len(ch["commits"]) != 1:
                problems.append(f"{ns}/{jid}: {len(ch['commits'])} commit "
                                "span(s), expected exactly 1")
                continue
            commit = ch["commits"][0]
            ordered = [b for b in ch["bodies"]
                       if any(c["t0"] <= b["t0"] + eps
                              for c in ch["claims"])
                       and b["t0"] <= commit["t1"] + eps]
            if not ordered:
                problems.append(f"{ns}/{jid}: no body inside the "
                                "claim->commit window")
        return problems

    # -- engine selection (in-graph lowering, DESIGN §26) -------------------

    def lowering_decisions(self) -> List[dict]:
        """The ``lowering`` spans' payloads — the engine-selection
        decision (requested/chosen engine, oracle verdict, per-function
        reasons), the hybrid plane's per-stage ``lowering.<stage>``
        verdicts (DESIGN §28), and any runtime ``ingraph.fallback`` /
        ``hybrid.fallback`` degrades, in time order: the timeline proof
        that an interpreted stage (or a whole store-plane fallback) was
        a DECISION, not a silent drop."""
        out = []
        for s in sorted(self.spans, key=lambda s: (s["t0"], s["t1"])):
            if s["name"] in ("lowering", "ingraph.fallback",
                             "hybrid.fallback") \
                    or s["name"].startswith("lowering."):
                entry = {"span": s["name"], "it": s.get("it", 0),
                         "t0": s["t0"]}
                entry.update(s.get("attrs") or {})
                out.append(entry)
        return out

    def autotune_decisions(self) -> List[dict]:
        """The controller's ``autotune.<knob>`` spans in time order
        (lmr-autotune, DESIGN §29) — every applied knob change with its
        evidence payload (observed metric, the threshold that tripped,
        old→new, direction). This is the explainability contract: a
        perf knob that moved without an entry here moved OUTSIDE the
        controller (operator action or a bug), and the stability
        acceptance (no knob reverses direction more than once per
        chaos window) is checkable straight off this list."""
        out = []
        for s in sorted(self.spans, key=lambda s: (s["t0"], s["t1"])):
            if s["name"].startswith("autotune."):
                entry = {"span": s["name"],
                         "knob": s["name"].split(".", 1)[1],
                         "it": s.get("it", 0), "t0": s["t0"]}
                entry.update(s.get("attrs") or {})
                out.append(entry)
        return out

    def engines_by_iteration(self) -> Dict[int, str]:
        """Which engine actually executed each iteration's data plane:
        ``ingraph`` when the compiled program ran (an ``ingraph.run``
        span), ``store`` when job bodies / phase barriers did. An
        iteration showing BOTH ran in-graph first and degraded mid-
        iteration — it reports as ``store`` (that is where its results
        came from), with the fallback visible in
        :meth:`lowering_decisions`."""
        out: Dict[int, str] = {}
        hybrid_its = set()
        for s in self.spans:
            it = s.get("it", 0)
            if s["name"].endswith(_BODY_SUFFIX) \
                    or s["name"].startswith("phase."):
                out[it] = "store"
            elif s["name"] == "ingraph.run":
                out.setdefault(it, "ingraph")
            elif s["name"] == "hybrid.run":
                # compiled legs ride the store phases (DESIGN §28):
                # the iteration still reports where its results came
                # from, qualified as hybrid rather than pure store
                hybrid_its.add(it)
        return {it: ("hybrid" if out[it] == "store" and it in hybrid_its
                     else out[it]) for it in sorted(out)}

    def speculation_outcomes(self) -> List[dict]:
        """Per speculated (iteration, job): the winner/loser shape of
        its duplicate execution. ``winner`` is the worker whose commit
        landed; ``losers`` are the other workers that ran a body (the
        first-commit-wins casualty, clone or disowned original);
        ``cancelled`` says a spec_cancel span dissolved a shadow lease."""
        out = []
        for (it, ns, jid), group in sorted(self.by_job.items(),
                                           key=lambda kv: str(kv[0])):
            spec_claims = [s for s in group if s["name"] == "claim"
                           and s.get("attrs", {}).get("speculative")]
            if not spec_claims:
                continue
            commits = [s for s in group if s["name"] == "commit"]
            winner = commits[0]["worker"] if commits else None
            bodies = [s for s in group if s["name"].endswith(_BODY_SUFFIX)]
            losers = sorted({b["worker"] for b in bodies
                             if winner is not None
                             and b["worker"] != winner})
            out.append({"iteration": it, "ns": ns, "job": jid,
                        "winner": winner, "losers": losers,
                        "cancelled": any(s["name"] == "spec_cancel"
                                         for s in group),
                        "commit_count": len(commits)})
        return out

    # -- waterfall / phase timing -------------------------------------------

    def _bodies_by_label(self, iteration: Optional[int] = None
                         ) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        for s in self.spans:
            if iteration is not None and s.get("it", 0) != iteration:
                continue
            if s["name"].endswith(_BODY_SUFFIX):
                out.setdefault(s["name"][:-len(_BODY_SUFFIX)],
                               []).append(s)
        return out

    def phase_waterfall(self) -> List[dict]:
        """Per job-label (map / pre_merge / reduce) window + totals,
        from real body spans instead of JobTimes inference."""
        rows = []
        for label, bodies in sorted(self._bodies_by_label().items()):
            t0 = min(b["t0"] for b in bodies)
            t1 = max(b["t1"] for b in bodies)
            rows.append({"phase": label, "jobs": len(bodies),
                         "t0": t0, "t1": t1,
                         "window_s": round(t1 - t0, 4),
                         "busy_s": round(sum(b["t1"] - b["t0"]
                                             for b in bodies), 4)})
        return rows

    def premerge_overlap(self) -> Optional[float]:
        """Fraction of pre-merge body time hidden behind the map phase,
        computed from REAL spans (stats.overlap_fraction's shape, minus
        the JobTimes inference) — over the LAST iteration only: mixing
        iterations would compare pre-merges against another iteration's
        map window. None when either phase is absent."""
        bodies = self._bodies_by_label(self.last_iteration)
        maps, pres = bodies.get("map"), bodies.get("pre_merge")
        if not maps or not pres:
            return None
        map_end = max(b["t1"] for b in maps)
        total = sum(b["t1"] - b["t0"] for b in pres)
        if total <= 0:
            return None
        hidden = sum(max(0.0, min(b["t1"], map_end) - b["t0"])
                     for b in pres)
        return min(1.0, hidden / total)

    def slowest_jobs(self, k: int = 10) -> List[dict]:
        """Top-k jobs by TOTAL body time (duplicate executions summed —
        a straggler's cost includes the clone that covered it)."""
        per_job = []
        for (it, ns, jid), group in self.by_job.items():
            bodies = [s for s in group if s["name"].endswith(_BODY_SUFFIX)]
            if not bodies:
                continue
            per_job.append({
                "iteration": it, "ns": ns, "job": jid,
                "body_s": round(sum(b["t1"] - b["t0"] for b in bodies), 4),
                "executions": len(bodies),
                "workers": sorted({b["worker"] for b in bodies}),
            })
        per_job.sort(key=lambda r: -r["body_s"])
        return per_job[:k]

    # -- Chrome trace-event export ------------------------------------------

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (the Perfetto-loadable subset): one
        complete ("X") event per span in MICROSECONDS, one tid per
        worker with a thread_name metadata record, span attrs + ids in
        ``args``. Times are rebased to the earliest span so the
        timeline starts at 0 regardless of the host clock."""
        if not self.spans:
            return {"traceEvents": [], "displayTimeUnit": "ms"}
        base = min(s["t0"] for s in self.spans)
        tids: Dict[str, int] = {}
        events: List[dict] = []
        for w in sorted({s["worker"] for s in self.spans}):
            tids[w] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tids[w], "args": {"name": w}})
        for s in self.spans:
            args = {"sid": s["sid"], "parent": s.get("parent"),
                    "ns": s.get("ns"), "job": s.get("job"),
                    "attempt": s.get("attempt"), "it": s.get("it")}
            args.update(s.get("attrs") or {})
            events.append({
                "name": s["name"], "ph": "X", "pid": 1,
                "tid": tids[s["worker"]],
                "ts": round((s["t0"] - base) * 1e6, 1),
                "dur": round(max(0.0, s["t1"] - s["t0"]) * 1e6, 1),
                "cat": s["name"].split(".")[0],
                "args": {k: v for k, v in args.items() if v is not None},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_chrome(doc: dict) -> List[str]:
    """Schema check for the exported trace-event JSON (the acceptance
    gate's oracle): required keys, types, non-negative times, metadata
    thread names for every tid. Returns problems (empty = valid)."""
    problems = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    named_tids = set()
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            problems.append(f"event {i}: ph {ph!r} not in (X, M)")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"event {i}: missing name")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            problems.append(f"event {i}: pid/tid must be ints")
        if ph == "M":
            if ev.get("name") == "thread_name":
                named_tids.add(ev.get("tid"))
            continue
        for key in ("ts", "dur"):
            v = ev.get(key)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(f"event {i}: {key}={v!r} not a "
                                "non-negative number")
        if not isinstance(ev.get("args", {}), dict):
            problems.append(f"event {i}: args not a dict")
    for ev in events:
        if ev.get("ph") == "X" and ev.get("tid") not in named_tids:
            problems.append(f"tid {ev.get('tid')} has no thread_name "
                            "metadata")
            break
    return problems


def utest() -> None:
    """Self-test: chain assembly/completeness, histograms, overlap from
    spans, chrome export + schema validation."""
    def sp(name, t0, t1, worker="w", ns="map_jobs", job=0, attempt=0,
           parent=None, it=0, **attrs):
        d = {"sid": f"{name}-{worker}-{job}-{t0}-{it}", "parent": parent,
             "name": name, "worker": worker, "ns": ns, "job": job,
             "attempt": attempt, "it": it, "t0": t0, "t1": t1}
        if attrs:
            d["attrs"] = attrs
        return d

    spans = [
        sp("coord.claim_batch", 0.0, 0.1, ns=None, job=None),
        sp("claim", 0.0, 0.1),
        sp("dispatch", -0.4, 0.1),     # insert→claim (DESIGN §23)
        sp("map.body", 0.2, 1.0),
        sp("store.build", 0.8, 0.9, file="result.P0.M0"),
        sp("commit", 1.1, 1.2),
        # job 1: speculated — original w loses, clone w2 wins
        sp("claim", 0.0, 0.1, job=1),
        sp("map.body", 0.2, 5.0, job=1),
        sp("claim", 2.0, 2.1, job=1, worker="w2", speculative=True),
        sp("map.body", 2.2, 3.0, job=1, worker="w2"),
        sp("commit", 3.0, 3.1, job=1, worker="w2"),
        sp("spec_cancel", 5.0, 5.1, job=1, ns="map_jobs"),
        sp("pre_merge.body", 0.5, 0.9, ns="pre_jobs", job=0),
        # iteration 2 reuses job id 0 (namespaces re-inserted per
        # iteration): its chain must group separately, and the
        # completeness check must judge the LATEST iteration
        sp("claim", 10.0, 10.1, it=2),
        sp("map.body", 10.2, 11.0, it=2),
        sp("commit", 11.1, 11.2, it=2),
    ]
    col = TraceCollection(spans)
    assert col.check_complete([("map_jobs", 0), ("map_jobs", 1)]) == []
    assert col.check_complete([("map_jobs", 7)]) \
        == ["map_jobs/7: no claim span"]
    # per-iteration grouping: job 0 has ONE commit per iteration, never
    # a conflated pair; job_chain defaults to the latest iteration
    assert len(col.job_chain("map_jobs", 0, iteration=0)["commits"]) == 1
    assert col.job_chain("map_jobs", 0)["iteration"] == 2
    assert col.last_iteration == 2
    outcomes = col.speculation_outcomes()
    assert len(outcomes) == 1 and outcomes[0]["winner"] == "w2"
    assert outcomes[0]["losers"] == ["w"]
    assert outcomes[0]["commit_count"] == 1

    ops = col.op_stats()
    assert ops["coord.claim_batch"]["count"] == 1
    assert abs(ops["store.build"]["p50_ms"] - 100.0) < 1e-6
    # dispatch (insert→claim) reports in the same histogram table
    assert abs(col.dispatch_stats()["p50_ms"] - 500.0) < 1e-6
    assert TraceCollection([]).dispatch_stats() is None

    # overlap is computed over the LAST iteration only — iteration 2
    # ran no pre-merge, so the full collection reports None, while a
    # single-iteration collection sees the fully-hidden body (0.5-0.9
    # under a map phase ending at 5.0)
    assert col.premerge_overlap() is None
    col0 = TraceCollection([s for s in spans if s.get("it", 0) == 0])
    assert col0.premerge_overlap() == 1.0
    rows = {r["phase"]: r for r in col.phase_waterfall()}
    assert rows["map"]["jobs"] == 4 and rows["pre_merge"]["jobs"] == 1
    top = col.slowest_jobs(1)
    assert top[0]["job"] == 1 and top[0]["executions"] == 2

    # engine surfacing (DESIGN §26): the lowering decision chain and
    # the per-iteration engine map, mid-run fallback included —
    # iteration 2 starts in-graph, degrades, and finishes on the store
    # plane, so it must report as "store" with the fallback listed
    espans = [
        sp("lowering", -1.0, -0.9, ns="ingraph", job=None,
           engine="ingraph", requested="auto", verdict="in-graph"),
        sp("ingraph.run", 0.0, 1.0, ns="ingraph", job=1, it=1),
        sp("ingraph.fallback", 1.5, 1.5, ns="ingraph", job=None, it=2,
           reason="boom"),
        sp("map.body", 2.0, 3.0, it=2),
    ]
    ecol = TraceCollection(espans)
    assert ecol.engines_by_iteration() == {1: "ingraph", 2: "store"}
    decs = ecol.lowering_decisions()
    assert decs[0]["span"] == "lowering" and decs[0]["engine"] == "ingraph"
    assert decs[1]["span"] == "ingraph.fallback" \
        and decs[1]["reason"] == "boom"
    assert col.lowering_decisions() == []      # untouched runs: empty

    # hybrid stage granularity (DESIGN §28): per-stage lowering.<stage>
    # verdicts and hybrid.fallback degrades join the decision chain, and
    # an iteration whose store phases ran compiled legs reports "hybrid"
    hspans = [
        sp("lowering", -1.0, -0.9, ns="hybrid", job=None,
           engine="hybrid", requested="auto", verdict="store-plane"),
        sp("lowering.map", -0.9, -0.9, ns="hybrid", job=None,
           stage="map", engine="hybrid", compiled="true"),
        sp("lowering.reduce", -0.9, -0.9, ns="hybrid", job=None,
           stage="reduce", engine="store", compiled="false"),
        sp("hybrid.run", 0.0, 0.5, ns="hybrid", job=1, it=1, stage="map"),
        sp("map.body", 0.0, 1.0, it=1),
        sp("hybrid.fallback", 1.5, 1.5, ns="hybrid", job=None, it=2,
           stage="map", reason="trace failed"),
        sp("map.body", 2.0, 3.0, it=2),
    ]
    hcol = TraceCollection(hspans)
    assert hcol.engines_by_iteration() == {1: "hybrid", 2: "store"}
    hdecs = hcol.lowering_decisions()
    assert [d["span"] for d in hdecs] == [
        "lowering", "lowering.map", "lowering.reduce", "hybrid.fallback"]
    assert hdecs[1]["stage"] == "map" and hdecs[1]["compiled"] == "true"
    assert hdecs[3]["reason"] == "trace failed"

    doc = col.to_chrome()
    assert validate_chrome(doc) == []
    assert any(e["ph"] == "M" for e in doc["traceEvents"])
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert min(e["ts"] for e in xs) == 0.0
    assert validate_chrome({"traceEvents": [{"ph": "Z"}]}) != []

    assert percentile([], 50) == 0.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 99) == 4.0
    # exact-rank halves (the banker's-rounding trap): nearest-rank p50
    # of an even sample is the FIRST of the middle pair
    assert percentile([1.0, 2.0], 50) == 1.0
    assert percentile([1, 2, 3, 4, 5, 6], 50) == 3
    assert percentile([1, 2, 3], 0) == 1 and percentile([1, 2, 3], 100) == 3
