"""Multi-tenant scheduling — the fairness half of lmr-sched (DESIGN §23).

The engine's job stores coordinate exactly ONE task: the task document
is a singleton, and every claim scans one set of namespaces. This
module turns one shared store into a multi-tenant control plane:

- :class:`Tenant` — a named share of the store with a fair-share
  ``weight`` and an optional admission quota (``max_pending``);
- :class:`TenantView` — a full JobStore facade for one tenant over the
  shared store: job namespaces are prefixed (``t~<tenant>~<ns>``), the
  task singleton moves into a per-tenant persistent document, and
  admission control runs inside ``insert_jobs``. A stock ``Server`` or
  ``Worker`` pointed at a view runs UNCHANGED — many concurrent tasks
  per store is just many views over it;
- :class:`FairScheduler` — stride scheduling over the tenants: each
  claimed job charges its tenant ``STRIDE_SCALE / weight`` virtual
  time, and the next claim round trip goes to the tenant with the
  LOWEST accumulated pass. Long-run throughput converges to the weight
  ratio, and — the starvation bound — a tenant flooding the store with
  tiny jobs can delay another tenant's next claim by at most one lease
  per scheduling round, never by its whole backlog;
- :class:`FairWorker` — a claim-and-execute loop serving every tenant
  through one pool member: per poll it asks the scheduler for the
  tenant order, delegates to that tenant's (stock, state-isolated)
  inner Worker, and charges the scheduler by jobs actually committed.
  The weighted-fair ordering is therefore applied at the claim entry
  point itself: WHICH tenant's ``claim_batch`` fires next is the
  scheduler's decision, so fairness needs no cooperation from the
  flooding tenant.

Admission control is the backpressure half: ``insert_jobs`` through a
view with ``max_pending`` set refuses (``AdmissionError``, classified
permanent — the retry layer must not burn backoff on a full queue) any
batch that would push the tenant's live jobs past its quota, and the
per-tenant admitted/rejected counters feed the bench and the tests.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import uuid
from typing import Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import (DEFAULT_SLEEP, MAX_JOB_RETRIES,
                                              Status)
from lua_mapreduce_tpu.coord.jobstore import JobStore
from lua_mapreduce_tpu.faults.errors import NoTaskError, PermanentStoreError

TENANT_SEP = "~"
_TENANT_NAME = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")

# live = occupying queue capacity: everything short of the terminal
# states counts against the admission quota
_LIVE_STATES = (Status.WAITING, Status.RUNNING, Status.BROKEN,
                Status.FINISHED)


class AdmissionError(PermanentStoreError):
    """A tenant's insert was refused by its admission quota. Permanent
    by classification: retrying the same insert against a full queue
    is deterministic failure — the submitter must drain or shed."""


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One tenant's scheduling contract: fair-share ``weight`` (claims
    converge to the weight ratio under contention) and an optional
    ``max_pending`` admission quota (live jobs per namespace)."""

    name: str
    weight: float = 1.0
    max_pending: Optional[int] = None

    def __post_init__(self):
        if not _TENANT_NAME.match(self.name):
            raise ValueError(f"tenant name {self.name!r} must match "
                             f"{_TENANT_NAME.pattern}")
        if not (self.weight > 0):
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if self.max_pending is not None and self.max_pending < 1:
            raise ValueError(f"tenant {self.name!r}: max_pending must be "
                             "≥ 1 (or None for unlimited)")


def tenant_ns(tenant: str, ns: str) -> str:
    """Physical namespace of a tenant's logical one. The prefix is
    path-safe (FileJobStore turns namespaces into ``<ns>.idx`` files),
    and ``~`` never appears in engine namespaces."""
    return f"t{TENANT_SEP}{tenant}{TENANT_SEP}{ns}"


class FairScheduler:
    """Stride scheduler: min-pass tenant claims next; each claimed job
    advances its tenant's pass by ``STRIDE_SCALE / weight``. Thread-safe
    — one instance serves a whole in-process pool, so the pool's
    AGGREGATE claim ordering is weighted-fair, not just each member's."""

    STRIDE_SCALE = 1 << 16

    def __init__(self, tenants: Sequence[Tenant]):
        if not tenants:
            raise ValueError("FairScheduler needs at least one tenant")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {sorted(names)}")
        self._lock = threading.Lock()
        self.tenants: Dict[str, Tenant] = {t.name: t for t in tenants}
        self._stride = {t.name: self.STRIDE_SCALE / t.weight
                        for t in tenants}
        self._pass: Dict[str, float] = {t.name: 0.0 for t in tenants}
        self._charged: Dict[str, int] = {t.name: 0 for t in tenants}

    def order(self) -> List[str]:
        """Tenant names, lowest pass first (name-tiebroken so equal
        shares alternate deterministically instead of starving on dict
        order)."""
        with self._lock:
            return sorted(self._pass, key=lambda n: (self._pass[n], n))

    def charge(self, tenant: str, jobs: int = 1) -> None:
        """Account ``jobs`` claimed work against ``tenant``'s share."""
        if jobs <= 0:
            return
        with self._lock:
            self._pass[tenant] += jobs * self._stride[tenant]
            self._charged[tenant] += jobs

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"pass": self._pass[n], "weight":
                        self.tenants[n].weight,
                        "charged": self._charged[n]}
                    for n in self._pass}


class TenantView(JobStore):
    """One tenant's JobStore facade over a shared concrete store.

    Namespace ops delegate with the tenant prefix applied; the task
    singleton lives in a per-tenant persistent document (optimistic
    timestamp CAS — the ``persistent_table`` discipline, so concurrent
    ``update_task`` folds from workers merge instead of clobbering);
    persistent tables are tenant-prefixed; the errors stream stays
    SHARED (one post-mortem log per store) with every entry tagged
    ``tenant``. ``_inner`` keeps :func:`faults.wrappers.unwrap` — and
    therefore the sched wakeup channels — resolving to the shared
    store, so all tenants ride one notify bus.

    Admission: with ``tenant.max_pending`` set, ``insert_jobs`` refuses
    batches that would push the namespace's live jobs past the quota.
    """

    def __init__(self, store, tenant: Tenant,
                 counters: Optional[Dict[str, int]] = None):
        from lua_mapreduce_tpu.faults.wrappers import unwrap
        self._inner = unwrap(store)
        self.tenant = tenant
        self.admission = counters if counters is not None else \
            {"admitted": 0, "rejected": 0}
        self._task_key = f"_task{TENANT_SEP}{tenant.name}"

    def _ns(self, ns: str) -> str:
        return tenant_ns(self.tenant.name, ns)

    # -- task singleton (per-tenant persistent document) -------------------

    def put_task(self, doc: dict) -> None:
        while True:
            cur = self._inner.pt_get(self._task_key)
            ts = cur.get("timestamp") if cur is not None else None
            d = dict(doc)
            d["timestamp"] = (ts or 0) + 1
            if self._inner.pt_cas(self._task_key, ts, d):
                return

    def get_task(self) -> Optional[dict]:
        doc = self._inner.pt_get(self._task_key)
        if doc is None:
            return None
        d = dict(doc)
        d.pop("timestamp", None)
        return d

    def update_task(self, fields: dict) -> None:
        while True:
            cur = self._inner.pt_get(self._task_key)
            if cur is None:
                raise NoTaskError(
                    f"no task document for tenant {self.tenant.name!r}")
            d = dict(cur)
            d.update(fields)
            d["timestamp"] = cur["timestamp"] + 1
            if self._inner.pt_cas(self._task_key, cur["timestamp"], d):
                return

    def delete_task(self) -> None:
        self._inner.pt_delete(self._task_key)

    # -- job queues --------------------------------------------------------

    def insert_jobs(self, ns, docs):
        q = self.tenant.max_pending
        docs = list(docs)
        if q is not None:
            counts = self._inner.counts(self._ns(ns))
            live = sum(counts[s] for s in _LIVE_STATES)
            if live + len(docs) > q:
                self.admission["rejected"] += len(docs)
                raise AdmissionError(
                    f"tenant {self.tenant.name!r}: insert of {len(docs)} "
                    f"job(s) into {ns!r} exceeds max_pending={q} "
                    f"({live} live)", op="insert_jobs", name=ns)
        self.admission["admitted"] += len(docs)
        return self._inner.insert_jobs(self._ns(ns), docs)

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        return self._inner.claim(self._ns(ns), worker, preferred_ids, steal)

    def claim_batch(self, ns, worker, k=1, preferred_ids=None, steal=True):
        return self._inner.claim_batch(self._ns(ns), worker, k,
                                       preferred_ids, steal)

    def commit_batch(self, ns, worker, entries):
        return self._inner.commit_batch(self._ns(ns), worker, entries)

    def release_batch(self, ns, worker, job_ids):
        return self._inner.release_batch(self._ns(ns), worker, job_ids)

    def heartbeat_batch(self, ns, job_ids, worker):
        return self._inner.heartbeat_batch(self._ns(ns), job_ids, worker)

    def heartbeat(self, ns, job_id, worker):
        return self._inner.heartbeat(self._ns(ns), job_id, worker)

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        return self._inner.set_job_status(self._ns(ns), job_id, status,
                                          expect, expect_worker)

    def get_job(self, ns, job_id):
        return self._inner.get_job(self._ns(ns), job_id)

    def jobs(self, ns):
        return self._inner.jobs(self._ns(ns))

    def job_workers(self, ns):
        return self._inner.job_workers(self._ns(ns))

    def set_job_times(self, ns, job_id, times):
        return self._inner.set_job_times(self._ns(ns), job_id, times)

    def counts(self, ns):
        return self._inner.counts(self._ns(ns))

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        return self._inner.scavenge(self._ns(ns), max_retries)

    def requeue_stale(self, ns, older_than_s):
        return self._inner.requeue_stale(self._ns(ns), older_than_s)

    def speculate(self, ns, job_id):
        return self._inner.speculate(self._ns(ns), job_id)

    def claim_spec(self, ns, worker):
        return self._inner.claim_spec(self._ns(ns), worker)

    def cancel_spec(self, ns, job_id, worker):
        return self._inner.cancel_spec(self._ns(ns), job_id, worker)

    def drop_ns(self, ns):
        return self._inner.drop_ns(self._ns(ns))

    # -- shared surfaces ---------------------------------------------------

    def insert_error(self, worker, msg, info=None):
        tagged = dict(info or {})
        tagged.setdefault("tenant", self.tenant.name)
        return self._inner.insert_error(worker, msg, info=tagged)

    def drain_errors(self):
        return self._inner.drain_errors()

    def pt_get(self, name):
        return self._inner.pt_get(f"{self.tenant.name}{TENANT_SEP}{name}")

    def pt_cas(self, name, expected_ts, doc):
        return self._inner.pt_cas(
            f"{self.tenant.name}{TENANT_SEP}{name}", expected_ts, doc)

    def pt_delete(self, name):
        return self._inner.pt_delete(
            f"{self.tenant.name}{TENANT_SEP}{name}")

    def round_counts(self):
        return self._inner.round_counts()

    def classify(self, exc):
        return self._inner.classify(exc)


class FairWorker:
    """One pool member serving EVERY tenant under weighted fair share.

    Each tenant gets its own stock :class:`~engine.worker.Worker` over a
    :class:`TenantView` (state isolation for free: affinity caches,
    duration EWMAs, and release budgets are per-tenant because job ids
    collide across tenants). Per poll, the shared
    :class:`FairScheduler` orders the tenants by accumulated pass and
    the first tenant with claimable work executes — the claim round
    trip itself is what fairness rations. Committed jobs charge the
    scheduler, so a flood tenant's pass races ahead and the barrier
    tenant's next claim arrives within one scheduling round.

    The idle loop rides the sched wakeup channel of the SHARED store
    (capped jittered backoff interrupted by the Waiter), so dispatch
    stays millisecond-class across every tenant.
    """

    # full-poll refresh cadence for tenants the cheap claimable-counts
    # pre-filter skipped: phase flips that create claimable jobs are
    # caught by the filter itself; flips that don't (FINISHED) surface
    # within this many rounds — bounded staleness on the exit path only
    REFRESH_EVERY = 8

    def __init__(self, store, tenants: Sequence[Tenant],
                 name: Optional[str] = None,
                 scheduler: Optional[FairScheduler] = None,
                 verbose: bool = False, **worker_config):
        from lua_mapreduce_tpu.engine.worker import Worker
        self.name = name or f"fair-{uuid.uuid4().hex[:8]}"
        self.store = store
        self.scheduler = scheduler if scheduler is not None \
            else FairScheduler(tenants)
        self.max_iter = int(worker_config.pop("max_iter", 20))
        self.max_sleep = float(worker_config.pop("max_sleep", 20.0))
        self.idle_poll_ms = worker_config.pop("idle_poll_ms", None)
        self._workers: Dict[str, Worker] = {}
        self._views: Dict[str, TenantView] = {}
        self._last_outcome: Dict[str, str] = {}
        self._round = 0
        for t in tenants:
            view = TenantView(store, t)
            w = Worker(view, name=f"{self.name}.{t.name}",
                       verbose=verbose)
            # inner workers never sleep — this loop owns all waiting —
            # and a huge max_iter keeps their own idle budget inert
            w.configure(max_iter=10 ** 9, **worker_config)
            self._views[t.name] = view
            self._workers[t.name] = w

    @property
    def jobs_executed(self) -> int:
        return sum(w.jobs_executed for w in self._workers.values())

    @staticmethod
    def _has_claimable(view: TenantView) -> bool:
        """Cheap pre-filter: index-count scan only (no task-doc read,
        no spec resolution, no payload copies) — the guard that keeps a
        wakeup at N-tenant scale from costing N full polls per pool
        member (the thundering-herd tax the bench exposed). Known
        bounded staleness: a speculation-OPEN straggler is status
        RUNNING, invisible to counts — a FairWorker reaches its
        clone-claim probe only on the periodic refresh round (≤
        REFRESH_EVERY polls late); the detector's retraction path
        already tolerates slow clone pickup."""
        for ns in ("map_jobs", "pre_jobs", "red_jobs"):
            c = view.counts(ns)
            if c[Status.WAITING] or c[Status.BROKEN]:
                return True
        return False

    def poll_once(self) -> str:
        """One fair round: tenants in pass order; the first with
        claimable work (per the cheap pre-filter) gets a full poll,
        executes, and is charged. Tenants with nothing claimable reuse
        their last outcome except on periodic refresh rounds (catching
        FINISHED flips). Aggregate outcome: "executed" the moment any
        tenant ran; "finished" when EVERY tenant's task is finished;
        "wait" when none has a task yet; else "idle"."""
        self._round += 1
        refresh = (self._round % self.REFRESH_EVERY) == 1
        outcomes = []
        for tn in self.scheduler.order():
            w = self._workers[tn]
            cached = self._last_outcome.get(tn)
            if (not refresh and cached is not None
                    and not self._has_claimable(self._views[tn])):
                outcomes.append(cached)
                continue
            before = w.jobs_executed
            out = w.poll_once()
            self._last_outcome[tn] = out if out != "executed" else "idle"
            if out == "executed":
                self.scheduler.charge(tn, max(1, w.jobs_executed - before))
                return "executed"
            outcomes.append(out)
        if outcomes and all(o == "finished" for o in outcomes):
            return "finished"
        if outcomes and all(o == "wait" for o in outcomes):
            return "wait"
        return "idle"

    def execute(self) -> int:
        """Run until ``max_iter`` consecutive quiet (timed-out) idle
        polls or every tenant's task finished. Returns total jobs
        executed. The wait discipline is Worker's exactly
        (sched.jittered_wait — one shared schedule): capped jittered
        backoff that the shared store's "jobs" wakeup channel
        interrupts, with only timed-out waits draining the idle budget
        (a flood tenant's notify traffic must not idle out the pool)."""
        import random

        from lua_mapreduce_tpu.engine.worker import resolve_idle_poll_s
        from lua_mapreduce_tpu.sched.waiter import channel_for, \
            jittered_wait
        waiter = channel_for(self.store, "jobs").waiter()
        cap = resolve_idle_poll_s(self.idle_poll_ms, self.max_sleep)
        rng = random.Random(self.name)
        idle = 0
        sleep = DEFAULT_SLEEP
        while idle < self.max_iter:
            out = self.poll_once()
            if out == "executed":
                idle = 0
                sleep = DEFAULT_SLEEP
                continue
            if out == "finished":
                # EVERY tenant's task is finished: terminal for this
                # pool member whether or not it personally got work —
                # a late joiner must not idle out its whole budget
                # against a completed fleet
                break
            woken, sleep = jittered_wait(waiter, sleep, cap, rng,
                                         floor_s=DEFAULT_SLEEP)
            if not woken:
                idle += 1
        return self.jobs_executed


def dispatch_latencies(store, tenant: str, ns: str = "map_jobs"
                       ) -> List[float]:
    """Per-job dispatch latency (insert→first claim, seconds) of a
    tenant's namespace, read from the job records: ``started_time``
    (the claim stamp) minus ``creation_time`` (the insert stamp).
    Jobs never claimed are skipped. The store-side twin of the
    lmr-trace ``dispatch`` span, for tests/benches that run untraced."""
    from lua_mapreduce_tpu.faults.wrappers import unwrap
    out = []
    for doc in unwrap(store).jobs(tenant_ns(tenant, ns)):
        t0, t1 = doc.get("creation_time"), doc.get("started_time")
        if t0 and t1 and t1 >= t0:
            out.append(t1 - t0)
    return out


def utest() -> None:
    """Self-test: stride ordering converges to the weight ratio,
    admission quotas refuse floods, the tenant view isolates task docs
    and namespaces on the shared store."""
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore, make_job

    heavy, light = Tenant("heavy", weight=3.0), Tenant("light", weight=1.0)
    sched = FairScheduler([heavy, light])
    takes = []
    for _ in range(40):
        t = sched.order()[0]
        takes.append(t)
        sched.charge(t)
    ratio = takes.count("heavy") / max(1, takes.count("light"))
    assert 2.0 <= ratio <= 4.0, takes     # ~3:1 by stride construction

    store = MemJobStore()
    a = TenantView(store, Tenant("a", max_pending=3))
    b = TenantView(store, Tenant("b"))
    a.put_task({"status": "MAP", "spec": {}})
    assert b.get_task() is None            # task singletons are per-tenant
    a.update_task({"iteration": 2})
    assert a.get_task()["iteration"] == 2
    assert "timestamp" not in a.get_task()

    a.insert_jobs("map_jobs", [make_job(f"k{i}", i) for i in range(3)])
    try:
        a.insert_jobs("map_jobs", [make_job("k3", 3)])
    except AdmissionError:
        pass
    else:
        raise AssertionError("quota breach must be refused")
    assert a.admission == {"admitted": 3, "rejected": 1}
    b.insert_jobs("map_jobs", [make_job("x", 0)])    # b is unbounded

    # namespaces are disjoint on the shared store
    doc = a.claim("map_jobs", "w1")
    assert doc is not None and doc["_id"] == 0
    assert b.counts("map_jobs")[Status.WAITING] == 1
    assert store.counts(tenant_ns("a", "map_jobs"))[Status.RUNNING] == 1
    # draining one claimed job makes quota room again
    t5 = {"started": 0.0, "finished": 0.0, "written": 0.0, "cpu": 0.0,
          "real": 0.0}
    assert a.commit_batch("map_jobs", "w1", [(0, t5)]) == [0]
    a.insert_jobs("map_jobs", [make_job("k3", 3)])

    try:
        Tenant("bad~name")
    except ValueError:
        pass
    else:
        raise AssertionError("separator in tenant name must be rejected")
