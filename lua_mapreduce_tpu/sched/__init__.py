"""lmr-sched: event-driven, multi-tenant control plane (DESIGN §23).

Two coupled layers over the existing claim protocol:

- **watch/notify** (:mod:`sched.waiter`) — per-backend wakeup channels
  (in-process event bus, dirmtime cursors, generation-stamped
  conditional reads) behind one :class:`Waiter` abstraction, so job
  inserts and phase flips wake idle pool members in milliseconds while
  the jittered long-interval poll stays as the lost-notification
  fallback;
- **multi-tenancy** (:mod:`sched.tenancy`) — many concurrent tasks per
  store under per-tenant namespaces, with weighted-fair-share claim
  ordering (stride scheduling) and admission quotas, so one tenant's
  many-tiny-jobs flood cannot starve another's barrier;
- **leader lease** (:mod:`sched.lease`) — epoch-fenced CAS lease on the
  store's persistent table plus the :class:`FencedJobStore` mutation
  guard (DESIGN §31), making the coordinator itself replaceable:
  standbys watch the "leader" notify topic and take over mid-phase via
  the server's resume matrix; a fenced zombie can never corrupt state.
"""

from lua_mapreduce_tpu.sched.lease import (FENCED_OPS, LEASE_NAME, STATE_NS,
                                           FencedJobStore, LeaderLease,
                                           default_holder, frame_state,
                                           resolve_lease_ttl, unframe_state)
from lua_mapreduce_tpu.sched.tenancy import (AdmissionError, FairScheduler,
                                             FairWorker, Tenant, TenantView,
                                             dispatch_latencies, tenant_ns)
from lua_mapreduce_tpu.sched.waiter import (Channel, DirChannel, LocalChannel,
                                            NullChannel, NullWaiter,
                                            StoreChannel, Waiter, channel_for,
                                            notify, notify_enabled)

__all__ = [
    "AdmissionError", "FairScheduler", "FairWorker", "Tenant", "TenantView",
    "dispatch_latencies", "tenant_ns",
    "Channel", "DirChannel", "LocalChannel", "NullChannel", "NullWaiter",
    "StoreChannel", "Waiter", "channel_for", "notify", "notify_enabled",
    "LeaderLease", "FencedJobStore", "FENCED_OPS", "LEASE_NAME", "STATE_NS",
    "default_holder", "frame_state", "resolve_lease_ttl", "unframe_state",
]


def utest() -> None:
    from lua_mapreduce_tpu.sched import lease, tenancy, waiter
    waiter.utest()
    tenancy.utest()
    lease.utest()
