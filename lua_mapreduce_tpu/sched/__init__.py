"""lmr-sched: event-driven, multi-tenant control plane (DESIGN §23).

Two coupled layers over the existing claim protocol:

- **watch/notify** (:mod:`sched.waiter`) — per-backend wakeup channels
  (in-process event bus, dirmtime cursors, generation-stamped
  conditional reads) behind one :class:`Waiter` abstraction, so job
  inserts and phase flips wake idle pool members in milliseconds while
  the jittered long-interval poll stays as the lost-notification
  fallback;
- **multi-tenancy** (:mod:`sched.tenancy`) — many concurrent tasks per
  store under per-tenant namespaces, with weighted-fair-share claim
  ordering (stride scheduling) and admission quotas, so one tenant's
  many-tiny-jobs flood cannot starve another's barrier.
"""

from lua_mapreduce_tpu.sched.tenancy import (AdmissionError, FairScheduler,
                                             FairWorker, Tenant, TenantView,
                                             dispatch_latencies, tenant_ns)
from lua_mapreduce_tpu.sched.waiter import (Channel, DirChannel, LocalChannel,
                                            NullChannel, NullWaiter,
                                            StoreChannel, Waiter, channel_for,
                                            notify, notify_enabled)

__all__ = [
    "AdmissionError", "FairScheduler", "FairWorker", "Tenant", "TenantView",
    "dispatch_latencies", "tenant_ns",
    "Channel", "DirChannel", "LocalChannel", "NullChannel", "NullWaiter",
    "StoreChannel", "Waiter", "channel_for", "notify", "notify_enabled",
]


def utest() -> None:
    from lua_mapreduce_tpu.sched import tenancy, waiter
    waiter.utest()
    tenancy.utest()
