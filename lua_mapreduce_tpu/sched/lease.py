"""lmr-ha: epoch-fenced leader lease + hot-standby election (DESIGN §31).

The coordinator was the last single point of failure: workers, shuffle
bytes, and mid-stripe spills all survive SIGKILL (DESIGN §19-§21, §27),
but the server's resume matrix only helps if a human restarts the
process. This module makes coordinator death a *scheduling event*:

- :class:`LeaderLease` — a CAS-acquired lease on the job store's
  persistent table carrying a monotonic **epoch** (the fencing token).
  The lease document is ``{"timestamp": version, "epoch": E, "holder":
  name, "deadline": T}``; every write bumps ``version`` through
  ``pt_cas`` (compare-and-swap on the stored version), so two
  coordinators can never both believe the same write landed. Renewal
  runs on an injectable clock from a stop-event-driven daemon thread
  (the worker-heartbeat idiom); a failed renewal CAS means the lease
  moved under us — the holder is **fenced** and must abdicate, never
  retry.
- :class:`FencedJobStore` — wraps the server's (already retry-wrapped)
  job store and guards every server-side mutation (put_task /
  update_task / insert_jobs / requeue / scavenge / speculate / drop_ns
  / autotune deployments, all of which ride ``update_task``) with the
  lease validity check: the fast path is one clock comparison; past the
  local deadline the holder re-validates with ONE inline renewal CAS,
  and a holder whose lease moved gets a classified permanent
  :class:`StaleLeaderError` — so a zombie coordinator returning from a
  GC pause, SIGSTOP, or partition (the ``slow``/blackout FaultPlan
  kinds simulate all three) can never corrupt job state. Each rejection
  is counted (``fenced_writes``), traced (``leader.fenced``), and
  landed on the errors stream with the epoch/holder evidence for
  post-mortem diagnosis.
- standbys watch the **"leader"** topic of the existing notify bus
  (sched/waiter.py), so takeover is event-driven: a clean release wakes
  the standbys immediately, and a SIGKILLed leader's silence degrades
  to the TTL-bounded timeout probe — takeover latency is bounded by
  ``ttl + probe`` either way, which is what the ha bench's
  ``< 2 × TTL`` acceptance bar measures.

The fencing argument (DESIGN §31 spells it out in full): mutations are
safe while ``clock() < deadline`` — the takeover path cannot acquire
before the deadline, so validity windows of successive epochs never
overlap (up to clock skew, which the TTL margin absorbs). Past its
deadline a holder must win a renewal CAS before mutating; losing that
CAS is proof of a takeover, and the permanent classification makes the
retry layer fail fast instead of backing off into a later corruption.

Loop-state framing: :func:`frame_state` / :func:`unframe_state` are the
CRC-framed encoding of the ``_state.<iteration>`` checkpoint the server
publishes before every FINISHED→WAIT flip, closing the last resume hole
(the "loop" protocol's threaded state used to live purely in server
memory).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import zlib
from typing import Any, Callable, Optional

from lua_mapreduce_tpu.faults.errors import StaleLeaderError

# the lease's persistent-table document name (pt_* plane: one per coord
# store root, like the task document)
LEASE_NAME = "leader"

# the loop-state checkpoint prefix: `_state.<iteration>` sits outside
# every engine namespace (like `_trace.`), so purges of either can never
# touch result bytes
STATE_NS = "_state"

_STATE_MAGIC = b"LMRS1"

DEFAULT_TTL_S = 10.0

_holder_seq = [0]
_holder_lock = threading.Lock()


def default_holder() -> str:
    """A fleet-unique holder name: host.pid.seq — seq disambiguates
    multiple Server instances inside one process (the test fleets)."""
    with _holder_lock:
        _holder_seq[0] += 1
        seq = _holder_seq[0]
    return f"{socket.gethostname()}.{os.getpid()}.{seq}"


def resolve_lease_ttl(arg) -> float:
    """Lease TTL resolution order: explicit argument, else
    ``LMR_LEASE_TTL_S`` env, else :data:`DEFAULT_TTL_S`. Sub-100ms TTLs
    would renew faster than a loaded store round-trips and are
    rejected."""
    if arg is None:
        arg = os.environ.get("LMR_LEASE_TTL_S") or DEFAULT_TTL_S
    ttl = float(arg)
    if ttl < 0.1:
        raise ValueError(f"lease TTL {ttl}s is below the 0.1s floor — "
                         "renewal could not outrun a loaded store")
    return ttl


def frame_state(obj: Any) -> bytes:
    """CRC-framed encoding of a JSON-serializable loop state: magic +
    8-byte big-endian length + payload + crc32(payload). The frame is
    self-validating so a torn write (crashed leader mid-publish) reads
    as corrupt, never as silently-wrong state."""
    from lua_mapreduce_tpu.core.serialize import to_plain
    payload = json.dumps(to_plain(obj), sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return (_STATE_MAGIC + len(payload).to_bytes(8, "big") + payload
            + crc.to_bytes(4, "big"))


def unframe_state(data: bytes) -> Any:
    """Decode + CRC-verify a :func:`frame_state` frame; raises
    ``ValueError`` on any truncation, magic mismatch, or checksum
    failure (the caller treats corrupt state as absent)."""
    if len(data) < len(_STATE_MAGIC) + 12 \
            or not data.startswith(_STATE_MAGIC):
        raise ValueError("loop-state frame: bad magic/truncated header")
    off = len(_STATE_MAGIC)
    n = int.from_bytes(data[off:off + 8], "big")
    payload = data[off + 8:off + 8 + n]
    if len(payload) != n:
        raise ValueError("loop-state frame: truncated payload")
    crc = int.from_bytes(data[off + 8 + n:off + 12 + n], "big")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ValueError("loop-state frame: CRC mismatch")
    return json.loads(payload.decode("utf-8"))


class LeaderLease:
    """One coordinator's handle on the fleet's leader lease.

    ``store`` is a JobStore (wrapped or raw — pt ops delegate through
    the proxy stack); ``clock`` must be a wall clock shared by every
    contender (cross-process deadline comparisons), injectable for
    virtual-time tests. All CAS traffic manages its own ``timestamp``
    version field: ``pt_cas`` compares-and-swaps on the stored version
    and writes the new document verbatim (it never auto-bumps).
    """

    def __init__(self, store, holder: Optional[str] = None,
                 ttl_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time,
                 name: str = LEASE_NAME):
        self.store = store
        self.holder = holder or default_holder()
        self.ttl_s = resolve_lease_ttl(ttl_s)
        self.clock = clock
        self.name = name
        self.epoch = 0              # 0 = never held
        self.took_over = False      # last acquire bumped past a dead leader
        self._version = 0           # the doc version this holder last wrote
        self._deadline = 0.0        # local copy of the renewed deadline
        self._fenced = False
        self._lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- acquisition / renewal ---------------------------------------------

    def _doc(self, version: int, epoch: int, deadline: float) -> dict:
        return {"timestamp": version, "epoch": epoch,
                "holder": self.holder, "deadline": deadline}

    def peek(self) -> Optional[dict]:
        """The stored lease document (None before the first election)."""
        return self.store.pt_get(self.name)

    def try_acquire(self) -> bool:
        """ONE election round: CAS-acquire a free/expired/released lease.
        Returns True with ``epoch``/``took_over`` set on a win; False
        when a live holder keeps the lease. Winning always bumps the
        epoch past the previous holder's — the fencing invariant."""
        now = self.clock()
        cur = self.store.pt_get(self.name)
        with self._lock:
            if cur is None:
                doc = self._doc(1, 1, now + self.ttl_s)
                if not self.store.pt_cas(self.name, None, doc):
                    return False
                self.epoch, self._version = 1, 1
                self.took_over = False
            else:
                released = not cur.get("holder")
                expired = now > float(cur.get("deadline") or 0.0)
                if not released and not expired:
                    return False
                version = int(cur.get("timestamp") or 0)
                epoch = int(cur.get("epoch") or 0) + 1
                doc = self._doc(version + 1, epoch, now + self.ttl_s)
                if not self.store.pt_cas(self.name, version, doc):
                    return False        # lost the election CAS
                self.epoch, self._version = epoch, version + 1
                # a takeover is an acquire over an EXPIRED lease a dead
                # leader never released; clean succession is not one
                self.took_over = expired and not released
            self._deadline = now + self.ttl_s
            self._fenced = False
        self._notify()
        return True

    def renew(self) -> bool:
        """Extend the deadline one TTL via the version CAS. A failed
        CAS means the lease moved under us (takeover) — the holder is
        FENCED from here on; renewal is never retried."""
        with self._lock:
            if self._fenced or self.epoch == 0:
                return False
            now = self.clock()
            doc = self._doc(self._version + 1, self.epoch,
                            now + self.ttl_s)
            try:
                ok = self.store.pt_cas(self.name, self._version, doc)
            except Exception:
                # a store blip mid-renew: the lease may or may not have
                # moved — keep the OLD local deadline (never extend on
                # uncertainty); the next renewal or the fencing check's
                # inline CAS settles it
                return not self._expired_locked(now)
            if not ok:
                self._fenced = True
                return False
            self._version += 1
            self._deadline = now + self.ttl_s
            return True

    def release(self) -> None:
        """Clean abdication: clear the holder and expire the deadline
        (epoch stays — successors still bump past it), then wake the
        standbys. Best-effort: a lost release degrades to the TTL."""
        with self._lock:
            if self._fenced or self.epoch == 0:
                return
            doc = self._doc(self._version + 1, self.epoch, 0.0)
            doc["holder"] = ""
            try:
                self.store.pt_cas(self.name, self._version, doc)
            except Exception:
                pass
            self.epoch = 0
            self._fenced = False
        self._notify()

    # -- validity (the fencing check) ---------------------------------------

    def _expired_locked(self, now: float) -> bool:
        return now >= self._deadline

    def validate(self) -> bool:
        """The per-mutation fencing check. Fast path: one clock
        comparison against the locally-renewed deadline (mutations are
        safe strictly inside the validity window — takeover cannot
        happen before it ends). Past the deadline: ONE inline renewal
        CAS decides — win it and the window reopens; lose it and the
        holder is fenced for good."""
        with self._lock:
            if self._fenced or self.epoch == 0:
                return False
            if not self._expired_locked(self.clock()):
                return True
        return self.renew()

    @property
    def fenced(self) -> bool:
        return self._fenced

    # -- renewal thread ------------------------------------------------------

    def start_renewal(self) -> None:
        """Daemon renewal at ttl/3 cadence (the worker-heartbeat idiom:
        a stop Event both paces and interrupts the wait). Stops itself
        the moment a renewal is fenced."""
        if self._thread is not None and self._thread.is_alive():
            return
        stop = threading.Event()

        def loop():
            while not stop.wait(self.ttl_s / 3.0):
                if not self.renew():
                    return

        self._stop = stop
        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"lease-renew-{self.holder}")
        self._thread.start()

    def stop_renewal(self, release: bool = False) -> None:
        """Stop renewing; with ``release`` also abdicate cleanly.
        ``release=False`` is the simulated-crash path tests use — the
        lease is left to expire exactly as a SIGKILL would leave it."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if release:
            self.release()

    # -- standby side --------------------------------------------------------

    def standby_waiter(self):
        """A cursor on the store's "leader" topic: wakes on acquire /
        release notifications; a lost one times out into the probe."""
        from lua_mapreduce_tpu.sched.waiter import channel_for
        return channel_for(self.store, "leader").waiter()

    def _notify(self) -> None:
        from lua_mapreduce_tpu.sched.waiter import notify
        notify(self.store, "leader")


# the server-side mutation surface the fencing guard covers; reads
# (get_task / jobs / counts / drain_errors / pt_get) and the errors
# stream (workers write it leaderlessly) stay unguarded
FENCED_OPS = ("put_task", "update_task", "delete_task", "insert_jobs",
              "drop_ns", "scavenge", "requeue_stale", "speculate",
              "cancel_spec", "set_job_status")


class FencedJobStore:
    """Epoch-fencing guard over the server's job-store stack.

    Follows the wrapper convention (faults/wrappers.py): ``_inner`` +
    ``__getattr__`` delegation so ``unwrap()`` and non-mutating ops
    pass through untouched. Stacks OUTERMOST — above the retry layer —
    so a fenced rejection fails fast instead of burning the retry
    budget (StaleLeaderError is permanent, so even a mis-stacked guard
    would not be retried)."""

    def __init__(self, inner, lease: LeaderLease):
        self._inner = inner
        self._lease = lease

    def __getattr__(self, attr):
        return getattr(self._inner, attr)

    def _check(self, op: str):
        if self._lease.validate():
            return
        lease = self._lease
        cur = None
        try:
            cur = lease.peek()
        except Exception:
            pass
        cur_epoch = int(cur.get("epoch") or 0) if cur else None
        cur_holder = cur.get("holder") if cur else None
        from lua_mapreduce_tpu.faults.retry import COUNTERS
        COUNTERS.bump("fenced_writes")
        msg = (f"fenced write rejected: {op} by {lease.holder!r} with "
               f"stale epoch {lease.epoch} (current epoch {cur_epoch}, "
               f"holder {cur_holder!r}) — zombie leader abdicates")
        # post-mortem diagnosis (DESIGN §31): the rejection lands on
        # the errors stream with the epoch evidence, through the RAW
        # store — the zombie's diagnostic write must not itself be
        # fenced, retried, or traced
        try:
            from lua_mapreduce_tpu.faults.wrappers import unwrap
            unwrap(self._inner).insert_error(
                lease.holder, msg,
                info={"classification": "fenced-write", "op": op,
                      "epoch": lease.epoch, "current_epoch": cur_epoch,
                      "current_holder": cur_holder})
        except Exception:
            pass
        from lua_mapreduce_tpu.trace.span import active_tracer
        tracer = active_tracer()
        if tracer is not None:
            with tracer.span("leader.fenced", op=op, epoch=lease.epoch,
                             current_epoch=cur_epoch):
                pass
        raise StaleLeaderError(msg, op=op, epoch=lease.epoch,
                               current_epoch=cur_epoch, holder=cur_holder)

    # -- the guarded mutation surface ---------------------------------------

    def put_task(self, doc):
        self._check("put_task")
        return self._inner.put_task(doc)

    def update_task(self, fields):
        self._check("update_task")
        return self._inner.update_task(fields)

    def delete_task(self):
        self._check("delete_task")
        return self._inner.delete_task()

    def insert_jobs(self, ns, docs):
        self._check("insert_jobs")
        return self._inner.insert_jobs(ns, docs)

    def drop_ns(self, ns):
        self._check("drop_ns")
        return self._inner.drop_ns(ns)

    def scavenge(self, ns, max_retries=None):
        self._check("scavenge")
        if max_retries is None:
            return self._inner.scavenge(ns)
        return self._inner.scavenge(ns, max_retries)

    def requeue_stale(self, ns, older_than_s):
        self._check("requeue_stale")
        return self._inner.requeue_stale(ns, older_than_s)

    def speculate(self, ns, job_id):
        self._check("speculate")
        return self._inner.speculate(ns, job_id)

    def cancel_spec(self, ns, job_id, worker):
        self._check("cancel_spec")
        return self._inner.cancel_spec(ns, job_id, worker)

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        self._check("set_job_status")
        return self._inner.set_job_status(ns, job_id, status, expect=expect,
                                          expect_worker=expect_worker)


def utest() -> None:
    """Self-test: election CAS, epoch monotonicity, expiry takeover,
    renewal fencing, the FencedJobStore guard + errors-stream evidence,
    clean-release succession, and the CRC state framing."""
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore

    now = [1000.0]
    clock = lambda: now[0]   # noqa: E731 — shared virtual clock

    store = MemJobStore()
    a = LeaderLease(store, holder="A", ttl_s=10.0, clock=clock)
    b = LeaderLease(store, holder="B", ttl_s=10.0, clock=clock)

    # first election: A wins epoch 1; B loses while A is live
    assert a.try_acquire() and a.epoch == 1 and not a.took_over
    assert not b.try_acquire()
    # renewal extends the deadline through the version CAS
    now[0] += 5.0
    assert a.renew() and a.validate()

    # A goes silent past the TTL: B's acquire is a TAKEOVER, epoch 2
    now[0] += 20.0
    assert b.try_acquire() and b.epoch == 2 and b.took_over
    # the zombie's renewal CAS fails → fenced, and stays fenced
    assert not a.renew() and a.fenced and not a.validate()

    # the fencing guard: B's writes pass, A's raise StaleLeaderError
    fb = FencedJobStore(store, b)
    fa = FencedJobStore(store, a)
    fb.put_task({"_id": "unique", "status": "WAIT", "iteration": 1})
    try:
        fa.update_task({"status": "MAP"})
    except StaleLeaderError as e:
        assert e.epoch == 1 and e.current_epoch == 2
        assert e.transient is False
    else:
        raise AssertionError("zombie write must be fenced")
    # the rejection landed on the errors stream with the evidence
    errs = store.drain_errors()
    assert any(e.get("classification") == "fenced-write"
               and e.get("current_epoch") == 2
               and e.get("epoch") == 1 for e in errs), errs
    # reads delegate unguarded even for the zombie
    assert fa.get_task()["status"] == "WAIT"

    # clean release: successor bumps the epoch but it is NOT a takeover
    b.release()
    c = LeaderLease(store, holder="C", ttl_s=10.0, clock=clock)
    assert c.try_acquire() and c.epoch == 3 and not c.took_over

    # validate() past the local deadline re-validates via ONE inline
    # renewal CAS (the window reopens when nobody took over)
    now[0] += 15.0
    assert c.validate() and not c.fenced

    # CRC framing round-trip + corruption detection
    state = {"centroids": [[1.0, 2.0], [3.0, 4.0]], "iter": 7}
    buf = frame_state(state)
    assert unframe_state(buf) == state
    for bad in (buf[:-1], b"XXXX" + buf[4:],
                buf[:-2] + bytes([buf[-2] ^ 1, buf[-1]])):
        try:
            unframe_state(bad)
        except ValueError:
            pass
        else:
            raise AssertionError("corrupt frame must not decode")

    # TTL resolution: env fallback + the floor
    assert resolve_lease_ttl(2.5) == 2.5
    try:
        resolve_lease_ttl(0.01)
    except ValueError:
        pass
    else:
        raise AssertionError("sub-floor TTL must be rejected")
