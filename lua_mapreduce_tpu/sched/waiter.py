"""Watch/notify wakeup primitives — the event half of lmr-sched (DESIGN §23).

The control plane this replaces is pure polling: an idle worker sleeps a
fixed interval and re-scans the claim surface, so dispatch latency is
bounded below by the poll period and a large idle fleet burns claim
scans discovering nothing. This module gives every backend a cheap
wakeup channel instead:

- **memfs / in-process pools** — a condition-variable event bus keyed by
  the shared job-store instance: ``notify`` is one predicate bump plus a
  broadcast, wakeups are sub-millisecond.
- **sharedfs / FileJobStore** — a directory-mtime CURSOR: ``notify``
  appends one byte to a per-topic wake file; waiters probe that single
  inode's ``(size, mtime_ns)`` signature on a short ramping interval.
  One ``stat`` is orders of magnitude cheaper than a claim scan (flock +
  record read + payload-cache resolution), which is what makes
  millisecond-class dispatch affordable across processes and NFS hosts.
- **objectfs / fake-GCS** — a GENERATION-STAMPED conditional read: the
  producer PUTs a tiny ``_sched.<topic>.wake`` object carrying a fresh
  generation token; waiters re-read it and wake when the token moved
  past their cursor. Maps 1:1 onto the object contract (no append, no
  rename) and onto a real bucket's metadata reads.

Degradation ladder (the contract every engine caller relies on):

1. notification arrives → the waiter returns True within one probe
   interval (in-process: immediately);
2. notification LOST (crashed producer, dropped wake write, cleared
   generation) → the wait times out and the caller falls back to
   exactly today's poll — degraded latency, never a hang. The protocol
   model checker enumerates this edge exhaustively
   (``ModelConfig(allow_notify=True)``, analysis/protocol.py);
3. notify disabled (``LMR_SCHED_NOTIFY=0``) → :class:`NullChannel`
   everywhere: waits are plain sleeps, behavior byte-identical to the
   pre-sched engine.

A STALE or duplicate wakeup is always a no-op by construction: the
woken caller re-polls the claim surface, finds nothing, and goes back
to waiting — wakeups carry no payload, so there is nothing to get
wrong. Clocks and sleeps are injectable throughout (the faults/retry.py
convention); lint rule LMR011 keeps every engine/coord wait on this
module instead of bare ``time.sleep``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable, Dict, Optional

# probe ramp for the polling-cursor waiters: start fine (milliseconds —
# the dispatch-latency budget), back off geometrically to a cap so a
# long timeout costs tens of probes, not thousands
PROBE_MIN_S = 0.002
PROBE_MAX_S = 0.05
PROBE_GROWTH = 1.6

_FALSEY = ("", "0", "off", "false", "no")


def notify_enabled() -> bool:
    """The fleet-wide off switch: ``LMR_SCHED_NOTIFY=0`` (or any falsey
    value) degrades every channel to :class:`NullChannel` — waits become
    plain sleeps and the engine is byte-identical to the pre-sched
    polling plane. Unset/truthy = on (the default)."""
    val = os.environ.get("LMR_SCHED_NOTIFY")
    if val is None:
        return True
    return val.strip().lower() not in _FALSEY


class Waiter:
    """One consumer's view of a wakeup channel.

    ``wait(timeout_s)`` blocks until a notification lands (True) or the
    timeout elapses (False — the poll-fallback signal). The cursor is
    per-waiter: a notification that fired BETWEEN two waits is consumed
    by the next ``wait`` immediately, so the poll-then-arm race window
    (checked the claim surface, found nothing, notification fired
    before the wait was armed) can never lose a wakeup.

    ``can_notify`` is False only for :class:`NullWaiter` — engine
    callers gate their jittered-backoff behavior on it so the notify-off
    path keeps the exact legacy sleep schedule.
    """

    can_notify = True

    def wait(self, timeout_s: float) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        """Release waiter resources. Idempotent; default: nothing."""


class NullWaiter(Waiter):
    """Pure-sleep fallback (notify off / unknown store). This is THE
    one sanctioned sleep site for engine/coord wait paths (LMR011):
    the sleep function is injectable for virtual-clock tests."""

    can_notify = False

    def __init__(self, sleep: Callable[[float], None] = time.sleep):
        self._sleep = sleep

    def wait(self, timeout_s: float) -> bool:
        if timeout_s > 0:
            self._sleep(timeout_s)
        return False


class _CondWaiter(Waiter):
    """In-process waiter over a shared (condition, generation) pair."""

    def __init__(self, channel: "LocalChannel"):
        self._channel = channel
        with channel._cond:
            self._seen = channel._gen

    def wait(self, timeout_s: float) -> bool:
        ch = self._channel
        with ch._cond:
            if ch._gen != self._seen:
                self._seen = ch._gen       # pending notify: consume now
                return True
            ch._cond.wait(timeout=max(0.0, timeout_s))
            woken = ch._gen != self._seen
            self._seen = ch._gen
            return woken


class _CursorWaiter(Waiter):
    """Shared ramping-probe loop for the file/object cursor waiters:
    subclasses supply ``_signature()`` — a cheap token that changes on
    every notify (stat signature, generation stamp). A probe that
    errors reads as "unchanged": storage weather degrades to the poll
    fallback, never to a raised wait."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self._clock = clock
        self._sleep = sleep
        self._seen = self._probe()

    def _signature(self):
        raise NotImplementedError

    def _probe(self):
        try:
            return self._signature()
        except Exception:
            return None

    def wait(self, timeout_s: float) -> bool:
        deadline = self._clock() + max(0.0, timeout_s)
        probe = PROBE_MIN_S
        while True:
            sig = self._probe()
            if sig != self._seen:
                self._seen = sig
                return True
            remaining = deadline - self._clock()
            if remaining <= 0:
                return False
            self._sleep(min(probe, remaining))
            probe = min(probe * PROBE_GROWTH, PROBE_MAX_S)


class _FileCursorWaiter(_CursorWaiter):
    """Dirmtime cursor over one wake file (sharedfs / FileJobStore)."""

    def __init__(self, path: str, **kw):
        self._path = path
        super().__init__(**kw)

    def _signature(self):
        try:
            st = os.stat(self._path)
        except OSError:
            return None
        return (st.st_size, st.st_mtime_ns)


class _StoreCursorWaiter(_CursorWaiter):
    """Generation-stamped conditional read over an object store."""

    def __init__(self, channel: "StoreChannel", **kw):
        self._channel = channel
        super().__init__(**kw)

    def _signature(self):
        return self._channel._read_generation()


# --------------------------------------------------------------------------
# channels (the producer side; waiters are minted from them)
# --------------------------------------------------------------------------


class Channel:
    """A named wakeup topic: ``notify`` on the producer side, ``waiter``
    mints a consumer cursor. ``notify`` is best-effort by contract — a
    failed notification is a LOST one, and the waiter's timeout fallback
    absorbs it (degradation rung 2)."""

    can_notify = True

    def notify(self) -> None:
        raise NotImplementedError

    def waiter(self, clock: Callable[[], float] = time.monotonic,
               sleep: Callable[[float], None] = time.sleep) -> Waiter:
        raise NotImplementedError


class NullChannel(Channel):
    """Notify disabled: producers no-op, waiters plain-sleep."""

    can_notify = False

    def notify(self) -> None:
        pass

    def waiter(self, clock=time.monotonic, sleep=time.sleep) -> Waiter:
        return NullWaiter(sleep)


class LocalChannel(Channel):
    """In-process event bus: one condition + generation counter shared
    by every waiter minted from this channel."""

    def __init__(self):
        self._cond = threading.Condition()
        self._gen = 0

    def notify(self) -> None:
        with self._cond:
            self._gen += 1
            self._cond.notify_all()

    def waiter(self, clock=time.monotonic, sleep=time.sleep) -> Waiter:
        return _CondWaiter(self)


class DirChannel(Channel):
    """Wake file in a shared directory. ``notify`` appends ONE byte
    (O_APPEND writes this small are atomic), so the file's
    ``(size, mtime_ns)`` signature strictly advances — the cursor the
    waiters watch. Notifications are low-rate (phase flips, inserts,
    lease retirements), so growth is bytes per task, not per poll."""

    def __init__(self, path: str):
        self.path = path

    def notify(self) -> None:
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o666)
            try:
                os.write(fd, b".")
            finally:
                os.close(fd)
        except OSError:
            pass        # lost notification: the timeout fallback covers it

    def waiter(self, clock=time.monotonic, sleep=time.sleep) -> Waiter:
        return _FileCursorWaiter(self.path, clock=clock, sleep=sleep)


class StoreChannel(Channel):
    """Generation-stamped wake object through any :class:`Store`
    (objectfs local emulation, real/fake GCS, memfs). ``notify`` PUTs a
    fresh monotonic generation token; waiters conditionally re-read it.
    IO goes through the UNWRAPPED innermost store (the trace-flush
    rule): wakeup traffic must not consume FaultPlan occurrences, pay
    retry backoff, or trace itself."""

    def __init__(self, store, name: str):
        from lua_mapreduce_tpu.faults.wrappers import unwrap
        self._store = unwrap(store)
        self._name = name
        self._lock = threading.Lock()
        self._counter = 0

    def notify(self) -> None:
        with self._lock:
            self._counter += 1
            token = f"{time.time_ns()}.{os.getpid()}.{self._counter}"
        try:
            with self._store.builder() as b:
                b.write(token)
                b.build(self._name)
        except Exception:
            pass        # lost notification: the timeout fallback covers it

    def _read_generation(self) -> Optional[str]:
        try:
            if not self._store.exists(self._name):
                return None
            return self._store.read_range(self._name, 0, 64).decode(
                "latin-1")
        except Exception:
            return None

    def waiter(self, clock=time.monotonic, sleep=time.sleep) -> Waiter:
        return _StoreCursorWaiter(self, clock=clock, sleep=sleep)


# --------------------------------------------------------------------------
# routing: store/jobstore instance -> channel, per topic
# --------------------------------------------------------------------------

# topics keep producer/consumer traffic separated so commit-completion
# notifies (the server's barrier wakeup) never wake the idle-worker
# fleet into pointless claim scans, and vice versa:
#   "jobs" — claimable work appeared (inserts, releases, requeues,
#            broken marks, speculation opens, task phase flips);
#            workers wait on it
#   "done" — lease retirements landed (commits); the server's barrier
#            poll waits on it
#   "leader" — the leader lease moved (acquire / renew-expiry window /
#            release / takeover); HA standby coordinators wait on it so
#            takeover is event-driven, not polled (DESIGN §31). A lost
#            notification degrades to the standby's TTL-bounded timeout
#            probe, same ladder as every other topic.
TOPICS = ("jobs", "done", "leader")

WAKE_PREFIX = "_sched"          # object names: _sched.<topic>.wake

# in-process channels keyed by the concrete store instance (weak: a
# dropped store must not pin its bus), then by topic
_local_channels: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_local_lock = threading.Lock()
_NULL = NullChannel()


def _local_channel(store, topic: str) -> LocalChannel:
    with _local_lock:
        by_topic: Optional[Dict[str, LocalChannel]] = \
            _local_channels.get(store)
        if by_topic is None:
            by_topic = {}
            _local_channels[store] = by_topic
        ch = by_topic.get(topic)
        if ch is None:
            ch = by_topic[topic] = LocalChannel()
        return ch


def channel_for(store, topic: str = "jobs") -> Channel:
    """The wakeup channel of a job store (or data store), routed by
    backend:

    - ``MemJobStore`` / ``MemStore`` → the in-process event bus;
    - ``FileJobStore`` → a dirmtime cursor in its coord root;
    - ``SharedStore`` → a dirmtime cursor in its directory;
    - ``ObjectStore`` (local or gs://) → a generation-stamped wake
      object;
    - anything else, or ``LMR_SCHED_NOTIFY`` off → :class:`NullChannel`.

    Wrapper stacks (retry/tracing/injection, tenant views) are unwrapped
    first, so every participant sharing one concrete store shares one
    bus."""
    if topic not in TOPICS:
        raise ValueError(f"unknown sched topic {topic!r}; use {TOPICS}")
    if not notify_enabled():
        return _NULL
    from lua_mapreduce_tpu.coord.filestore import FileJobStore
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore
    from lua_mapreduce_tpu.faults.wrappers import unwrap
    from lua_mapreduce_tpu.store.memfs import MemStore
    from lua_mapreduce_tpu.store.objectfs import ObjectStore
    from lua_mapreduce_tpu.store.sharedfs import SharedStore
    raw = unwrap(store)
    if isinstance(raw, (MemJobStore, MemStore)):
        return _local_channel(raw, topic)
    if isinstance(raw, FileJobStore):
        return DirChannel(os.path.join(raw.root,
                                       f"{WAKE_PREFIX}.{topic}.wake"))
    if isinstance(raw, SharedStore):
        return DirChannel(os.path.join(raw.path,
                                       f".{WAKE_PREFIX}.{topic}.wake"))
    if isinstance(raw, ObjectStore):
        return StoreChannel(raw, f"{WAKE_PREFIX}.{topic}.wake")
    return _NULL


def notify(store, topic: str = "jobs") -> None:
    """Fire-and-forget producer hook: bump ``store``'s channel for
    ``topic``. Never raises — a lost notification degrades to the
    consumer's poll fallback by design."""
    try:
        channel_for(store, topic).notify()
    except Exception:
        pass


def jittered_wait(waiter: Waiter, sleep_s: float, cap_s: float, rng,
                  floor_s: float = 0.1):
    """ONE idle-backoff step, shared by every engine idle loop (Worker
    and FairWorker must not drift apart on the jitter/growth schedule
    DESIGN §23 documents): wait up to ``sleep_s`` — jittered by
    rng.uniform(0.6, 1.0) when the waiter is notify-capable and the
    interval exceeds the floor, so an idle fleet's fallback polls
    de-synchronize; the notify-off path keeps the exact legacy
    schedule. Returns ``(woken, next_sleep_s)``: a wakeup resets the
    backoff to the floor (re-poll promptly), a timeout grows it 1.5x
    toward ``cap_s``."""
    timeout = sleep_s
    if waiter.can_notify and timeout > floor_s:
        timeout *= rng.uniform(0.6, 1.0)
    woken = waiter.wait(timeout)
    return woken, (floor_s if woken else min(sleep_s * 1.5, cap_s))


def utest() -> None:
    """Self-test: cursor semantics (pending notify consumed, lost
    notify times out, stale wake absorbed) on the local and dir
    channels, plus routing and the off switch."""
    import tempfile

    from lua_mapreduce_tpu.coord.jobstore import MemJobStore

    # local bus: notify between waits is consumed by the NEXT wait
    ch = LocalChannel()
    w = ch.waiter()
    ch.notify()
    assert w.wait(0.0) is True          # pending: no block needed
    assert w.wait(0.0) is False         # consumed: nothing new
    # cross-thread wake
    got = []
    t = threading.Thread(target=lambda: got.append(w.wait(5.0)))
    t.start()
    time.sleep(0.02)
    ch.notify()
    t.join(timeout=5.0)
    assert got == [True]

    # dir channel: signature cursor over the wake file
    with tempfile.TemporaryDirectory() as d:
        dch = DirChannel(os.path.join(d, "t.wake"))
        dw = dch.waiter()
        assert dw.wait(0.01) is False   # no notify: timeout fallback
        dch.notify()
        assert dw.wait(1.0) is True
        assert dw.wait(0.01) is False   # stale wake consumed exactly once
        # a waiter created AFTER existing notifies absorbs them as its
        # baseline (pre-history is not a wakeup)
        dch.notify()
        fresh = dch.waiter()
        assert fresh.wait(0.01) is False

    # routing + off switch
    js = MemJobStore()
    a, b = channel_for(js, "jobs"), channel_for(js, "jobs")
    assert a is b and isinstance(a, LocalChannel)
    assert channel_for(js, "done") is not a
    prev = os.environ.get("LMR_SCHED_NOTIFY")
    os.environ["LMR_SCHED_NOTIFY"] = "0"
    try:
        assert isinstance(channel_for(js, "jobs"), NullChannel)
        assert not channel_for(js, "jobs").can_notify
    finally:
        if prev is None:
            os.environ.pop("LMR_SCHED_NOTIFY", None)
        else:
            os.environ["LMR_SCHED_NOTIFY"] = prev
    try:
        channel_for(js, "bogus")
    except ValueError:
        pass
    else:
        raise AssertionError("unknown topic must be rejected")
