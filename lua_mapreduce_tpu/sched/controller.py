"""Self-tuning feedback controller (lmr-autotune, DESIGN §29).

Every performance knob this codebase grew — batch-lease size (§16),
push-buffer budget (§24), the straggler factor (§21), the retry
backoff base (§19), the fleet size itself — shipped as a hand-set
default plus an env var. This module closes the loop: a small
deterministic controller rides the Server's housekeeping cadence (and
the LocalExecutor's per-iteration mirror), consumes the same live
signal streams the operator would read (FaultCounters deltas,
round-count deltas, the task doc's fleet duration EWMA, queue depths),
and adapts the knobs it owns through the EXISTING task-doc negotiation
— the controller writes the doc, the fleet follows the doc, exactly
like a human retuning a deployment mid-run, only every tick.

Design rules (the stability argument, DESIGN §29):

- **Hysteresis bands.** Every knob has a raise threshold and a lower
  threshold separated by a wide dead band; a metric wandering inside
  the band changes nothing. Thresholds are on RATIOS (claim overhead
  over body time, wasted seconds over useful seconds), so they need no
  per-deployment calibration.
- **Per-knob cooldowns.** After a change, a knob is frozen for
  ``cooldown_s`` — at the housekeeping cadence one decision's effect
  (a doc write the fleet follows on its next poll) must be observable
  before the next decision, or the controller chases its own wake.
- **Flip lockout.** A knob may keep moving in one direction, but once
  it has REVERSED direction it may not reverse again until
  ``flip_reset_s`` of quiet — this is what makes "no knob changes
  direction more than once across a chaos window" a structural
  guarantee instead of a tuning accident.
- **Explainable decisions.** Every applied change emits an
  ``autotune.<knob>`` trace span carrying the evidence: the observed
  metric, the threshold that tripped, and old→new. Suppressed changes
  (cooldown / flip lockout) are counted (``autotune_vetoes``), so the
  stats stream shows restraint as well as action.
- **Semantics-neutral.** The controller only touches perf knobs whose
  every legal value is byte-identical on output (batch_k, push budget,
  speculation factor, retry base, fleet size); it never touches the
  crash-consistency knobs (pipeline/push/replication/coding/engine).

The elastic half writes a ``fleet_target`` onto the task doc and calls
an optional owner-provided hook; ``FleetSupervisor`` (below) is the
hook for thread/subprocess fleets — it grows the pool toward the
target and retires surplus members GRACEFULLY (a retiring worker stops
claiming after its current lease commits, so no lease is ever lost to
a scale-down; analysis/protocol.py enumerates exactly this edge).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence


def resolve_autotune(arg) -> bool:
    """The autotune knob's shared resolution order: explicit argument,
    else ``LMR_AUTOTUNE`` env, else off."""
    if arg is None:
        import os
        raw = (os.environ.get("LMR_AUTOTUNE") or "").strip().lower()
        return raw in ("1", "true", "yes", "on")
    return bool(arg)


# The knobs the controller owns once autotune is on, and how each is
# applied. This registry is what the docs' knob table, the LMR018 lint
# rule, and the worker-side doc-follow gate all reference — ONE list,
# so "controller-owned" cannot drift between the layers.
#   batch_k        — task doc (workers already follow doc batch_k)
#   push_budget_mb — task doc (workers follow it under the autotune
#                    marker; re-budgets live BufferPools in place)
#   speculation    — task doc (workers already follow doc speculation)
#   retry_base_ms  — configure_retry() locally + task doc (workers
#                    apply it under the autotune marker)
#   fleet          — fleet_target on the task doc + the owner's hook
CONTROLLER_KNOBS = ("batch_k", "push_budget_mb", "speculation",
                    "retry_base_ms", "fleet")


@dataclasses.dataclass(frozen=True)
class Decision:
    """One applied knob change and its evidence — the span payload."""
    knob: str
    metric: str
    observed: float
    threshold: float
    old: float
    new: float

    @property
    def direction(self) -> int:
        return 1 if self.new > self.old else -1


@dataclasses.dataclass
class Observation:
    """One control window's signals, gathered by the owner (Server
    housekeeping pass / LocalExecutor iteration end). Counter fields
    are DELTAS over the window; ``None`` means the signal is not
    available in this owner (its knobs simply hold)."""
    t: float
    body_ewma_s: Optional[float] = None   # fleet job-body duration EWMA
    rpc_p99_s: Optional[float] = None     # coord round-trip p99 (claim
    #                                       overhead proxy, same store)
    jobs_done: int = 0
    claim_rounds: int = 0
    push_frames: int = 0
    push_evictions: int = 0
    spec_launched: int = 0
    spec_wins: int = 0
    spec_wasted_s: float = 0.0
    store_retries: int = 0
    waiting: int = 0                      # claimable backlog (jobs)
    running: int = 0
    fleet: Optional[int] = None           # current worker count


@dataclasses.dataclass
class AutotuneConfig:
    """Bands, cooldowns, and bounds. Defaults are deliberately
    conservative (wide dead bands, halving/doubling steps); tests and
    benches override cooldowns to match their compressed clocks."""
    cooldown_s: float = 2.0
    flip_reset_s: float = 60.0
    # batch_k: claim round-trip p99 over body EWMA. Above the raise
    # band the control plane dominates tiny jobs → double k; below the
    # lower band jobs are long enough that wide leases only hurt
    # stealability → halve back toward 1. The [0.1, 1.0] dead band is
    # 10x wide.
    batch_ratio_hi: float = 1.0
    batch_ratio_lo: float = 0.1
    batch_k_max: int = 64
    # push budget: evictions per window. Any sustained eviction burst
    # grows the pool ×1.5; ``shrink_after`` consecutive eviction-free
    # windows decay it ×0.75 back toward the configured floor.
    evict_burst: int = 4
    shrink_after: int = 5
    push_budget_max_mb: float = 512.0
    # speculation factor: wasted duplicate seconds over useful job
    # seconds. Above the band the detector clones too eagerly → raise
    # the factor (clone later); a near-zero waste WITH wins → lower it
    # toward ``speculation_min`` (cloning earlier is paying off).
    waste_frac_hi: float = 0.5
    waste_frac_lo: float = 0.05
    speculation_min: float = 1.5
    speculation_max: float = 6.0
    # retry backoff base: transient faults per second. A dense fault
    # burst doubles the base (back off harder, stop hammering a
    # browning-out store); ``shrink_after`` quiet windows halve it
    # back toward the configured floor.
    fault_rate_hi: float = 2.0
    retry_base_max_ms: float = 400.0
    # elastic fleet: target draining the claimable backlog within
    # ``drain_target_s``. Scale up only when the projected drain time
    # exceeds 1.5x the target (hysteresis); retire to baseline after
    # ``shrink_after`` consecutive empty-queue windows.
    drain_target_s: float = 10.0
    fleet_max: int = 8


class _Knob:
    """Per-knob change gate: cooldown + flip lockout + change log."""

    def __init__(self, name: str, value: float, cooldown_s: float,
                 flip_reset_s: float):
        self.name = name
        self.value = value
        self.cooldown_s = cooldown_s
        self.flip_reset_s = flip_reset_s
        self.changed_at: Optional[float] = None
        self.last_dir = 0
        self.flipped = False          # reversed direction once already

    def gate(self, now: float, direction: int) -> Optional[str]:
        """None = the change may proceed; else the veto reason."""
        if self.changed_at is not None:
            if now - self.changed_at < self.cooldown_s:
                return "cooldown"
            if now - self.changed_at >= self.flip_reset_s:
                # a long quiet period re-arms the flip budget: the
                # regime that caused the reversal is long gone
                self.flipped = False
        if self.last_dir and direction != self.last_dir:
            if self.flipped:
                return "flip-lockout"
        return None

    def commit(self, now: float, new: float, direction: int) -> None:
        if self.last_dir and direction != self.last_dir:
            self.flipped = True
        self.last_dir = direction
        self.changed_at = now
        self.value = new


class AutotuneController:
    """The decision core. Owns per-knob state and the evidence plumbing
    (spans + counters); the OWNER gathers the :class:`Observation` and
    applies the returned :class:`Decision` list through its own
    mechanisms (task-doc writes, ``configure_retry``, pool resize,
    fleet hook). Knobs whose initial value is ``None`` are disabled —
    an owner with no push pool never tunes the push budget."""

    def __init__(self, *, batch_k: Optional[int] = None,
                 push_budget_mb: Optional[float] = None,
                 speculation: Optional[float] = None,
                 retry_base_ms: Optional[float] = None,
                 fleet: Optional[int] = None,
                 fleet_max: Optional[int] = None,
                 config: Optional[AutotuneConfig] = None,
                 clock: Callable[[], float] = time.time):
        self.cfg = config or AutotuneConfig()
        self.clock = clock
        cd, fr = self.cfg.cooldown_s, self.cfg.flip_reset_s
        self._knobs: Dict[str, _Knob] = {}
        if batch_k is not None:
            self._knobs["batch_k"] = _Knob("batch_k", int(batch_k), cd, fr)
        if push_budget_mb is not None:
            self._push_floor = float(push_budget_mb)
            self._knobs["push_budget_mb"] = _Knob(
                "push_budget_mb", float(push_budget_mb), cd, fr)
        if speculation is not None and speculation > 0:
            self._knobs["speculation"] = _Knob(
                "speculation", float(speculation), cd, fr)
        if retry_base_ms is not None:
            self._retry_floor = float(retry_base_ms)
            self._knobs["retry_base_ms"] = _Knob(
                "retry_base_ms", float(retry_base_ms), cd, fr)
        if fleet is not None:
            self._fleet_floor = int(fleet)
            if fleet_max is not None:
                self.cfg = dataclasses.replace(self.cfg,
                                               fleet_max=int(fleet_max))
            self._knobs["fleet"] = _Knob("fleet", int(fleet), cd, fr)
        self._rpc_samples: deque = deque(maxlen=128)
        self._quiet_evict = 0
        self._quiet_fault = 0
        self._quiet_queue = 0
        self.decisions: List[Decision] = []    # full history, evidence

    # -- signal helpers -----------------------------------------------------

    def note_rpc(self, seconds: float) -> None:
        """Feed one coordination round-trip latency sample (the owner
        times its own store RPCs — same store, same path as claims, so
        the rolling p99 is an honest claim-overhead proxy without
        requiring tracing to be on)."""
        if seconds >= 0:
            self._rpc_samples.append(seconds)

    def rpc_p99(self) -> Optional[float]:
        if not self._rpc_samples:
            return None
        from lua_mapreduce_tpu.trace.collect import percentile
        return percentile(list(self._rpc_samples), 99.0)

    def value(self, knob: str) -> Optional[float]:
        k = self._knobs.get(knob)
        return None if k is None else k.value

    # -- the tick -----------------------------------------------------------

    def tick(self, obs: Observation) -> List[Decision]:
        """Evaluate every owned knob against this window's evidence;
        returns the APPLIED decisions (already committed to knob state,
        already traced and counted — the owner's job is the mechanical
        apply)."""
        out: List[Decision] = []
        for fn in (self._tick_batch_k, self._tick_push_budget,
                   self._tick_speculation, self._tick_retry_base,
                   self._tick_fleet):
            d = fn(obs)
            if d is not None:
                out.append(d)
        if out:
            self.decisions.extend(out)
            self._emit(out)
        return out

    def _propose(self, knob: str, new: float, metric: str,
                 observed: float, threshold: float) -> Optional[Decision]:
        k = self._knobs[knob]
        if new == k.value:
            return None
        direction = 1 if new > k.value else -1
        veto = k.gate(self.clock(), direction)
        if veto is not None:
            from lua_mapreduce_tpu.faults.retry import COUNTERS
            COUNTERS.bump("autotune_vetoes")
            return None
        d = Decision(knob=knob, metric=metric, observed=observed,
                     threshold=threshold, old=k.value, new=new)
        k.commit(self.clock(), new, direction)
        return d

    def _tick_batch_k(self, obs: Observation) -> Optional[Decision]:
        k = self._knobs.get("batch_k")
        p99, body = obs.rpc_p99_s, obs.body_ewma_s
        if k is None or not p99 or not body or body <= 0:
            return None
        ratio = p99 / body
        cur = int(k.value)
        if ratio > self.cfg.batch_ratio_hi and cur < self.cfg.batch_k_max:
            return self._propose(
                "batch_k", min(self.cfg.batch_k_max, cur * 2),
                "claim_p99_over_body_ewma", ratio, self.cfg.batch_ratio_hi)
        if ratio < self.cfg.batch_ratio_lo and cur > 1:
            return self._propose(
                "batch_k", max(1, cur // 2),
                "claim_p99_over_body_ewma", ratio, self.cfg.batch_ratio_lo)
        return None

    def _tick_push_budget(self, obs: Observation) -> Optional[Decision]:
        k = self._knobs.get("push_budget_mb")
        if k is None:
            return None
        if obs.push_evictions >= self.cfg.evict_burst:
            self._quiet_evict = 0
            if k.value < self.cfg.push_budget_max_mb:
                return self._propose(
                    "push_budget_mb",
                    min(self.cfg.push_budget_max_mb,
                        round(k.value * 1.5, 3)),
                    "evictions_per_window", float(obs.push_evictions),
                    float(self.cfg.evict_burst))
            return None
        if obs.push_evictions == 0 and obs.push_frames > 0:
            self._quiet_evict += 1
            if self._quiet_evict >= self.cfg.shrink_after \
                    and k.value > self._push_floor:
                self._quiet_evict = 0
                return self._propose(
                    "push_budget_mb",
                    max(self._push_floor, round(k.value * 0.75, 3)),
                    "eviction_free_windows", float(self.cfg.shrink_after),
                    float(self.cfg.shrink_after))
        return None

    def _tick_speculation(self, obs: Observation) -> Optional[Decision]:
        k = self._knobs.get("speculation")
        if k is None or obs.spec_launched <= 0:
            return None
        body = obs.body_ewma_s or 0.0
        useful = max(obs.jobs_done, 1) * max(body, 1e-9)
        frac = obs.spec_wasted_s / (useful + obs.spec_wasted_s) \
            if obs.spec_wasted_s > 0 else 0.0
        if frac > self.cfg.waste_frac_hi \
                and k.value < self.cfg.speculation_max:
            return self._propose(
                "speculation",
                min(self.cfg.speculation_max, round(k.value * 1.25, 3)),
                "wasted_work_fraction", frac, self.cfg.waste_frac_hi)
        if frac < self.cfg.waste_frac_lo and obs.spec_wins > 0 \
                and k.value > self.cfg.speculation_min:
            return self._propose(
                "speculation",
                max(self.cfg.speculation_min, round(k.value * 0.8, 3)),
                "wasted_work_fraction", frac, self.cfg.waste_frac_lo)
        return None

    def _tick_retry_base(self, obs: Observation) -> Optional[Decision]:
        k = self._knobs.get("retry_base_ms")
        if k is None:
            return None
        window = max(self.cfg.cooldown_s, 1e-3)
        rate = obs.store_retries / window
        if rate > self.cfg.fault_rate_hi:
            self._quiet_fault = 0
            if k.value < self.cfg.retry_base_max_ms:
                return self._propose(
                    "retry_base_ms",
                    min(self.cfg.retry_base_max_ms, round(k.value * 2, 3)),
                    "transient_faults_per_s", rate, self.cfg.fault_rate_hi)
            return None
        if obs.store_retries == 0:
            self._quiet_fault += 1
            if self._quiet_fault >= self.cfg.shrink_after \
                    and k.value > self._retry_floor:
                self._quiet_fault = 0
                return self._propose(
                    "retry_base_ms",
                    max(self._retry_floor, round(k.value / 2, 3)),
                    "fault_free_windows", float(self.cfg.shrink_after),
                    float(self.cfg.shrink_after))
        return None

    def _tick_fleet(self, obs: Observation) -> Optional[Decision]:
        k = self._knobs.get("fleet")
        if k is None:
            return None
        fleet = obs.fleet if obs.fleet is not None else int(k.value)
        body = obs.body_ewma_s
        if obs.waiting > 0 and body and body > 0 and fleet > 0:
            self._quiet_queue = 0
            drain_s = obs.waiting * body / fleet
            if drain_s > 1.5 * self.cfg.drain_target_s:
                desired = min(
                    self.cfg.fleet_max,
                    max(fleet + 1,
                        math.ceil(obs.waiting * body
                                  / self.cfg.drain_target_s)))
                if desired > k.value:
                    return self._propose(
                        "fleet", desired, "backlog_drain_s", drain_s,
                        1.5 * self.cfg.drain_target_s)
            return None
        if obs.waiting == 0:
            self._quiet_queue += 1
            if self._quiet_queue >= self.cfg.shrink_after \
                    and k.value > self._fleet_floor:
                self._quiet_queue = 0
                return self._propose(
                    "fleet", self._fleet_floor, "empty_queue_windows",
                    float(self.cfg.shrink_after),
                    float(self.cfg.shrink_after))
        return None

    # -- evidence -----------------------------------------------------------

    def _emit(self, decisions: Sequence[Decision]) -> None:
        """Every applied decision is explainable after the fact: an
        ``autotune.<knob>`` span carrying the metric, the threshold
        that tripped, and old→new; plus the fold-able counters."""
        from lua_mapreduce_tpu.faults.retry import COUNTERS
        from lua_mapreduce_tpu.trace.span import active_tracer
        tracer = active_tracer()
        for d in decisions:
            COUNTERS.bump("autotune_decisions")
            if d.knob == "fleet":
                COUNTERS.bump("autotune_scale_events")
            if tracer is not None:
                now = tracer.clock()
                tracer.add(f"autotune.{d.knob}", now, now,
                           metric=d.metric,
                           observed=round(float(d.observed), 6),
                           threshold=round(float(d.threshold), 6),
                           old=d.old, new=d.new,
                           direction=d.direction)


class FleetSupervisor:
    """The elastic hook for thread/subprocess fleets: keep ``spawn``-ed
    members matched to the controller's target, never above ``cap``.

    Scale-up spawns; scale-down retires GRACEFULLY: ``retire(member)``
    must make the member stop claiming new leases and exit after its
    in-flight lease commits (the thread fleet sets ``max_jobs`` to the
    jobs already executed — Worker's bounded-lifetime check fires after
    the current poll completes, so no lease is abandoned; subprocess
    fleets simply stop respawning bounded-lifetime members). The
    no-lease-lost-across-retire property is the ``retire`` edge the
    protocol checker enumerates (analysis/protocol.py, elastic=True)."""

    def __init__(self, spawn: Callable[[int], object],
                 retire: Callable[[object], None],
                 baseline: int, cap: int):
        if baseline < 1 or cap < baseline:
            raise ValueError("need 1 <= baseline <= cap")
        self.spawn = spawn
        self.retire = retire
        self.baseline = baseline
        self.cap = cap
        self.members: List[object] = []
        self._seq = 0
        self._lock = threading.Lock()

    @property
    def size(self) -> int:
        with self._lock:
            return len(self.members)

    def ensure_baseline(self) -> int:
        return self.resize(self.baseline)

    def resize(self, target: int) -> int:
        """Grow/shrink toward ``target`` (clamped to [baseline, cap]);
        returns the new size. Shrinks retire the NEWEST members first —
        the baseline crew keeps its warm caches and its affinity map.

        ``spawn``/``retire`` are caller-injected and may block (mint a
        thread, fork a process, RPC a scheduler) or re-enter this
        supervisor — so both run OUTSIDE the lock: each growth step
        reserves its seq under the lock, spawns unlocked, then appends
        under the lock. Concurrent resizers interleave safely: the
        re-check per iteration keeps the fleet at the LAST target, and
        a member is either in ``members`` or still owned by its
        spawning frame — never both, never neither."""
        target = max(self.baseline, min(self.cap, int(target)))
        while True:
            with self._lock:
                if len(self.members) >= target:
                    break
                seq = self._seq
                self._seq += 1
            m = self.spawn(seq)
            with self._lock:
                self.members.append(m)
        surplus = []
        with self._lock:
            while len(self.members) > target:
                surplus.append(self.members.pop())
        for m in surplus:
            self.retire(m)
        return self.size


def tenant_fleet_cap(tenants, baseline: int, hard_max: int) -> int:
    """The admission-quota bound on elastic growth: with per-tenant
    ``max_pending`` quotas (sched/tenancy.py) the claimable backlog can
    never exceed the quota sum, so workers beyond baseline + that sum
    could not all find work — the cap keeps a tenant flood from scaling
    the fleet past what admission control will ever feed it."""
    if not tenants:
        return hard_max
    quota = sum(int(t.max_pending) for t in tenants)
    return max(baseline, min(hard_max, baseline + quota))


def utest() -> None:
    """Self-test: band/cooldown/flip behavior on a virtual clock."""
    now = [0.0]
    cfg = AutotuneConfig(cooldown_s=1.0, flip_reset_s=100.0,
                         shrink_after=2)
    c = AutotuneController(batch_k=1, push_budget_mb=8.0, speculation=2.0,
                           retry_base_ms=25.0, fleet=2, fleet_max=6,
                           config=cfg, clock=lambda: now[0])

    def obs(**kw):
        kw.setdefault("t", now[0])
        return Observation(**kw)

    # claim overhead dominates tiny jobs: batch_k doubles...
    c.note_rpc(0.05)
    d = c.tick(obs(body_ewma_s=0.01, rpc_p99_s=0.05, jobs_done=10))
    assert [x.knob for x in d] == ["batch_k"] and c.value("batch_k") == 2
    # ...but not again inside the cooldown
    now[0] += 0.5
    assert c.tick(obs(body_ewma_s=0.01, rpc_p99_s=0.05)) == []
    now[0] += 1.0
    assert c.value("batch_k") == 2
    d = c.tick(obs(body_ewma_s=0.01, rpc_p99_s=0.05))
    assert c.value("batch_k") == 4
    # dead band: nothing moves
    now[0] += 2.0
    assert c.tick(obs(body_ewma_s=0.1, rpc_p99_s=0.05)) == []
    # reversal (long jobs): allowed once...
    now[0] += 2.0
    d = c.tick(obs(body_ewma_s=10.0, rpc_p99_s=0.05))
    assert c.value("batch_k") == 2
    # ...a second reversal (up again) is flip-locked
    now[0] += 2.0
    assert c.tick(obs(body_ewma_s=0.01, rpc_p99_s=0.05)) == []
    # same direction still fine
    now[0] += 2.0
    c.tick(obs(body_ewma_s=10.0, rpc_p99_s=0.05))
    assert c.value("batch_k") == 1

    # push budget grows on an eviction burst, decays after quiet windows
    now[0] += 10.0
    d = c.tick(obs(push_evictions=8, push_frames=8))
    assert c.value("push_budget_mb") == 12.0
    now[0] += 2.0
    c.tick(obs(push_frames=4))
    now[0] += 2.0
    c.tick(obs(push_frames=4))
    assert c.value("push_budget_mb") == 9.0       # one flip, allowed
    # speculation: heavy waste raises the factor
    now[0] += 2.0
    d = c.tick(obs(body_ewma_s=0.1, jobs_done=4, spec_launched=4,
                   spec_wasted_s=5.0))
    assert c.value("speculation") == 2.5
    # retry base doubles under a fault storm
    now[0] += 2.0
    d = c.tick(obs(store_retries=50))
    assert c.value("retry_base_ms") == 50.0
    # fleet scales up under backlog, retires to baseline when drained
    now[0] += 2.0
    d = c.tick(obs(body_ewma_s=5.0, waiting=20, running=2, fleet=2))
    assert c.value("fleet") == 6                   # capped at fleet_max
    now[0] += 2.0
    c.tick(obs(waiting=0, fleet=6))
    now[0] += 2.0
    c.tick(obs(waiting=0, fleet=6))
    assert c.value("fleet") == 2

    # the supervisor: graceful resize with newest-first retirement
    spawned, retired = [], []
    sup = FleetSupervisor(lambda i: f"w{i}", retired.append,
                          baseline=2, cap=4)
    sup.ensure_baseline()
    assert sup.size == 2
    sup.resize(10)
    assert sup.size == 4 and not retired
    sup.resize(1)                                  # clamped to baseline
    assert sup.size == 2 and retired == ["w3", "w2"]

    # tenant quota cap
    class _T:
        def __init__(self, mp):
            self.max_pending = mp
    assert tenant_fleet_cap([_T(2), _T(3)], baseline=2, hard_max=32) == 7
    assert tenant_fleet_cap([], baseline=2, hard_max=32) == 32
    assert tenant_fleet_cap([_T(100)], baseline=2, hard_max=8) == 8

    assert resolve_autotune(True) and not resolve_autotune(False)
    print("sched/controller utest ok")
