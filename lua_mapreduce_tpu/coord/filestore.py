"""Shared-directory job store for multi-process / multi-host pools.

The distributed coordination backend (SURVEY.md §2.6 analog): a directory on
a filesystem all participants can reach. Mutable claim state lives in the
binary job index (idx.py — native C++ or Python engine, both flock-CAS);
immutable payloads, per-job timing, the task singleton, the errors stream,
and persistent-table documents are JSON files written atomically.

Write discipline per namespace: only the server inserts jobs, and payload
files are written *before* their index records become claimable, so a worker
that wins a claim always finds the payload. Only the claiming worker writes
its job's timing/worker sidecars. Everything multi-writer goes through a
flock or the index CAS.
"""

from __future__ import annotations

import copy
import fcntl
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import (MAX_JOB_RETRIES,
                                              MAX_PENDING_INSERTS, Status)
from lua_mapreduce_tpu.coord.idx import open_index
from lua_mapreduce_tpu.coord.jobstore import CLAIMABLE, JobStore


def worker_hash(worker: str) -> int:
    """Stable int64 id for a worker name (index records store integers)."""
    h = hashlib.blake2b(worker.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little", signed=True)


# job-times field order in the index record (idx format JSIX0002 embeds
# the 5 times per record; the v1 scheme was one t<jid>.json rename per
# job — at many-tiny-jobs scale those renames dominated the commit)
TIMES_KEYS = ("started", "finished", "written", "cpu", "real")


def _times5(times: Optional[dict]):
    if not times:
        return None
    return tuple(float(times.get(k) or 0.0) for k in TIMES_KEYS)


def _times_doc(t5) -> Optional[dict]:
    return dict(zip(TIMES_KEYS, t5)) if t5 is not None else None


def _atomic_write_json(path: str, doc) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


class _FLock:
    def __init__(self, path: str):
        self._path = path

    def __enter__(self):
        self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o666)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        os.close(self._fd)


class FileJobStore(JobStore):
    def __init__(self, root: str, engine: str = "auto"):
        self.root = root
        self.engine = engine
        os.makedirs(root, exist_ok=True)
        os.makedirs(os.path.join(root, "locks"), exist_ok=True)
        os.makedirs(os.path.join(root, "pt"), exist_ok=True)
        # per-namespace payload-batch cache: ns -> [(base, docs), ...].
        # Batches are immutable once written, so the cache never goes
        # stale except when the ns is dropped (invalidated there) or a
        # new batch lands (rescan on miss).
        self._batches: Dict[str, List] = {}
        # parsed claim-log cache: ns -> ((size, mtime_ns), {jid: name});
        # the log is append-only, so size strictly grows on change
        self._wlogs: Dict[str, tuple] = {}

    # -- paths -------------------------------------------------------------

    def _idx(self, ns: str):
        return open_index(os.path.join(self.root, f"{ns}.idx"), self.engine)

    def _ns_dir(self, ns: str) -> str:
        d = os.path.join(self.root, f"{ns}.d")
        os.makedirs(d, exist_ok=True)
        return d

    def _wlog(self, ns: str) -> str:
        """Append-only claim log: one ``jid\\tworker`` line per claim,
        last entry per jid wins. Replaces the v1 per-job ``w<jid>.txt``
        sidecars — a file CREATE per claim was a metadata round trip
        that survived batching; one O_APPEND write per LEASE (small
        writes append atomically) is free, and readers get the whole
        map in one read instead of one open per job."""
        return os.path.join(self._ns_dir(ns), "workers.log")

    def _read_wlog(self, ns: str) -> Dict[int, str]:
        """Parsed claim log, cached on (size, mtime): per-job lookups
        (get_job in a loop) must not re-parse a many-thousand-line log
        per call. Callers treat the returned dict as read-only."""
        path = self._wlog(ns)
        try:
            st = os.stat(path)
            sig = (st.st_size, st.st_mtime_ns)
        except OSError:
            return {}
        cached = self._wlogs.get(ns)
        if cached is not None and cached[0] == sig:
            return cached[1]
        out: Dict[int, str] = {}
        try:
            with open(path) as f:
                for line in f:
                    jid, sep, name = line.rstrip("\n").partition("\t")
                    if sep and name:
                        try:
                            out[int(jid)] = name
                        except ValueError:
                            continue
        except OSError:
            return out
        self._wlogs[ns] = (sig, out)
        return out

    def _append_wlog(self, ns: str, jids, worker: str) -> None:
        try:
            payload = "".join(f"{jid}\t{worker}\n" for jid in jids)
            fd = os.open(self._wlog(ns),
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o666)
            try:
                os.write(fd, payload.encode())
            finally:
                os.close(fd)
        except OSError:
            pass  # observability only

    def _lockfile(self, name: str) -> str:
        return os.path.join(self.root, "locks", f"{name}.lock")

    # -- task singleton ----------------------------------------------------

    def put_task(self, doc: dict) -> None:
        with _FLock(self._lockfile("task")):
            _atomic_write_json(os.path.join(self.root, "task.json"), doc)

    def get_task(self) -> Optional[dict]:
        return _read_json(os.path.join(self.root, "task.json"))

    def update_task(self, fields: dict) -> None:
        with _FLock(self._lockfile("task")):
            path = os.path.join(self.root, "task.json")
            doc = _read_json(path)
            if doc is None:
                from lua_mapreduce_tpu.faults.errors import NoTaskError
                raise NoTaskError("no task document")
            doc.update(fields)
            _atomic_write_json(path, doc)

    def delete_task(self) -> None:
        with _FLock(self._lockfile("task")):
            try:
                os.remove(os.path.join(self.root, "task.json"))
            except FileNotFoundError:
                pass

    # -- jobs --------------------------------------------------------------

    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        """Insert a batch of job payloads, then make them claimable.

        Payloads are written as ONE manifest file per batch of up to
        MAX_PENDING_INSERTS jobs (the reference buffers control-plane
        inserts the same way, cnn.lua:80-111) — at reference fan-in scale
        (~2,000 map jobs, README.md:59) the former file-per-job scheme
        meant thousands of sequential ``os.replace`` round trips per
        phase. Manifests land before ``idx.insert`` flips the records
        claimable, so a winning worker always finds its payload.
        """
        idx = self._idx(ns)
        base = idx.count()
        docs = list(docs)
        # clear manifests left by a crash between a previous manifest
        # write and its idx.insert — a duplicate-base survivor would
        # shadow this insert's payloads for readers
        d = self._ns_dir(ns)
        fresh = {os.path.basename(self._batch_path(
            ns, base + off, len(docs[off:off + MAX_PENDING_INSERTS])))
            for off in range(0, len(docs), MAX_PENDING_INSERTS)}
        for name in os.listdir(d):
            if (name.startswith("b") and name.endswith(".json")
                    and name not in fresh):
                try:
                    stale_base = int(name[1:-5].split("_")[0])
                except ValueError:
                    continue
                if stale_base >= base:
                    try:
                        os.remove(os.path.join(d, name))
                    except FileNotFoundError:
                        pass
        for off in range(0, len(docs), MAX_PENDING_INSERTS):
            chunk = docs[off:off + MAX_PENDING_INSERTS]
            _atomic_write_json(self._batch_path(ns, base + off, len(chunk)),
                               chunk)
        # new generation AFTER the manifests land, BEFORE records become
        # claimable: a worker that wins a claim always sees fresh payloads
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp.gen.")
        with os.fdopen(fd, "w") as f:
            f.write(f"{time.time_ns()}.{base}.{len(docs)}")
        os.replace(tmp, self._gen_path(ns))
        got = idx.insert(len(docs))
        if got != base:
            from lua_mapreduce_tpu.faults.errors import ConcurrentInsertError
            raise ConcurrentInsertError(
                f"concurrent insert into {ns!r}: expected base {base}, got "
                f"{got} — a namespace has exactly one inserter (the server)")
        return list(range(base, base + len(docs)))

    def _batch_path(self, ns: str, base: int, count: int) -> str:
        return os.path.join(self._ns_dir(ns), f"b{base}_{count}.json")

    def _gen_path(self, ns: str) -> str:
        return os.path.join(self.root, f"{ns}.gen")

    def _read_gen(self, ns: str) -> Optional[str]:
        """Payload generation token. insert_jobs rewrites it after its
        batch manifests land, so OTHER processes' caches (a worker that
        outlives a ``"loop"``-protocol drop_ns + re-insert) detect the
        recreated namespace; their own drop_ns only invalidates locally."""
        return _read_json_text(self._gen_path(ns))

    def _resolve_batches(self, ns: str) -> list:
        """The namespace's batch list [(base, docs), ...], cached against
        the generation token. The token is read BEFORE the rescan, so a
        token raced by a concurrent insert merely forces one extra rescan
        later — batch manifests are immutable, never wrong. Duplicate
        bases (a crash-orphaned manifest that raced insert-time cleanup)
        resolve to the newest file."""
        stamp = self._read_gen(ns)
        cached = self._batches.get(ns)
        if cached is not None and cached[0] == stamp:
            return cached[1]
        entries: Dict[int, tuple] = {}
        d = self._ns_dir(ns)
        for name in os.listdir(d):
            if name.startswith("b") and name.endswith(".json"):
                try:
                    b = int(name[1:-5].split("_")[0])
                except ValueError:
                    continue
                path = os.path.join(d, name)
                loaded = _read_json(path)
                if loaded is None:
                    continue
                try:
                    mtime = os.stat(path).st_mtime_ns
                except OSError:
                    mtime = 0
                prev = entries.get(b)
                if prev is None or mtime >= prev[0]:
                    entries[b] = (mtime, loaded)
        batches = sorted((b, docs) for b, (_, docs) in entries.items())
        self._batches[ns] = (stamp, batches)
        return batches

    @staticmethod
    def _lookup_payload(batches: list, jid: int) -> Optional[dict]:
        for base, docs in batches:
            if base <= jid < base + len(docs):
                return docs[jid - base]
        return None

    def _payload_doc(self, ns: str, jid: int) -> dict:
        """One job's payload, DEEP-copied: the cache must stay pristine
        when a caller (user mapfn mutating its value in place) edits the
        returned doc — the old file-per-job scheme re-parsed JSON per
        read, and retries depend on that isolation."""
        doc = self._lookup_payload(self._resolve_batches(ns), jid)
        return copy.deepcopy(doc) if doc is not None else {}

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        got = self.claim_batch(ns, worker, 1, preferred_ids, steal)
        return got[0] if got else None

    def claim_batch(self, ns, worker, k=1, preferred_ids=None, steal=True):
        """Lease up to k jobs in ONE locked index pass plus ONE claim-log
        append. The claimed docs are built from the claim's own return
        (id, repetitions) plus the payload cache — no per-job index
        re-read, no per-job sidecar IO, no times read (a fresh claim's
        times are a previous attempt's, which no caller of claim uses)."""
        self._bump("claim")
        now = time.time()
        claimed = self._idx(ns).claim_batch(worker_hash(worker), now, k,
                                            preferred_ids, steal)
        if not claimed:
            return []
        self._append_wlog(ns, [jid for jid, _ in claimed], worker)
        batches = self._resolve_batches(ns)
        docs = []
        for jid, reps in claimed:
            doc = copy.deepcopy(self._lookup_payload(batches, jid)) or {}
            doc.update(_id=jid, status=Status.RUNNING, repetitions=reps,
                       worker=worker, started_time=now, times=None)
            docs.append(doc)
        return docs

    def commit_batch(self, ns, worker, entries):
        """Retire a batch in ONE flock cycle: status transition AND job
        times land together in each index record (idx format JSIX0002),
        CASed on this worker's ownership per entry. The v1 protocol paid
        two status CAS flocks plus one times-sidecar rename per job."""
        entries = [(jid, _times5(times)) for jid, times in entries]
        if not entries:
            return []
        self._bump("commit")
        ok = self._idx(ns).commit_batch(entries, worker_hash(worker))
        return [jid for (jid, _), o in zip(entries, ok) if o]

    def release_batch(self, ns, worker, job_ids):
        """RUNNING→WAITING for leased-but-unstarted jobs, one flock."""
        if not job_ids:
            return 0
        self._bump("commit")
        ok = self._idx(ns).cas_status_batch(list(job_ids), Status.WAITING,
                                            1 << int(Status.RUNNING),
                                            worker_hash(worker))
        return sum(ok)

    def heartbeat_batch(self, ns, job_ids, worker):
        if not job_ids:
            return 0
        return self._idx(ns).heartbeat_batch(list(job_ids),
                                             worker_hash(worker),
                                             time.time())

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        self._bump("commit")
        mask = 0
        if expect is not None:
            for s in expect:
                mask |= 1 << int(s)
        whash = worker_hash(expect_worker) if expect_worker else 0
        return self._idx(ns).cas_status(job_id, status, mask, whash)

    def get_job(self, ns, job_id):
        idx = self._idx(ns)
        if idx.get(job_id) is None:
            return None
        return self._job_doc(ns, job_id, idx)

    def jobs(self, ns):
        idx = self._idx(ns)
        docs = []
        # one locked pass over the index (times included — the index
        # record embeds them), ONE batch resolution and ONE claim-log
        # read for the whole snapshot (per-jid resolution would re-read
        # the gen file / one sidecar per job)
        batches = self._resolve_batches(ns)
        wnames = self._read_wlog(ns)
        for jid, (status, reps, whash, started, t5, spec_state,
                  spec_whash) in enumerate(idx.snapshot()):
            doc = copy.deepcopy(self._lookup_payload(batches, jid)) or {}
            doc.update(_id=jid, status=Status(status), repetitions=reps,
                       worker=wnames.get(jid, whash or None),
                       started_time=started or None,
                       times=_times_doc(t5), spec_state=spec_state,
                       spec_worker=spec_whash or None)
            docs.append(doc)
        return docs

    def _job_doc(self, ns, jid, idx) -> dict:
        state = idx.get(jid)
        status, reps, whash, started, t5, spec_state, spec_whash = state
        doc = dict(self._payload_doc(ns, jid))
        doc.update(_id=jid, status=Status(status), repetitions=reps,
                   worker=self._read_wlog(ns).get(jid, whash or None),
                   started_time=started or None,
                   times=_times_doc(t5), spec_state=spec_state,
                   spec_worker=spec_whash or None)
        return doc

    def job_workers(self, ns):
        """id → worker from the claim log alone — ONE file read, no
        payload reads, no deep copies, no index lock (the server calls
        this once per reduce prepare; the v1 scheme opened one sidecar
        per job). Copied so callers cannot mutate the cache."""
        return dict(self._read_wlog(ns))

    def set_job_times(self, ns, job_id, times):
        self._bump("commit")
        t5 = _times5(dict(times))
        if t5 is not None:
            # a dropped namespace (straggler finishing late) is a no-op,
            # matching the v1 sidecar-write behavior
            self._idx(ns).set_times(job_id, t5)

    def counts(self, ns):
        return self._idx(ns).counts()

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        return self._idx(ns).scavenge(max_retries)

    def requeue_stale(self, ns, older_than_s):
        return self._idx(ns).requeue_stale(time.time() - older_than_s)

    def heartbeat(self, ns, job_id, worker):
        return self._idx(ns).heartbeat(job_id, worker_hash(worker),
                                       time.time())

    # -- duplicate leases (speculative execution, DESIGN §21) --------------

    def speculate(self, ns, job_id):
        self._bump("commit")
        return bool(self._idx(ns).speculate(job_id))

    def claim_spec(self, ns, worker):
        self._bump("claim")
        got = self._idx(ns).claim_spec(worker_hash(worker))
        if got is None:
            return None
        jid, reps = got
        # the clone doc carries the ORIGINAL claimant as ``worker`` (the
        # claim log's last entry — claim_spec never appends to it, so
        # producer lookups keep naming the original)
        doc = copy.deepcopy(
            self._lookup_payload(self._resolve_batches(ns), jid)) or {}
        doc.update(_id=jid, status=Status.RUNNING, repetitions=reps,
                   worker=self._read_wlog(ns).get(jid), times=None,
                   spec_state=2, spec_worker=worker, speculative=True)
        return doc

    def cancel_spec(self, ns, job_id, worker):
        self._bump("commit")
        return bool(self._idx(ns).cancel_spec(
            job_id, worker_hash(worker) if worker is not None else 0))

    def drop_ns(self, ns):
        self._batches.pop(ns, None)
        self._wlogs.pop(ns, None)
        for stale in (f"{ns}.idx", f"{ns}.gen"):
            try:
                os.remove(os.path.join(self.root, stale))
            except FileNotFoundError:
                pass
        d = os.path.join(self.root, f"{ns}.d")
        if os.path.isdir(d):
            for f in os.listdir(d):
                try:
                    os.remove(os.path.join(d, f))
                except FileNotFoundError:
                    pass
            os.rmdir(d)

    # -- errors ------------------------------------------------------------

    def insert_error(self, worker, msg, info=None):
        doc = {"worker": worker, "msg": msg, "time": time.time()}
        if info:
            doc.update(info)
        line = json.dumps(doc)
        with _FLock(self._lockfile("errors")):
            with open(os.path.join(self.root, "errors.jsonl"), "a") as f:
                f.write(line + "\n")

    def drain_errors(self):
        path = os.path.join(self.root, "errors.jsonl")
        with _FLock(self._lockfile("errors")):
            try:
                with open(path) as f:
                    lines = f.readlines()
                os.remove(path)
            except FileNotFoundError:
                return []
        return [json.loads(l) for l in lines if l.strip()]

    # -- persistent documents ----------------------------------------------

    def _pt_path(self, name: str) -> str:
        return os.path.join(self.root, "pt", f"{name}.json")

    def pt_get(self, name):
        return _read_json(self._pt_path(name))

    def pt_cas(self, name, expected_ts, doc):
        with _FLock(self._lockfile(f"pt_{name}")):
            cur = _read_json(self._pt_path(name))
            cur_ts = cur.get("timestamp") if cur is not None else None
            if cur_ts != expected_ts:
                return False
            _atomic_write_json(self._pt_path(name), doc)
            return True

    def pt_delete(self, name):
        with _FLock(self._lockfile(f"pt_{name}")):
            try:
                os.remove(self._pt_path(name))
            except FileNotFoundError:
                pass


def _read_json_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None
