"""Shared-directory job store for multi-process / multi-host pools.

The distributed coordination backend (SURVEY.md §2.6 analog): a directory on
a filesystem all participants can reach. Mutable claim state lives in the
binary job index (idx.py — native C++ or Python engine, both flock-CAS);
immutable payloads, per-job timing, the task singleton, the errors stream,
and persistent-table documents are JSON files written atomically.

Write discipline per namespace: only the server inserts jobs, and payload
files are written *before* their index records become claimable, so a worker
that wins a claim always finds the payload. Only the claiming worker writes
its job's timing/worker sidecars. Everything multi-writer goes through a
flock or the index CAS.
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status
from lua_mapreduce_tpu.coord.idx import open_index
from lua_mapreduce_tpu.coord.jobstore import CLAIMABLE, JobStore


def worker_hash(worker: str) -> int:
    """Stable int64 id for a worker name (index records store integers)."""
    h = hashlib.blake2b(worker.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little", signed=True)


def _atomic_write_json(path: str, doc) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


class _FLock:
    def __init__(self, path: str):
        self._path = path

    def __enter__(self):
        self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o666)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        os.close(self._fd)


class FileJobStore(JobStore):
    def __init__(self, root: str, engine: str = "auto"):
        self.root = root
        self.engine = engine
        os.makedirs(root, exist_ok=True)
        os.makedirs(os.path.join(root, "locks"), exist_ok=True)
        os.makedirs(os.path.join(root, "pt"), exist_ok=True)

    # -- paths -------------------------------------------------------------

    def _idx(self, ns: str):
        return open_index(os.path.join(self.root, f"{ns}.idx"), self.engine)

    def _ns_dir(self, ns: str) -> str:
        d = os.path.join(self.root, f"{ns}.d")
        os.makedirs(d, exist_ok=True)
        return d

    def _payload(self, ns: str, job_id: int) -> str:
        return os.path.join(self._ns_dir(ns), f"j{job_id}.json")

    def _times(self, ns: str, job_id: int) -> str:
        return os.path.join(self._ns_dir(ns), f"t{job_id}.json")

    def _wname(self, ns: str, job_id: int) -> str:
        return os.path.join(self._ns_dir(ns), f"w{job_id}.txt")

    def _lockfile(self, name: str) -> str:
        return os.path.join(self.root, "locks", f"{name}.lock")

    # -- task singleton ----------------------------------------------------

    def put_task(self, doc: dict) -> None:
        with _FLock(self._lockfile("task")):
            _atomic_write_json(os.path.join(self.root, "task.json"), doc)

    def get_task(self) -> Optional[dict]:
        return _read_json(os.path.join(self.root, "task.json"))

    def update_task(self, fields: dict) -> None:
        with _FLock(self._lockfile("task")):
            path = os.path.join(self.root, "task.json")
            doc = _read_json(path)
            if doc is None:
                raise RuntimeError("no task document")
            doc.update(fields)
            _atomic_write_json(path, doc)

    def delete_task(self) -> None:
        with _FLock(self._lockfile("task")):
            try:
                os.remove(os.path.join(self.root, "task.json"))
            except FileNotFoundError:
                pass

    # -- jobs --------------------------------------------------------------

    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        idx = self._idx(ns)
        base = idx.count()
        for i, doc in enumerate(docs):
            _atomic_write_json(self._payload(ns, base + i), doc)
        got = idx.insert(len(docs))
        if got != base:
            raise RuntimeError(
                f"concurrent insert into {ns!r}: expected base {base}, got "
                f"{got} — a namespace has exactly one inserter (the server)")
        return list(range(base, base + len(docs)))

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        idx = self._idx(ns)
        jid = idx.claim(worker_hash(worker), time.time(), preferred_ids, steal)
        if jid < 0:
            return None
        try:
            with open(self._wname(ns, jid), "w") as f:
                f.write(worker)
        except OSError:
            pass  # observability only
        return self._job_doc(ns, jid, idx)

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        mask = 0
        if expect is not None:
            for s in expect:
                mask |= 1 << int(s)
        whash = worker_hash(expect_worker) if expect_worker else 0
        return self._idx(ns).cas_status(job_id, status, mask, whash)

    def get_job(self, ns, job_id):
        idx = self._idx(ns)
        if idx.get(job_id) is None:
            return None
        return self._job_doc(ns, job_id, idx)

    def jobs(self, ns):
        idx = self._idx(ns)
        docs = []
        # one locked pass over the index; payload/times are per-job files
        # but immutable/single-writer, so they need no lock
        for jid, (status, reps, whash, started) in enumerate(idx.snapshot()):
            payload = _read_json(self._payload(ns, jid)) or {}
            doc = dict(payload)
            doc.update(_id=jid, status=Status(status), repetitions=reps,
                       worker=whash or None, started_time=started or None,
                       times=_read_json(self._times(ns, jid)))
            wname = _read_json_text(self._wname(ns, jid))
            if wname:
                doc["worker"] = wname
            docs.append(doc)
        return docs

    def _job_doc(self, ns, jid, idx) -> dict:
        state = idx.get(jid)
        payload = _read_json(self._payload(ns, jid)) or {}
        status, reps, whash, started = state
        doc = dict(payload)
        doc.update(_id=jid, status=Status(status), repetitions=reps,
                   worker=whash or None,
                   started_time=started or None,
                   times=_read_json(self._times(ns, jid)))
        wname = _read_json_text(self._wname(ns, jid))
        if wname:
            doc["worker"] = wname
        return doc

    def set_job_times(self, ns, job_id, times):
        _atomic_write_json(self._times(ns, job_id), dict(times))

    def counts(self, ns):
        return self._idx(ns).counts()

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        return self._idx(ns).scavenge(max_retries)

    def requeue_stale(self, ns, older_than_s):
        return self._idx(ns).requeue_stale(time.time() - older_than_s)

    def drop_ns(self, ns):
        try:
            os.remove(os.path.join(self.root, f"{ns}.idx"))
        except FileNotFoundError:
            pass
        d = os.path.join(self.root, f"{ns}.d")
        if os.path.isdir(d):
            for f in os.listdir(d):
                try:
                    os.remove(os.path.join(d, f))
                except FileNotFoundError:
                    pass
            os.rmdir(d)

    # -- errors ------------------------------------------------------------

    def insert_error(self, worker, msg):
        line = json.dumps({"worker": worker, "msg": msg, "time": time.time()})
        with _FLock(self._lockfile("errors")):
            with open(os.path.join(self.root, "errors.jsonl"), "a") as f:
                f.write(line + "\n")

    def drain_errors(self):
        path = os.path.join(self.root, "errors.jsonl")
        with _FLock(self._lockfile("errors")):
            try:
                with open(path) as f:
                    lines = f.readlines()
                os.remove(path)
            except FileNotFoundError:
                return []
        return [json.loads(l) for l in lines if l.strip()]

    # -- persistent documents ----------------------------------------------

    def _pt_path(self, name: str) -> str:
        return os.path.join(self.root, "pt", f"{name}.json")

    def pt_get(self, name):
        return _read_json(self._pt_path(name))

    def pt_cas(self, name, expected_ts, doc):
        with _FLock(self._lockfile(f"pt_{name}")):
            cur = _read_json(self._pt_path(name))
            cur_ts = cur.get("timestamp") if cur is not None else None
            if cur_ts != expected_ts:
                return False
            _atomic_write_json(self._pt_path(name), doc)
            return True

    def pt_delete(self, name):
        with _FLock(self._lockfile(f"pt_{name}")):
            try:
                os.remove(self._pt_path(name))
            except FileNotFoundError:
                pass


def _read_json_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None
