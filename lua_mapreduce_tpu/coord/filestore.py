"""Shared-directory job store for multi-process / multi-host pools.

The distributed coordination backend (SURVEY.md §2.6 analog): a directory on
a filesystem all participants can reach. Mutable claim state lives in the
binary job index (idx.py — native C++ or Python engine, both flock-CAS);
immutable payloads, per-job timing, the task singleton, the errors stream,
and persistent-table documents are JSON files written atomically.

Write discipline per namespace: only the server inserts jobs, and payload
files are written *before* their index records become claimable, so a worker
that wins a claim always finds the payload. Only the claiming worker writes
its job's timing/worker sidecars. Everything multi-writer goes through a
flock or the index CAS.
"""

from __future__ import annotations

import copy
import fcntl
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import (MAX_JOB_RETRIES,
                                              MAX_PENDING_INSERTS, Status)
from lua_mapreduce_tpu.coord.idx import open_index
from lua_mapreduce_tpu.coord.jobstore import CLAIMABLE, JobStore


def worker_hash(worker: str) -> int:
    """Stable int64 id for a worker name (index records store integers)."""
    h = hashlib.blake2b(worker.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little", signed=True)


def _atomic_write_json(path: str, doc) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.")
    with os.fdopen(fd, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


class _FLock:
    def __init__(self, path: str):
        self._path = path

    def __enter__(self):
        self._fd = os.open(self._path, os.O_RDWR | os.O_CREAT, 0o666)
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        os.close(self._fd)


class FileJobStore(JobStore):
    def __init__(self, root: str, engine: str = "auto"):
        self.root = root
        self.engine = engine
        os.makedirs(root, exist_ok=True)
        os.makedirs(os.path.join(root, "locks"), exist_ok=True)
        os.makedirs(os.path.join(root, "pt"), exist_ok=True)
        # per-namespace payload-batch cache: ns -> [(base, docs), ...].
        # Batches are immutable once written, so the cache never goes
        # stale except when the ns is dropped (invalidated there) or a
        # new batch lands (rescan on miss).
        self._batches: Dict[str, List] = {}

    # -- paths -------------------------------------------------------------

    def _idx(self, ns: str):
        return open_index(os.path.join(self.root, f"{ns}.idx"), self.engine)

    def _ns_dir(self, ns: str) -> str:
        d = os.path.join(self.root, f"{ns}.d")
        os.makedirs(d, exist_ok=True)
        return d

    def _times(self, ns: str, job_id: int) -> str:
        return os.path.join(self._ns_dir(ns), f"t{job_id}.json")

    def _wname(self, ns: str, job_id: int) -> str:
        return os.path.join(self._ns_dir(ns), f"w{job_id}.txt")

    def _lockfile(self, name: str) -> str:
        return os.path.join(self.root, "locks", f"{name}.lock")

    # -- task singleton ----------------------------------------------------

    def put_task(self, doc: dict) -> None:
        with _FLock(self._lockfile("task")):
            _atomic_write_json(os.path.join(self.root, "task.json"), doc)

    def get_task(self) -> Optional[dict]:
        return _read_json(os.path.join(self.root, "task.json"))

    def update_task(self, fields: dict) -> None:
        with _FLock(self._lockfile("task")):
            path = os.path.join(self.root, "task.json")
            doc = _read_json(path)
            if doc is None:
                raise RuntimeError("no task document")
            doc.update(fields)
            _atomic_write_json(path, doc)

    def delete_task(self) -> None:
        with _FLock(self._lockfile("task")):
            try:
                os.remove(os.path.join(self.root, "task.json"))
            except FileNotFoundError:
                pass

    # -- jobs --------------------------------------------------------------

    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        """Insert a batch of job payloads, then make them claimable.

        Payloads are written as ONE manifest file per batch of up to
        MAX_PENDING_INSERTS jobs (the reference buffers control-plane
        inserts the same way, cnn.lua:80-111) — at reference fan-in scale
        (~2,000 map jobs, README.md:59) the former file-per-job scheme
        meant thousands of sequential ``os.replace`` round trips per
        phase. Manifests land before ``idx.insert`` flips the records
        claimable, so a winning worker always finds its payload.
        """
        idx = self._idx(ns)
        base = idx.count()
        docs = list(docs)
        # clear manifests left by a crash between a previous manifest
        # write and its idx.insert — a duplicate-base survivor would
        # shadow this insert's payloads for readers
        d = self._ns_dir(ns)
        fresh = {os.path.basename(self._batch_path(
            ns, base + off, len(docs[off:off + MAX_PENDING_INSERTS])))
            for off in range(0, len(docs), MAX_PENDING_INSERTS)}
        for name in os.listdir(d):
            if (name.startswith("b") and name.endswith(".json")
                    and name not in fresh):
                try:
                    stale_base = int(name[1:-5].split("_")[0])
                except ValueError:
                    continue
                if stale_base >= base:
                    try:
                        os.remove(os.path.join(d, name))
                    except FileNotFoundError:
                        pass
        for off in range(0, len(docs), MAX_PENDING_INSERTS):
            chunk = docs[off:off + MAX_PENDING_INSERTS]
            _atomic_write_json(self._batch_path(ns, base + off, len(chunk)),
                               chunk)
        # new generation AFTER the manifests land, BEFORE records become
        # claimable: a worker that wins a claim always sees fresh payloads
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp.gen.")
        with os.fdopen(fd, "w") as f:
            f.write(f"{time.time_ns()}.{base}.{len(docs)}")
        os.replace(tmp, self._gen_path(ns))
        got = idx.insert(len(docs))
        if got != base:
            raise RuntimeError(
                f"concurrent insert into {ns!r}: expected base {base}, got "
                f"{got} — a namespace has exactly one inserter (the server)")
        return list(range(base, base + len(docs)))

    def _batch_path(self, ns: str, base: int, count: int) -> str:
        return os.path.join(self._ns_dir(ns), f"b{base}_{count}.json")

    def _gen_path(self, ns: str) -> str:
        return os.path.join(self.root, f"{ns}.gen")

    def _read_gen(self, ns: str) -> Optional[str]:
        """Payload generation token. insert_jobs rewrites it after its
        batch manifests land, so OTHER processes' caches (a worker that
        outlives a ``"loop"``-protocol drop_ns + re-insert) detect the
        recreated namespace; their own drop_ns only invalidates locally."""
        return _read_json_text(self._gen_path(ns))

    def _resolve_batches(self, ns: str) -> list:
        """The namespace's batch list [(base, docs), ...], cached against
        the generation token. The token is read BEFORE the rescan, so a
        token raced by a concurrent insert merely forces one extra rescan
        later — batch manifests are immutable, never wrong. Duplicate
        bases (a crash-orphaned manifest that raced insert-time cleanup)
        resolve to the newest file."""
        stamp = self._read_gen(ns)
        cached = self._batches.get(ns)
        if cached is not None and cached[0] == stamp:
            return cached[1]
        entries: Dict[int, tuple] = {}
        d = self._ns_dir(ns)
        for name in os.listdir(d):
            if name.startswith("b") and name.endswith(".json"):
                try:
                    b = int(name[1:-5].split("_")[0])
                except ValueError:
                    continue
                path = os.path.join(d, name)
                loaded = _read_json(path)
                if loaded is None:
                    continue
                try:
                    mtime = os.stat(path).st_mtime_ns
                except OSError:
                    mtime = 0
                prev = entries.get(b)
                if prev is None or mtime >= prev[0]:
                    entries[b] = (mtime, loaded)
        batches = sorted((b, docs) for b, (_, docs) in entries.items())
        self._batches[ns] = (stamp, batches)
        return batches

    @staticmethod
    def _lookup_payload(batches: list, jid: int) -> Optional[dict]:
        for base, docs in batches:
            if base <= jid < base + len(docs):
                return docs[jid - base]
        return None

    def _payload_doc(self, ns: str, jid: int) -> dict:
        """One job's payload, DEEP-copied: the cache must stay pristine
        when a caller (user mapfn mutating its value in place) edits the
        returned doc — the old file-per-job scheme re-parsed JSON per
        read, and retries depend on that isolation."""
        doc = self._lookup_payload(self._resolve_batches(ns), jid)
        return copy.deepcopy(doc) if doc is not None else {}

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        idx = self._idx(ns)
        jid = idx.claim(worker_hash(worker), time.time(), preferred_ids, steal)
        if jid < 0:
            return None
        try:
            with open(self._wname(ns, jid), "w") as f:
                f.write(worker)
        except OSError:
            pass  # observability only
        return self._job_doc(ns, jid, idx)

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        mask = 0
        if expect is not None:
            for s in expect:
                mask |= 1 << int(s)
        whash = worker_hash(expect_worker) if expect_worker else 0
        return self._idx(ns).cas_status(job_id, status, mask, whash)

    def get_job(self, ns, job_id):
        idx = self._idx(ns)
        if idx.get(job_id) is None:
            return None
        return self._job_doc(ns, job_id, idx)

    def jobs(self, ns):
        idx = self._idx(ns)
        docs = []
        # one locked pass over the index, ONE batch resolution for the
        # whole snapshot (per-jid resolution would re-read the gen file
        # n times); times/worker sidecars are single-writer, no lock
        batches = self._resolve_batches(ns)
        for jid, (status, reps, whash, started) in enumerate(idx.snapshot()):
            doc = copy.deepcopy(self._lookup_payload(batches, jid)) or {}
            doc.update(_id=jid, status=Status(status), repetitions=reps,
                       worker=whash or None, started_time=started or None,
                       times=_read_json(self._times(ns, jid)))
            wname = _read_json_text(self._wname(ns, jid))
            if wname:
                doc["worker"] = wname
            docs.append(doc)
        return docs

    def _job_doc(self, ns, jid, idx) -> dict:
        state = idx.get(jid)
        status, reps, whash, started = state
        doc = dict(self._payload_doc(ns, jid))
        doc.update(_id=jid, status=Status(status), repetitions=reps,
                   worker=whash or None,
                   started_time=started or None,
                   times=_read_json(self._times(ns, jid)))
        wname = _read_json_text(self._wname(ns, jid))
        if wname:
            doc["worker"] = wname
        return doc

    def job_workers(self, ns):
        """id → worker from the w-sidecars alone — no payload reads, no
        deep copies (the server calls this once per reduce prepare)."""
        out = {}
        idx = self._idx(ns)
        for jid in range(idx.count()):
            wname = _read_json_text(self._wname(ns, jid))
            if wname:
                out[jid] = wname
        return out

    def set_job_times(self, ns, job_id, times):
        _atomic_write_json(self._times(ns, job_id), dict(times))

    def counts(self, ns):
        return self._idx(ns).counts()

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        return self._idx(ns).scavenge(max_retries)

    def requeue_stale(self, ns, older_than_s):
        return self._idx(ns).requeue_stale(time.time() - older_than_s)

    def heartbeat(self, ns, job_id, worker):
        return self._idx(ns).heartbeat(job_id, worker_hash(worker),
                                       time.time())

    def drop_ns(self, ns):
        self._batches.pop(ns, None)
        for stale in (f"{ns}.idx", f"{ns}.gen"):
            try:
                os.remove(os.path.join(self.root, stale))
            except FileNotFoundError:
                pass
        d = os.path.join(self.root, f"{ns}.d")
        if os.path.isdir(d):
            for f in os.listdir(d):
                try:
                    os.remove(os.path.join(d, f))
                except FileNotFoundError:
                    pass
            os.rmdir(d)

    # -- errors ------------------------------------------------------------

    def insert_error(self, worker, msg):
        line = json.dumps({"worker": worker, "msg": msg, "time": time.time()})
        with _FLock(self._lockfile("errors")):
            with open(os.path.join(self.root, "errors.jsonl"), "a") as f:
                f.write(line + "\n")

    def drain_errors(self):
        path = os.path.join(self.root, "errors.jsonl")
        with _FLock(self._lockfile("errors")):
            try:
                with open(path) as f:
                    lines = f.readlines()
                os.remove(path)
            except FileNotFoundError:
                return []
        return [json.loads(l) for l in lines if l.strip()]

    # -- persistent documents ----------------------------------------------

    def _pt_path(self, name: str) -> str:
        return os.path.join(self.root, "pt", f"{name}.json")

    def pt_get(self, name):
        return _read_json(self._pt_path(name))

    def pt_cas(self, name, expected_ts, doc):
        with _FLock(self._lockfile(f"pt_{name}")):
            cur = _read_json(self._pt_path(name))
            cur_ts = cur.get("timestamp") if cur is not None else None
            if cur_ts != expected_ts:
                return False
            _atomic_write_json(self._pt_path(name), doc)
            return True

    def pt_delete(self, name):
        with _FLock(self._lockfile(f"pt_{name}")):
            try:
                os.remove(self._pt_path(name))
            except FileNotFoundError:
                pass


def _read_json_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None
