"""Distributed persistent key/value table.

Analog of reference mapreduce/persistent_table.lua: a named singleton
document shared by every process of a task, used for cross-process and
cross-iteration state (the APRIL-ANN example keeps its model-checkpoint
filename and convergence flag in one, common.lua:57-77). Concurrency control
is the reference's, minus its races:

- optimistic writes: each commit CASes on the document's ``timestamp`` and
  bumps it (persistent_table.lua:41-74's query-match + ``$inc``)
- an advisory spin lock built from the same CAS (the findAndModify spin
  lock of persistent_table.lua:113-161)
- reserved keys are rejected (persistent_table.lua:95-110)
- ``read_only`` mode forbids mutation (persistent_table.lua:176-251)
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, Optional

from lua_mapreduce_tpu.coord.jobstore import JobStore

_RESERVED = ("timestamp", "locked", "_id")


class ConflictError(RuntimeError):
    """Another writer committed since this table last refreshed."""


class PersistentTable:
    """Dict-like proxy over a persistent document in a JobStore.

    Local reads/writes hit a cache; ``update()`` commits dirty state with an
    optimistic CAS (raising :class:`ConflictError` on a lost race) or, when
    clean, refreshes from the store. ``lock()``/``unlock()`` give advisory
    mutual exclusion for read-modify-write sections.
    """

    def __init__(self, name: str, store: JobStore, read_only: bool = False):
        self._name = name
        self._store = store
        self._read_only = read_only
        self._ts: Optional[int] = None
        self._data: Dict[str, Any] = {}
        self._dirty_keys: set = set()   # locally modified, uncommitted keys
        self._locked = False   # the advisory-lock flag as of last refresh
        self.refresh()

    # -- core protocol -----------------------------------------------------

    def refresh(self) -> None:
        """Pull the latest committed document. Locally-dirty keys keep
        their local values; every other key takes the committed value — so
        the ConflictError → refresh() → update() retry never reverts
        another writer's commit to a key this table did not touch."""
        doc = self._store.pt_get(self._name)
        if doc is None:
            self._ts = None
            self._data = {k: v for k, v in self._data.items()
                          if k in self._dirty_keys}
            return
        committed = {k: v for k, v in doc.items() if k not in _RESERVED}
        for k in self._dirty_keys:
            if k in self._data:
                committed[k] = self._data[k]
        self._ts = doc["timestamp"]
        self._locked = bool(doc.get("locked", False))
        self._data = committed

    def update(self) -> None:
        """Commit dirty state (CAS on timestamp), or refresh when clean
        (the dual role of persistent_table.lua's ``:update``)."""
        if not self._dirty_keys:
            self.refresh()
            return
        self._assert_writable()
        new_ts = (self._ts or 0) + 1
        doc = dict(self._data)
        doc["timestamp"] = new_ts
        if self._locked:
            # committing inside a lock() section must not release the lock
            doc["locked"] = True
        if not self._store.pt_cas(self._name, self._ts, doc):
            raise ConflictError(
                f"persistent table {self._name!r}: concurrent commit beat "
                f"timestamp {self._ts}; refresh() and retry")
        self._ts = new_ts
        self._dirty_keys.clear()

    def set(self, mapping: Dict[str, Any]) -> None:
        """Bulk local assignment (commit with update())."""
        for k, v in mapping.items():
            self[k] = v

    def drop(self) -> None:
        self._assert_writable()
        self._store.pt_delete(self._name)
        self._ts, self._data = None, {}
        self._dirty_keys.clear()

    # -- advisory lock (persistent_table.lua:113-161) ----------------------

    def lock(self, poll: float = 0.1, timeout: Optional[float] = None,
             waiter=None) -> None:
        self._assert_writable()
        # contention waits ride the injectable Waiter (lmr-sched,
        # DESIGN §23 / lint LMR011): the default NullWaiter sleeps
        # exactly like the old poll; callers on a notify-capable store
        # may pass its channel's waiter for prompt handoff
        if waiter is None:
            from lua_mapreduce_tpu.sched.waiter import NullWaiter
            waiter = NullWaiter()
        deadline = None if timeout is None else time.time() + timeout
        while True:
            doc = self._store.pt_get(self._name)
            ts = doc["timestamp"] if doc else None
            locked = bool(doc.get("locked")) if doc else False
            if not locked:
                new = dict(doc or {})
                new["locked"] = True
                new["timestamp"] = (ts or 0) + 1
                if self._store.pt_cas(self._name, ts, new):
                    self._ts = new["timestamp"]
                    self._locked = True
                    return
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"lock({self._name!r}) timed out")
            waiter.wait(poll)

    def unlock(self) -> None:
        self._assert_writable()
        while True:
            doc = self._store.pt_get(self._name)
            if doc is None or not doc.get("locked"):
                self._locked = False
                return
            new = dict(doc)
            new["locked"] = False
            new["timestamp"] = doc["timestamp"] + 1
            if self._store.pt_cas(self._name, doc["timestamp"], new):
                self._ts = new["timestamp"]
                self._locked = False
                return

    # -- dict protocol -----------------------------------------------------

    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def __setitem__(self, key: str, value: Any) -> None:
        self._assert_writable()
        if key in _RESERVED or key.startswith("_"):
            raise KeyError(f"reserved key {key!r} "
                           "(reference persistent_table.lua:95-110)")
        self._data[key] = value
        self._dirty_keys.add(key)

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def keys(self):
        return self._data.keys()

    def items(self):
        return self._data.items()

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    @property
    def dirty(self) -> bool:
        return bool(self._dirty_keys)

    @property
    def read_only(self) -> bool:
        return self._read_only

    def _assert_writable(self) -> None:
        if self._read_only:
            raise PermissionError(
                f"persistent table {self._name!r} is read-only")


def utest() -> None:
    """Self-test (reference persistent_table.lua:256-264: two clients
    round-tripping one document, optimistic conflict, lock)."""
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore

    store = MemJobStore()
    a = PersistentTable("_pt_utest", store)
    a["model"] = "m.ckpt"
    a.update()
    b = PersistentTable("_pt_utest", store)
    assert b["model"] == "m.ckpt"
    b["model"] = "m2.ckpt"
    b.update()
    a["model"] = "retried-write"           # a still holds the old stamp
    try:
        a.update()
    except ConflictError:
        pass
    else:
        raise AssertionError("stale write must raise ConflictError")
    a.refresh()                            # new stamp; pending write kept
    a.update()
    assert PersistentTable("_pt_utest", store)["model"] == "retried-write"

    ro = PersistentTable("_pt_utest", store, read_only=True)
    try:
        ro["model"] = "x"
    except PermissionError:
        pass
    else:
        raise AssertionError("read_only must reject writes")

    a.lock()
    a.unlock()
    a.drop()
