"""Job store — the coordination backend interface and in-memory engine.

The reference coordinates everything through MongoDB collections
(SURVEY.md §2.6): ``map_jobs``/``red_jobs`` job queues claimed by atomically
flipping a status field (task.lua:258-343), a ``task`` singleton document as
the orchestrator checkpoint (task.lua:96-116), an ``errors`` collection
(cnn.lua:62-78), and ``persistent_table`` documents with optimistic
timestamps (persistent_table.lua:41-74). This module defines the same five
capabilities as an explicit interface whose claim protocol is an atomic
compare-and-swap — no claim/readback race window.
"""

from __future__ import annotations

import abc
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status

CLAIMABLE = (Status.WAITING, Status.BROKEN)


def make_job(key: Any, value: Any) -> dict:
    """Immutable part of a job document (reference utils.lua:87-98
    ``make_job`` schema; mutable claim state lives in the store index)."""
    return {"key": key, "value": value, "creation_time": time.time()}


class JobStore(abc.ABC):
    """Coordination-plane interface (control plane only — bulk data goes
    through the storage layer, never through the job store)."""

    # -- task singleton (orchestrator checkpoint, task.lua:96-116) ---------

    @abc.abstractmethod
    def put_task(self, doc: dict) -> None: ...

    @abc.abstractmethod
    def get_task(self) -> Optional[dict]: ...

    @abc.abstractmethod
    def update_task(self, fields: dict) -> None: ...

    @abc.abstractmethod
    def delete_task(self) -> None: ...

    # -- job queues (map_jobs / red_jobs analogs) --------------------------

    @abc.abstractmethod
    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        """Append job docs with status WAITING; returns their ids."""

    @abc.abstractmethod
    def claim(self, ns: str, worker: str,
              preferred_ids: Optional[Sequence[int]] = None,
              steal: bool = True) -> Optional[dict]:
        """Atomically claim one WAITING|BROKEN job → RUNNING for ``worker``.

        Single-CAS replacement for the reference's update-then-readback
        (task.lua:294-309 and its FIXME races). ``preferred_ids`` implements
        the map-affinity cache (task.lua:249-292): those ids are tried
        first so a worker re-claims "its" map jobs across iterations;
        ``steal=False`` restricts the claim to the preferred ids (the worker
        steals others' jobs only after MAX_IDLE_COUNT idle polls).
        Returns the full job doc (with ``_id``, ``status``, ``repetitions``)
        or None if nothing is claimable.
        """

    @abc.abstractmethod
    def set_job_status(self, ns: str, job_id: int, status: Status,
                       expect: Optional[Sequence[Status]] = None,
                       expect_worker: Optional[str] = None) -> bool:
        """CAS a job's status; bumps ``repetitions`` when moving to BROKEN
        (job.lua:322-342). Returns False if ``expect`` (statuses) or
        ``expect_worker`` (claim ownership) does not match — a worker whose
        claim was stale-requeued and re-claimed by someone else must not be
        able to clobber the new claimant's state."""

    @abc.abstractmethod
    def get_job(self, ns: str, job_id: int) -> Optional[dict]: ...

    @abc.abstractmethod
    def jobs(self, ns: str) -> List[dict]: ...

    def job_workers(self, ns: str) -> Dict[int, str]:
        """job id → claiming worker name, for jobs a worker has touched.
        Lightweight producer lookup (server.lua:286-289 queries map jobs
        for hostnames): the default walks jobs(); file-backed stores
        override to read just the worker sidecars, skipping the payload
        deep-copies."""
        out = {}
        for doc in self.jobs(ns):
            if isinstance(doc.get("worker"), str):
                out[int(doc["_id"])] = doc["worker"]
        return out

    @abc.abstractmethod
    def set_job_times(self, ns: str, job_id: int, times: dict) -> None:
        """Record per-job timing for stats (job.lua:117-152)."""

    @abc.abstractmethod
    def counts(self, ns: str) -> Dict[Status, int]:
        """Per-status counts — the server's barrier poll
        (server.lua:186-234)."""

    @abc.abstractmethod
    def scavenge(self, ns: str, max_retries: int = MAX_JOB_RETRIES) -> int:
        """BROKEN jobs with repetitions ≥ max_retries → FAILED
        (server.lua:192-205). Returns how many were failed."""

    @abc.abstractmethod
    def requeue_stale(self, ns: str, older_than_s: float) -> int:
        """RUNNING or FINISHED jobs SILENT for more than ``older_than_s``
        → BROKEN (re-claimable). Silence is measured from the job's last
        liveness signal — its claim time or its worker's last
        :meth:`heartbeat` — so a legitimately long job whose worker keeps
        beating is never requeued mid-run. Beats stop when the job body
        returns, so ``older_than_s`` must exceed the heartbeat interval
        PLUS the worst-case finish/publish time (the FINISHED→WRITTEN
        window) — but not the longest job. Covers hard-killed
        workers that never mark their job broken — including a kill
        between the FINISHED and WRITTEN transitions — a gap the
        reference leaves open (its recovery relies on the worker's own
        xpcall handler, worker.lua:116-131). Returns count."""

    def heartbeat(self, ns: str, job_id: int, worker: str) -> bool:
        """Refresh the liveness timestamp of a RUNNING|FINISHED job this
        worker owns, so :meth:`requeue_stale` measures silence instead of
        elapsed time. Returns False when the claim is lost (requeued and
        re-claimed), the job is in another state, or the store does not
        track liveness (this default)."""
        return False

    @abc.abstractmethod
    def drop_ns(self, ns: str) -> None: ...

    # -- errors stream (cnn.lua:62-78) -------------------------------------

    @abc.abstractmethod
    def insert_error(self, worker: str, msg: str) -> None: ...

    @abc.abstractmethod
    def drain_errors(self) -> List[dict]: ...

    # -- persistent documents (persistent_table backing) -------------------

    @abc.abstractmethod
    def pt_get(self, name: str) -> Optional[dict]: ...

    @abc.abstractmethod
    def pt_cas(self, name: str, expected_ts: Optional[int], doc: dict) -> bool:
        """Write ``doc`` iff the stored timestamp equals ``expected_ts``
        (None = must not exist). The optimistic-concurrency primitive of
        persistent_table.lua:41-74."""

    @abc.abstractmethod
    def pt_delete(self, name: str) -> None: ...


class MemJobStore(JobStore):
    """In-process store: one lock, plain dicts. The engine for
    single-process elastic pools (server + worker threads)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._task: Optional[dict] = None
        self._jobs: Dict[str, List[dict]] = {}
        self._errors: List[dict] = []
        self._pt: Dict[str, dict] = {}

    # -- task --------------------------------------------------------------

    def put_task(self, doc: dict) -> None:
        with self._lock:
            self._task = dict(doc)

    def get_task(self) -> Optional[dict]:
        with self._lock:
            return dict(self._task) if self._task is not None else None

    def update_task(self, fields: dict) -> None:
        with self._lock:
            if self._task is None:
                raise RuntimeError("no task document")
            self._task.update(fields)

    def delete_task(self) -> None:
        with self._lock:
            self._task = None

    # -- jobs --------------------------------------------------------------

    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        with self._lock:
            queue = self._jobs.setdefault(ns, [])
            base = len(queue)
            ids = []
            for i, doc in enumerate(docs):
                d = dict(doc)
                d.update(_id=base + i, status=Status.WAITING, repetitions=0,
                         worker=None, started_time=None, hb_time=None,
                         times=None)
                queue.append(d)
                ids.append(base + i)
            return ids

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        with self._lock:
            queue = self._jobs.get(ns, [])

            def try_claim(d):
                if d["status"] in CLAIMABLE:
                    d["status"] = Status.RUNNING
                    d["worker"] = worker
                    d["started_time"] = time.time()
                    d["hb_time"] = None   # fresh claim, fresh silence clock
                    return dict(d)
                return None

            for jid in (preferred_ids or ()):
                if 0 <= jid < len(queue):
                    got = try_claim(queue[jid])
                    if got:
                        return got
            if steal:
                for d in queue:
                    got = try_claim(d)
                    if got:
                        return got
            return None

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if expect is not None and d["status"] not in expect:
                return False
            if expect_worker is not None and d["worker"] != expect_worker:
                return False
            if status == Status.BROKEN:
                d["repetitions"] += 1
            d["status"] = status
            return True

    def get_job(self, ns, job_id):
        with self._lock:
            queue = self._jobs.get(ns, [])
            return dict(queue[job_id]) if 0 <= job_id < len(queue) else None

    def jobs(self, ns):
        with self._lock:
            return [dict(d) for d in self._jobs.get(ns, [])]

    def set_job_times(self, ns, job_id, times):
        with self._lock:
            queue = self._jobs.get(ns)
            if queue is not None and 0 <= job_id < len(queue):
                queue[job_id]["times"] = dict(times)
            # dropped namespace (straggler finishing late): ignore

    def counts(self, ns):
        with self._lock:
            out = {s: 0 for s in Status}
            for d in self._jobs.get(ns, []):
                out[d["status"]] += 1
            return out

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        with self._lock:
            n = 0
            for d in self._jobs.get(ns, []):
                if d["status"] == Status.BROKEN and d["repetitions"] >= max_retries:
                    d["status"] = Status.FAILED
                    n += 1
            return n

    def requeue_stale(self, ns, older_than_s):
        with self._lock:
            n = 0
            cutoff = time.time() - older_than_s
            for d in self._jobs.get(ns, []):
                live = max(d["started_time"] or 0.0, d.get("hb_time") or 0.0)
                if (d["status"] in (Status.RUNNING, Status.FINISHED) and
                        d["started_time"] is not None and live < cutoff):
                    d["status"] = Status.BROKEN
                    d["repetitions"] += 1
                    n += 1
            return n

    def heartbeat(self, ns, job_id, worker):
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if d["status"] not in (Status.RUNNING, Status.FINISHED) \
                    or d["worker"] != worker:
                return False
            d["hb_time"] = time.time()
            return True

    def drop_ns(self, ns):
        with self._lock:
            self._jobs.pop(ns, None)

    # -- errors ------------------------------------------------------------

    def insert_error(self, worker, msg):
        with self._lock:
            self._errors.append({"worker": worker, "msg": msg,
                                 "time": time.time()})

    def drain_errors(self):
        with self._lock:
            out, self._errors = self._errors, []
            return out

    # -- persistent documents ----------------------------------------------

    def pt_get(self, name):
        with self._lock:
            doc = self._pt.get(name)
            return dict(doc) if doc is not None else None

    def pt_cas(self, name, expected_ts, doc):
        with self._lock:
            cur = self._pt.get(name)
            cur_ts = cur.get("timestamp") if cur is not None else None
            if cur_ts != expected_ts:
                return False
            self._pt[name] = dict(doc)
            return True

    def pt_delete(self, name):
        with self._lock:
            self._pt.pop(name, None)


def utest() -> None:
    """Self-test (reference task.lua:365-367 utest role): the claim /
    status machine on the in-memory store."""
    s = MemJobStore()
    ids = s.insert_jobs("map_jobs", [make_job(f"k{i}", i) for i in range(3)])
    assert ids == [0, 1, 2]
    doc = s.claim("map_jobs", "w1")
    assert doc is not None and doc["status"] == Status.RUNNING
    jid = doc["_id"]
    assert s.set_job_status("map_jobs", jid, Status.FINISHED,
                            expect=(Status.RUNNING,), expect_worker="w1")
    assert not s.set_job_status("map_jobs", jid, Status.WRITTEN,
                                expect=(Status.FINISHED,),
                                expect_worker="other")   # ownership CAS
    assert s.set_job_status("map_jobs", jid, Status.WRITTEN,
                            expect=(Status.FINISHED,), expect_worker="w1")
    c = s.counts("map_jobs")
    assert c[Status.WRITTEN] == 1 and c[Status.WAITING] == 2
    assert len(s.job_workers("map_jobs")) == 1
