"""Job store — the coordination backend interface and in-memory engine.

The reference coordinates everything through MongoDB collections
(SURVEY.md §2.6): ``map_jobs``/``red_jobs`` job queues claimed by atomically
flipping a status field (task.lua:258-343), a ``task`` singleton document as
the orchestrator checkpoint (task.lua:96-116), an ``errors`` collection
(cnn.lua:62-78), and ``persistent_table`` documents with optimistic
timestamps (persistent_table.lua:41-74). This module defines the same five
capabilities as an explicit interface whose claim protocol is an atomic
compare-and-swap — no claim/readback race window.
"""

from __future__ import annotations

import abc
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status

CLAIMABLE = (Status.WAITING, Status.BROKEN)


def make_job(key: Any, value: Any) -> dict:
    """Immutable part of a job document (reference utils.lua:87-98
    ``make_job`` schema; mutable claim state lives in the store index)."""
    return {"key": key, "value": value, "creation_time": time.time()}


class JobStore(abc.ABC):
    """Coordination-plane interface (control plane only — bulk data goes
    through the storage layer, never through the job store)."""

    # -- control-plane round-trip accounting -------------------------------
    #
    # Each claim/commit lock-or-IO cycle through THIS instance bumps a
    # counter: "claim" for claim()/claim_batch() passes, "commit" for
    # status/times writes. In-process pools share one instance, so the
    # server's IterationStats fold sees the whole pool's control traffic;
    # in multi-process pools each process counts its own (coord_bench
    # aggregates the workers' counters explicitly). A single class-level
    # lock guards lazy creation AND the read-modify-write — shared worker
    # threads must not lose increments of the protocol's effectiveness
    # metric (contention is negligible: one tiny bump per store round
    # trip that itself does real IO).

    _rounds_lock = threading.Lock()

    def _bump(self, op: str, n: int = 1) -> None:
        with JobStore._rounds_lock:
            r = getattr(self, "rounds", None)
            if r is None:
                r = self.rounds = {"claim": 0, "commit": 0}
            r[op] = r.get(op, 0) + n

    def round_counts(self) -> Dict[str, int]:
        """Snapshot of {"claim": ..., "commit": ...} round trips so far."""
        with JobStore._rounds_lock:
            return dict(getattr(self, "rounds", None) or
                        {"claim": 0, "commit": 0})

    # -- task singleton (orchestrator checkpoint, task.lua:96-116) ---------

    @abc.abstractmethod
    def put_task(self, doc: dict) -> None: ...

    @abc.abstractmethod
    def get_task(self) -> Optional[dict]: ...

    @abc.abstractmethod
    def update_task(self, fields: dict) -> None: ...

    @abc.abstractmethod
    def delete_task(self) -> None: ...

    # -- job queues (map_jobs / red_jobs analogs) --------------------------

    @abc.abstractmethod
    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        """Append job docs with status WAITING; returns their ids."""

    @abc.abstractmethod
    def claim(self, ns: str, worker: str,
              preferred_ids: Optional[Sequence[int]] = None,
              steal: bool = True) -> Optional[dict]:
        """Atomically claim one WAITING|BROKEN job → RUNNING for ``worker``.

        Single-CAS replacement for the reference's update-then-readback
        (task.lua:294-309 and its FIXME races). ``preferred_ids`` implements
        the map-affinity cache (task.lua:249-292): those ids are tried
        first so a worker re-claims "its" map jobs across iterations;
        ``steal=False`` restricts the claim to the preferred ids (the worker
        steals others' jobs only after MAX_IDLE_COUNT idle polls).
        Returns the full job doc (with ``_id``, ``status``, ``repetitions``)
        or None if nothing is claimable.
        """

    def claim_batch(self, ns: str, worker: str, k: int = 1,
                    preferred_ids: Optional[Sequence[int]] = None,
                    steal: bool = True) -> List[dict]:
        """Atomically claim up to ``k`` WAITING|BROKEN jobs → RUNNING for
        ``worker`` — the batch-lease entry point. One job's claim is one
        control-plane round trip; at the ~2,000-tiny-jobs fan-in the
        reference README targets, those round trips dominate wall time
        once PR 1 pipelined the data plane. A batch claim leases k jobs
        in ONE locked index pass; the worker executes them back-to-back
        and retires them through :meth:`commit_batch`.

        Semantics per job are identical to :meth:`claim` (preferred ids
        first, ``steal=False`` restricts to them); every leased job gets
        its own fresh liveness clock, so :meth:`requeue_stale` judges
        each batch member independently — a SIGKILLed worker's whole
        batch returns to the pool, job by job.

        This default serves stores without a native batch path: k single
        claims (correct, unamortized). Returns the claimed docs in claim
        order; [] when nothing is claimable."""
        out = []
        for _ in range(max(1, k)):
            doc = self.claim(ns, worker, preferred_ids, steal)
            if doc is None:
                break
            out.append(doc)
        return out

    def commit_batch(self, ns: str, worker: str,
                     entries: Sequence[tuple]) -> List[int]:
        """Retire a batch of executed jobs: for each ``(job_id, times)``
        entry, RUNNING→FINISHED→WRITTEN CASed on ``worker``'s ownership,
        with the job times recorded between the two transitions (the
        v1 per-job finish discipline, amortized). Entries
        whose claim was lost (stale-requeued and re-claimed) are skipped
        without disturbing the new claimant. Returns the job ids whose
        commit landed.

        This default loops the single-job protocol; batch-native stores
        override to do each transition sweep in one locked pass."""
        done = []
        for job_id, times in entries:
            if not self.set_job_status(ns, job_id, Status.FINISHED,
                                       expect=(Status.RUNNING,),
                                       expect_worker=worker):
                continue
            if times is not None:
                self.set_job_times(ns, job_id, times)
            self.set_job_status(ns, job_id, Status.WRITTEN,
                                expect=(Status.FINISHED,),
                                expect_worker=worker)
            done.append(job_id)
        return done

    def release_batch(self, ns: str, worker: str,
                      job_ids: Sequence[int]) -> int:
        """Return leased-but-unstarted jobs to the pool: RUNNING→WAITING
        CASed on ownership, WITHOUT bumping repetitions — these jobs
        never ran, so they must not creep toward the scavenger's FAILED
        threshold. Used when a batch aborts partway (user-code error);
        a SIGKILLed worker never gets to call this, which is fine — the
        stale requeue recovers its leases as BROKEN instead. Returns how
        many were released."""
        n = 0
        for job_id in job_ids:
            if self.set_job_status(ns, job_id, Status.WAITING,
                                   expect=(Status.RUNNING,),
                                   expect_worker=worker):
                n += 1
        return n

    def heartbeat_batch(self, ns: str, job_ids: Sequence[int],
                        worker: str) -> int:
        """:meth:`heartbeat` for every leased job of a batch — the batch
        lease runs ONE beat thread for all its jobs. Returns how many
        beats landed (jobs already committed/requeued simply miss)."""
        n = 0
        for job_id in job_ids:
            if self.heartbeat(ns, job_id, worker):
                n += 1
        return n

    @abc.abstractmethod
    def set_job_status(self, ns: str, job_id: int, status: Status,
                       expect: Optional[Sequence[Status]] = None,
                       expect_worker: Optional[str] = None) -> bool:
        """CAS a job's status; bumps ``repetitions`` when moving to BROKEN
        (job.lua:322-342). Returns False if ``expect`` (statuses) or
        ``expect_worker`` (claim ownership) does not match — a worker whose
        claim was stale-requeued and re-claimed by someone else must not be
        able to clobber the new claimant's state."""

    @abc.abstractmethod
    def get_job(self, ns: str, job_id: int) -> Optional[dict]: ...

    @abc.abstractmethod
    def jobs(self, ns: str) -> List[dict]: ...

    def job_workers(self, ns: str) -> Dict[int, str]:
        """job id → claiming worker name, for jobs a worker has touched.
        Lightweight producer lookup (server.lua:286-289 queries map jobs
        for hostnames): the default walks jobs(); file-backed stores
        override to read just the worker sidecars, skipping the payload
        deep-copies."""
        out = {}
        for doc in self.jobs(ns):
            if isinstance(doc.get("worker"), str):
                out[int(doc["_id"])] = doc["worker"]
        return out

    @abc.abstractmethod
    def set_job_times(self, ns: str, job_id: int, times: dict) -> None:
        """Record per-job timing for stats (job.lua:117-152)."""

    @abc.abstractmethod
    def counts(self, ns: str) -> Dict[Status, int]:
        """Per-status counts — the server's barrier poll
        (server.lua:186-234)."""

    @abc.abstractmethod
    def scavenge(self, ns: str, max_retries: int = MAX_JOB_RETRIES) -> int:
        """BROKEN jobs with repetitions ≥ max_retries → FAILED
        (server.lua:192-205). Returns how many were failed."""

    @abc.abstractmethod
    def requeue_stale(self, ns: str, older_than_s: float) -> int:
        """RUNNING or FINISHED jobs SILENT for more than ``older_than_s``
        → BROKEN (re-claimable). Silence is measured from the job's last
        liveness signal — its claim time or its worker's last
        :meth:`heartbeat` — so a legitimately long job whose worker keeps
        beating is never requeued mid-run. Beats stop when the job body
        returns, so ``older_than_s`` must exceed the heartbeat interval
        PLUS the worst-case finish/publish time (the FINISHED→WRITTEN
        window) — but not the longest job. Covers hard-killed
        workers that never mark their job broken — including a kill
        between the FINISHED and WRITTEN transitions — a gap the
        reference leaves open (its recovery relies on the worker's own
        xpcall handler, worker.lua:116-131). Returns count."""

    def heartbeat(self, ns: str, job_id: int, worker: str) -> bool:
        """Refresh the liveness timestamp of a RUNNING|FINISHED job this
        worker owns — or holds the SHADOW lease of (speculation, see
        :meth:`speculate`) — so :meth:`requeue_stale` measures silence
        instead of elapsed time. Returns False when the claim is lost
        (requeued and re-claimed), the job is in another state, or the
        store does not track liveness (this default). Doubling as the
        worker's cheap lease-revocation probe: a False on a lease the
        worker believed live means the other duplicate committed (or
        the scavenger intervened) and remaining work is wasted."""
        return False

    # -- duplicate leases (speculative execution, DESIGN §21) --------------

    def speculate(self, ns: str, job_id: int) -> bool:
        """Mark a RUNNING job speculation-OPEN so one other worker may
        clone its lease via :meth:`claim_spec` — the straggler
        detector's op. CASed on (RUNNING, no existing speculation):
        repeated detector passes are idempotent, and a job carries at
        most ONE shadow lease at a time. The original claimant keeps
        its lease untouched; FIRST-COMMIT-WINS arbitration happens at
        commit time (the one RUNNING|FINISHED→WRITTEN transition — the
        loser's commit fails the status CAS and degrades to a
        zero-repetition no-op, never a double commit, never a rep bump
        against either worker). Stores without speculation support
        keep this default: the detector simply never launches clones."""
        return False

    def claim_spec(self, ns: str, worker: str) -> Optional[dict]:
        """Take ONE speculation-open shadow lease for ``worker``:
        returns the cloned job doc (``speculative=True``, ``worker`` =
        the ORIGINAL claimant) or None. A worker never shadows its own
        job; candidates whose claimant sits on a different placement
        tag (engine/placement.py's failure domains, hashed from the
        worker name) are preferred — a straggler's slowness is often
        its domain's, and a clone sharing the domain would likely share
        the fate. Scan order is lowest id first within each preference
        class on every store; the protocol model abstracts the tag
        preference away (it has no placement), so its traces replay
        exactly on two-worker boxes — the gate's pinned configuration —
        where every candidate shares one preference class."""
        return None

    def cancel_spec(self, ns: str, job_id: int, worker: str) -> bool:
        """Dissolve a shadow lease ``worker`` holds — the loser /
        clone-failure path. The job's status and repetitions are NEVER
        touched: the original claimant still owns the lease, so a
        failed or revoked clone costs nothing but its own wasted time.
        ``worker=None`` clears any speculation regardless of holder
        (the detector's retraction)."""
        return False

    @abc.abstractmethod
    def drop_ns(self, ns: str) -> None: ...

    # -- fault classification (DESIGN §19) ---------------------------------

    def classify(self, exc: BaseException):
        """Transient/permanent verdict for exceptions this store's RPCs
        can raise — the coord-plane twin of ``Store.classify``, consumed
        by the RetryingJobStore wrapper. The central taxonomy already
        maps the index engines' raisables (bare OSError from a failed
        jsx op → transient; ENOENT/EACCES → permanent; NoTaskError /
        ConcurrentInsertError are classified by type)."""
        from lua_mapreduce_tpu.faults.errors import classify_exception
        return classify_exception(exc)

    # -- errors stream (cnn.lua:62-78) -------------------------------------

    @abc.abstractmethod
    def insert_error(self, worker: str, msg: str,
                     info: Optional[dict] = None) -> None:
        """Append to the errors stream. ``info`` (optional) carries the
        structured post-mortem fields — ``exc_class``, ``classification``
        ('user-code' | 'infra-transient' | 'infra-permanent'), job
        context — merged into the entry next to the traceback ``msg``,
        so drained errors can distinguish infra from user-code failures
        without parsing text."""

    @abc.abstractmethod
    def drain_errors(self) -> List[dict]: ...

    # -- persistent documents (persistent_table backing) -------------------

    @abc.abstractmethod
    def pt_get(self, name: str) -> Optional[dict]: ...

    @abc.abstractmethod
    def pt_cas(self, name: str, expected_ts: Optional[int], doc: dict) -> bool:
        """Write ``doc`` iff the stored timestamp equals ``expected_ts``
        (None = must not exist). The optimistic-concurrency primitive of
        persistent_table.lua:41-74."""

    @abc.abstractmethod
    def pt_delete(self, name: str) -> None: ...


class MemJobStore(JobStore):
    """In-process store: one lock, plain dicts. The engine for
    single-process elastic pools (server + worker threads)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._task: Optional[dict] = None
        self._jobs: Dict[str, List[dict]] = {}
        self._errors: List[dict] = []
        self._pt: Dict[str, dict] = {}

    # -- task --------------------------------------------------------------

    def put_task(self, doc: dict) -> None:
        with self._lock:
            self._task = dict(doc)

    def get_task(self) -> Optional[dict]:
        with self._lock:
            return dict(self._task) if self._task is not None else None

    def update_task(self, fields: dict) -> None:
        with self._lock:
            if self._task is None:
                from lua_mapreduce_tpu.faults.errors import NoTaskError
                raise NoTaskError("no task document")
            self._task.update(fields)

    def delete_task(self) -> None:
        with self._lock:
            self._task = None

    # -- jobs --------------------------------------------------------------

    def insert_jobs(self, ns: str, docs: Sequence[dict]) -> List[int]:
        with self._lock:
            queue = self._jobs.setdefault(ns, [])
            base = len(queue)
            ids = []
            for i, doc in enumerate(docs):
                d = dict(doc)
                d.update(_id=base + i, status=Status.WAITING, repetitions=0,
                         worker=None, started_time=None, hb_time=None,
                         times=None, spec_state=0, spec_worker=None)
                queue.append(d)
                ids.append(base + i)
            return ids

    def claim(self, ns, worker, preferred_ids=None, steal=True):
        got = self.claim_batch(ns, worker, 1, preferred_ids, steal)
        return got[0] if got else None

    def claim_batch(self, ns, worker, k=1, preferred_ids=None, steal=True):
        self._bump("claim")
        now = time.time()      # decided before the lock (lease math)
        with self._lock:
            queue = self._jobs.get(ns, [])
            out = []

            def try_claim(d):
                if d["status"] in CLAIMABLE and len(out) < k:
                    d["status"] = Status.RUNNING
                    d["worker"] = worker
                    d["started_time"] = now
                    d["hb_time"] = None   # fresh claim, fresh silence clock
                    d["spec_state"] = 0   # no carried shadow lease
                    d["spec_worker"] = None
                    out.append(dict(d))

            for jid in (preferred_ids or ()):
                if 0 <= jid < len(queue):
                    try_claim(queue[jid])
            if steal:
                for d in queue:
                    if len(out) >= k:
                        break
                    try_claim(d)
            return out

    @staticmethod
    def _owner_ok(d: dict, worker: str) -> bool:
        """Duplicate-lease ownership (DESIGN §21): the claimant owns the
        job and, while a shadow lease is taken, so does the speculative
        worker — the status CAS arbitrates first-commit-wins."""
        return (d["worker"] == worker
                or (d.get("spec_state") == 2
                    and d.get("spec_worker") == worker))

    @staticmethod
    def _clear_spec_on_unlease(d: dict, status: Status) -> None:
        """Leaving the leased states dissolves any shadow lease: a
        re-claimed job must never be committable by a stale clone."""
        if status in (Status.WAITING, Status.BROKEN):
            d["spec_state"] = 0
            d["spec_worker"] = None

    def commit_batch(self, ns, worker, entries):
        self._bump("commit")
        with self._lock:
            queue = self._jobs.get(ns, [])
            done = []
            for job_id, times in entries:
                if not (0 <= job_id < len(queue)):
                    continue
                d = queue[job_id]
                # RUNNING|FINISHED, matching the index engines: a job a
                # crashed commit left FINISHED must retire, not wait for
                # the stale requeue to re-execute completed work. A
                # speculative loser's entry fails the status check here
                # (the winner already moved it to WRITTEN) and is
                # skipped without any state change — first-commit-wins
                if (d["status"] not in (Status.RUNNING, Status.FINISHED)
                        or not self._owner_ok(d, worker)):
                    continue       # claim lost: the new claimant owns it
                if times is not None:
                    d["times"] = dict(times)
                d["status"] = Status.WRITTEN
                done.append(job_id)
            return done

    def heartbeat_batch(self, ns, job_ids, worker):
        now = time.time()
        with self._lock:
            queue = self._jobs.get(ns, [])
            n = 0
            for job_id in job_ids:
                if not (0 <= job_id < len(queue)):
                    continue
                d = queue[job_id]
                if d["status"] in (Status.RUNNING, Status.FINISHED) \
                        and self._owner_ok(d, worker):
                    d["hb_time"] = now
                    n += 1
            return n

    def set_job_status(self, ns, job_id, status, expect=None,
                       expect_worker=None):
        self._bump("commit")
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if expect is not None and d["status"] not in expect:
                return False
            if expect_worker is not None \
                    and not self._owner_ok(d, expect_worker):
                return False
            if status == Status.BROKEN:
                d["repetitions"] += 1
            d["status"] = status
            self._clear_spec_on_unlease(d, status)
            return True

    def get_job(self, ns, job_id):
        with self._lock:
            queue = self._jobs.get(ns, [])
            return dict(queue[job_id]) if 0 <= job_id < len(queue) else None

    def jobs(self, ns):
        with self._lock:
            return [dict(d) for d in self._jobs.get(ns, [])]

    def set_job_times(self, ns, job_id, times):
        self._bump("commit")
        with self._lock:
            queue = self._jobs.get(ns)
            if queue is not None and 0 <= job_id < len(queue):
                queue[job_id]["times"] = dict(times)
            # dropped namespace (straggler finishing late): ignore

    def counts(self, ns):
        with self._lock:
            out = {s: 0 for s in Status}
            for d in self._jobs.get(ns, []):
                out[d["status"]] += 1
            return out

    def scavenge(self, ns, max_retries=MAX_JOB_RETRIES):
        with self._lock:
            n = 0
            for d in self._jobs.get(ns, []):
                if d["status"] == Status.BROKEN and d["repetitions"] >= max_retries:
                    d["status"] = Status.FAILED
                    n += 1
            return n

    def requeue_stale(self, ns, older_than_s):
        cutoff = time.time() - older_than_s
        with self._lock:
            n = 0
            for d in self._jobs.get(ns, []):
                live = max(d["started_time"] or 0.0, d.get("hb_time") or 0.0)
                if (d["status"] in (Status.RUNNING, Status.FINISHED) and
                        d["started_time"] is not None and live < cutoff):
                    d["status"] = Status.BROKEN
                    d["repetitions"] += 1
                    # requeue dissolves any shadow lease (clone beats
                    # count as liveness — reaching here means BOTH
                    # holders went silent)
                    self._clear_spec_on_unlease(d, Status.BROKEN)
                    n += 1
            return n

    def heartbeat(self, ns, job_id, worker):
        now = time.time()
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if d["status"] not in (Status.RUNNING, Status.FINISHED) \
                    or not self._owner_ok(d, worker):
                return False
            d["hb_time"] = now
            return True

    # -- duplicate leases (speculative execution, DESIGN §21) --------------

    def speculate(self, ns, job_id):
        self._bump("commit")
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if d["status"] != Status.RUNNING or d.get("spec_state"):
                return False
            d["spec_state"] = 1
            d["spec_worker"] = None
            return True

    def claim_spec(self, ns, worker):
        from lua_mapreduce_tpu.coord.filestore import worker_hash
        from lua_mapreduce_tpu.coord.idx_py import worker_tag
        my_tag = worker_tag(worker_hash(worker))
        self._bump("claim")
        with self._lock:
            queue = self._jobs.get(ns, [])
            candidates = [d for d in queue
                          if d["status"] == Status.RUNNING
                          and d.get("spec_state") == 1
                          and d["worker"] != worker]
            ordered = ([d for d in candidates
                        if worker_tag(worker_hash(d["worker"])) != my_tag]
                       + [d for d in candidates
                          if worker_tag(worker_hash(d["worker"])) == my_tag])
            for d in ordered[:1]:
                d["spec_state"] = 2
                d["spec_worker"] = worker
                doc = dict(d)
                doc["speculative"] = True
                return doc
            return None

    def cancel_spec(self, ns, job_id, worker):
        self._bump("commit")
        with self._lock:
            queue = self._jobs.get(ns, [])
            if not (0 <= job_id < len(queue)):
                return False
            d = queue[job_id]
            if worker is not None:
                if d.get("spec_state") != 2 or d.get("spec_worker") != worker:
                    return False
            elif not d.get("spec_state"):
                return False
            d["spec_state"] = 0
            d["spec_worker"] = None
            return True

    def drop_ns(self, ns):
        with self._lock:
            self._jobs.pop(ns, None)

    # -- errors ------------------------------------------------------------

    def insert_error(self, worker, msg, info=None):
        doc = {"worker": worker, "msg": msg, "time": time.time()}
        if info:
            doc.update(info)
        with self._lock:
            self._errors.append(doc)

    def drain_errors(self):
        with self._lock:
            out, self._errors = self._errors, []
            return out

    # -- persistent documents ----------------------------------------------

    def pt_get(self, name):
        with self._lock:
            doc = self._pt.get(name)
            return dict(doc) if doc is not None else None

    def pt_cas(self, name, expected_ts, doc):
        with self._lock:
            cur = self._pt.get(name)
            cur_ts = cur.get("timestamp") if cur is not None else None
            if cur_ts != expected_ts:
                return False
            self._pt[name] = dict(doc)
            return True

    def pt_delete(self, name):
        with self._lock:
            self._pt.pop(name, None)


def utest() -> None:
    """Self-test (reference task.lua:365-367 utest role): the claim /
    status machine on the in-memory store."""
    s = MemJobStore()
    ids = s.insert_jobs("map_jobs", [make_job(f"k{i}", i) for i in range(3)])
    assert ids == [0, 1, 2]
    doc = s.claim("map_jobs", "w1")
    assert doc is not None and doc["status"] == Status.RUNNING
    jid = doc["_id"]
    assert s.set_job_status("map_jobs", jid, Status.FINISHED,
                            expect=(Status.RUNNING,), expect_worker="w1")
    assert not s.set_job_status("map_jobs", jid, Status.WRITTEN,
                                expect=(Status.FINISHED,),
                                expect_worker="other")   # ownership CAS
    assert s.set_job_status("map_jobs", jid, Status.WRITTEN,
                            expect=(Status.FINISHED,), expect_worker="w1")
    c = s.counts("map_jobs")
    assert c[Status.WRITTEN] == 1 and c[Status.WAITING] == 2
    assert len(s.job_workers("map_jobs")) == 1

    # batch lease: claim the remaining two in one pass, commit in one pass
    batch = s.claim_batch("map_jobs", "w2", k=5)
    assert [d["_id"] for d in batch] == [1, 2]
    assert all(d["status"] == Status.RUNNING for d in batch)
    t = {"started": 0.0, "finished": 0.0, "written": 0.0, "cpu": 0.0,
         "real": 0.0}
    assert s.commit_batch("map_jobs", "w2",
                          [(1, t), (2, t)]) == [1, 2]
    assert s.counts("map_jobs")[Status.WRITTEN] == 3
    assert s.round_counts()["claim"] >= 2
