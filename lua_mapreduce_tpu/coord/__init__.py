"""Coordination plane: job store, persistent table, native CAS index.

This package replaces the reference's MongoDB control plane (SURVEY.md §2.6):
job queue + claim protocol, barrier/progress counting, task-singleton
checkpoint, errors stream, and the persistent_table distributed state — all
designed around compare-and-swap from day one (the reference's acknowledged
write-concern races, task.lua:300-308, are the thing *not* copied).

Backends:
- MemJobStore  — in-process (server + worker threads share one object)
- FileJobStore — shared-directory store for multi-process / multi-host
  pools; job status lives in a compact binary index mutated under an
  exclusive file lock, implemented twice with one format: a C++ library
  (native/jobstore.cpp, the luamongo-client analog) and a pure-Python
  fallback (coord/idx_py.py). The two interoperate on the same files.
"""

from lua_mapreduce_tpu.coord.jobstore import JobStore, MemJobStore
from lua_mapreduce_tpu.coord.filestore import FileJobStore
from lua_mapreduce_tpu.coord.persistent_table import PersistentTable

__all__ = ["JobStore", "MemJobStore", "FileJobStore", "PersistentTable"]
