"""Pure-Python job-status index over a shared file (fallback engine).

The claim protocol's data structure: a compact binary table of per-job
mutable state (status, repetitions, worker, started_time), mutated only
under an exclusive ``flock`` of the index file — which makes every operation
a true atomic compare-and-swap across processes and hosts sharing the
directory. This replaces the reference's Mongo single-document atomicity and
closes its acknowledged claim races (task.lua:300-308 FIXMEs).

The on-disk format is shared byte-for-byte with the native C++ engine
(native/jobstore.cpp); processes may mix the two freely on the same files.

Layout (little-endian):
    header:  8s magic "JSIX0001" | q record count
    record:  i status | i repetitions | q worker-hash | d started_time | d heartbeat
(``heartbeat`` was the reserved field; 0.0 = never beaten — old files
read compatibly.)
"""

from __future__ import annotations

import fcntl
import os
import struct
from typing import Dict, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status

MAGIC = b"JSIX0001"
_HEADER = struct.Struct("<8sq")
_REC = struct.Struct("<iiqdd")
HEADER_SIZE = _HEADER.size       # 16
RECORD_SIZE = _REC.size          # 32

_CLAIM_MASK = (1 << Status.WAITING) | (1 << Status.BROKEN)


class PyJobIndex:
    """One namespace's job index. All methods open/lock/operate/close so
    any number of processes can interleave safely."""

    def __init__(self, path: str):
        self.path = path

    # -- internals ---------------------------------------------------------

    def _open_locked(self, create: bool = False):
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(self.path, flags, 0o666)
        fcntl.flock(fd, fcntl.LOCK_EX)
        return fd

    @staticmethod
    def _read_count(fd) -> int:
        os.lseek(fd, 0, os.SEEK_SET)
        head = os.read(fd, HEADER_SIZE)
        if len(head) < HEADER_SIZE:
            return 0
        magic, count = _HEADER.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad index magic in {head!r}")
        return count

    @staticmethod
    def _write_count(fd, count: int) -> None:
        os.lseek(fd, 0, os.SEEK_SET)
        os.write(fd, _HEADER.pack(MAGIC, count))

    @staticmethod
    def _read_rec(fd, job_id: int) -> Tuple[int, int, int, float, float]:
        os.lseek(fd, HEADER_SIZE + job_id * RECORD_SIZE, os.SEEK_SET)
        return _REC.unpack(os.read(fd, RECORD_SIZE))

    @staticmethod
    def _write_rec(fd, job_id: int, status: int, reps: int, worker: int,
                   started: float, reserved: float = 0.0) -> None:
        os.lseek(fd, HEADER_SIZE + job_id * RECORD_SIZE, os.SEEK_SET)
        os.write(fd, _REC.pack(status, reps, worker, started, reserved))

    # -- operations (mirror native/jobstore.cpp exports) -------------------

    def insert(self, n: int) -> int:
        """Append ``n`` WAITING records; returns the first new id."""
        fd = self._open_locked(create=True)
        try:
            count = self._read_count(fd) if os.fstat(fd).st_size else 0
            for i in range(n):
                self._write_rec(fd, count + i, Status.WAITING, 0, 0, 0.0)
            self._write_count(fd, count + n)
            return count
        finally:
            os.close(fd)

    def count(self) -> int:
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            return self._read_count(fd)
        finally:
            os.close(fd)

    def claim(self, worker: int, now: float,
              preferred: Optional[Sequence[int]] = None,
              steal: bool = True) -> int:
        """First WAITING|BROKEN → RUNNING. Returns claimed id or -1.
        ``steal=False`` restricts the scan to ``preferred``."""
        if not os.path.exists(self.path):
            return -1
        fd = self._open_locked()
        try:
            count = self._read_count(fd)

            def try_id(jid: int) -> bool:
                status, reps, w, st, rv = self._read_rec(fd, jid)
                if (1 << status) & _CLAIM_MASK:
                    self._write_rec(fd, jid, Status.RUNNING, reps, worker, now)
                    return True
                return False

            for jid in (preferred or ()):
                if 0 <= jid < count and try_id(jid):
                    return jid
            if steal:
                for jid in range(count):
                    if try_id(jid):
                        return jid
            return -1
        finally:
            os.close(fd)

    def cas_status(self, job_id: int, to: Status, expect_mask: int = 0,
                   expect_worker: int = 0) -> bool:
        """Set status iff current status is in ``expect_mask`` (bitmask of
        ``1 << status``; 0 = unconditional) AND, when ``expect_worker`` is
        nonzero, the record's claim owner matches. Moving to BROKEN
        increments ``repetitions`` (job.lua:322-342). A missing index
        (namespace dropped under a straggler) is a False, not an error."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            status, reps, w, st, rv = self._read_rec(fd, job_id)
            if expect_mask and not ((1 << status) & expect_mask):
                return False
            if expect_worker and w != expect_worker:
                return False
            if to == Status.BROKEN:
                reps += 1
            self._write_rec(fd, job_id, int(to), reps, w, st, rv)
            return True
        finally:
            os.close(fd)

    def get(self, job_id: int) -> Optional[Tuple[int, int, int, float]]:
        if not os.path.exists(self.path):
            return None
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return None
            status, reps, w, st, _ = self._read_rec(fd, job_id)
            return status, reps, w, st
        finally:
            os.close(fd)

    def counts(self) -> Dict[Status, int]:
        out = {s: 0 for s in Status}
        if not os.path.exists(self.path):
            return out
        fd = self._open_locked()
        try:
            for jid in range(self._read_count(fd)):
                status, *_ = self._read_rec(fd, jid)
                out[Status(status)] += 1
            return out
        finally:
            os.close(fd)

    def scavenge(self, max_retries: int = MAX_JOB_RETRIES) -> int:
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            n = 0
            for jid in range(self._read_count(fd)):
                status, reps, w, st, rv = self._read_rec(fd, jid)
                if status == Status.BROKEN and reps >= max_retries:
                    self._write_rec(fd, jid, Status.FAILED, reps, w, st, rv)
                    n += 1
            return n
        finally:
            os.close(fd)

    def requeue_stale(self, cutoff: float) -> int:
        """RUNNING|FINISHED records whose last liveness signal (claim
        time or worker heartbeat) predates ``cutoff`` → BROKEN (+1 rep).
        FINISHED is included so a worker killed between its FINISHED and
        WRITTEN transitions cannot wedge the barrier; a heartbeating
        worker's long job is never requeued."""
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            n = 0
            for jid in range(self._read_count(fd)):
                status, reps, w, st, hb = self._read_rec(fd, jid)
                if (status in (Status.RUNNING, Status.FINISHED) and
                        max(st, hb) < cutoff):
                    self._write_rec(fd, jid, Status.BROKEN, reps + 1, w, st, hb)
                    n += 1
            return n
        finally:
            os.close(fd)

    def heartbeat(self, job_id: int, worker: int, now: float) -> bool:
        """Refresh a RUNNING|FINISHED record's liveness timestamp iff
        ``worker`` still owns the claim (0 skips the ownership check)."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            status, reps, w, st, _ = self._read_rec(fd, job_id)
            if status not in (Status.RUNNING, Status.FINISHED):
                return False
            if worker and w != worker:
                return False
            self._write_rec(fd, job_id, status, reps, w, st, now)
            return True
        finally:
            os.close(fd)

    def snapshot(self) -> List[Tuple[int, int, int, float]]:
        """All records (status, reps, worker, started) in one locked pass —
        the bulk-stats read path (avoids one flock per job)."""
        if not os.path.exists(self.path):
            return []
        fd = self._open_locked()
        try:
            return [self._read_rec(fd, jid)[:4]
                    for jid in range(self._read_count(fd))]
        finally:
            os.close(fd)
