"""Pure-Python job-status index over a shared file (fallback engine).

The claim protocol's data structure: a compact binary table of per-job
mutable state (status, repetitions, worker, started_time), mutated only
under an exclusive ``flock`` of the index file — which makes every operation
a true atomic compare-and-swap across processes and hosts sharing the
directory. This replaces the reference's Mongo single-document atomicity and
closes its acknowledged claim races (task.lua:300-308 FIXMEs).

The on-disk format is shared byte-for-byte with the native C++ engine
(native/jobstore.cpp); processes may mix the two freely on the same files.

Layout (little-endian):
    header:  8s magic "JSIX0003" | q record count
    record:  i status | i repetitions | q worker-hash | d started_time
             | d heartbeat | 5d job times (started, finished, written,
             cpu, real; all-zero = not recorded) | q spec-worker-hash
             | i spec_state | i reserved

Format note: JSIX0002 embedded the per-job TIMES in the record (the v1
times sidecar was one tempfile+rename per job — at many-tiny-jobs scale
those renames dominated the commit path, and the server's stats fold
re-opened one JSON file per job). JSIX0003 adds the DUPLICATE-LEASE
fields (DESIGN §21): ``spec_state`` (0 = none, 1 = speculation OPEN —
the straggler detector marked this RUNNING job cloneable, 2 = TAKEN —
``spec_worker`` holds the shadow lease) ride every record so the
first-commit-wins arbitration is one CAS under the same flock as every
other transition. Index files are per-run coordination state, not
durable data, so older formats are not migrated — a v1/v2 file left by
an older process fails the magic check loudly rather than being
misread.
"""

from __future__ import annotations

import fcntl
import os
import struct
from typing import Dict, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status

MAGIC = b"JSIX0003"
_HEADER = struct.Struct("<8sq")
_REC = struct.Struct("<iiqdddddddqii")
HEADER_SIZE = _HEADER.size       # 16
RECORD_SIZE = _REC.size          # 88
N_TIMES = 5                      # started, finished, written, cpu, real
_ZERO_TIMES = (0.0,) * N_TIMES

# record tuple indices past the times block
_I_SPECW = 10                    # spec-worker hash
_I_SPECS = 11                    # spec_state

# spec_state values (DESIGN §21)
SPEC_NONE = 0
SPEC_OPEN = 1                    # detector marked: shadow lease claimable
SPEC_TAKEN = 2                   # spec-worker holds the shadow lease

# import-time drift guard: these numbers ARE the v3 wire format shared
# with native/jobstore.cpp (its static_asserts pin the same values, and
# idx.py cross-checks both sides via jsx_abi() when the native engine
# loads). A drifted struct string must fail here, before any index file
# is touched — as a real raise, not an assert, so python -O cannot
# strip the guard whose whole point is preventing silent corruption.
if HEADER_SIZE != 16 or RECORD_SIZE != 88:
    raise ImportError(f"JSIX0003 layout drifted: header {HEADER_SIZE}B, "
                      f"record {RECORD_SIZE}B (must be 16/88)")
if [int(s) for s in Status] != [0, 1, 2, 3, 4, 5]:
    raise ImportError("Status enum drifted from the JSIX0003 record "
                      "encoding (native/jobstore.cpp pins 0..5)")

_CLAIM_MASK = (1 << Status.WAITING) | (1 << Status.BROKEN)


def worker_tag(worker_hash: int, num_tags: int = 8) -> int:
    """Placement tag of a worker, from its stable name hash — the
    fleet-side twin of engine/placement.py's file tags. Used by the
    speculative claim to PREFER shadow workers on a different tag than
    the straggler (a degraded rack slows all its members; a clone on
    the same tag would likely share the fate). Unsigned arithmetic so
    Python and C++ (uint64 cast) agree on negative hashes."""
    return (worker_hash & 0xFFFFFFFFFFFFFFFF) % num_tags


class PyJobIndex:
    """One namespace's job index. All methods open/lock/operate/close so
    any number of processes can interleave safely."""

    def __init__(self, path: str):
        self.path = path

    # -- internals ---------------------------------------------------------

    def _open_locked(self, create: bool = False):
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(self.path, flags, 0o666)
        fcntl.flock(fd, fcntl.LOCK_EX)
        return fd

    @staticmethod
    def _read_count(fd) -> int:
        os.lseek(fd, 0, os.SEEK_SET)
        head = os.read(fd, HEADER_SIZE)
        if len(head) < HEADER_SIZE:
            return 0
        magic, count = _HEADER.unpack(head)
        if magic != MAGIC:
            raise ValueError(f"bad index magic in {head!r}")
        return count

    @staticmethod
    def _write_count(fd, count: int) -> None:
        os.lseek(fd, 0, os.SEEK_SET)
        os.write(fd, _HEADER.pack(MAGIC, count))

    @staticmethod
    def _read_rec(fd, job_id: int) -> tuple:
        """(status, reps, worker, started, heartbeat, t0..t4)."""
        os.lseek(fd, HEADER_SIZE + job_id * RECORD_SIZE, os.SEEK_SET)
        return _REC.unpack(os.read(fd, RECORD_SIZE))

    @staticmethod
    def _write_rec(fd, job_id: int, status: int, reps: int, worker: int,
                   started: float, heartbeat: float = 0.0,
                   times: Sequence[float] = _ZERO_TIMES,
                   spec_worker: int = 0, spec_state: int = SPEC_NONE) -> None:
        os.lseek(fd, HEADER_SIZE + job_id * RECORD_SIZE, os.SEEK_SET)
        os.write(fd, _REC.pack(status, reps, worker, started, heartbeat,
                               *times, spec_worker, spec_state, 0))

    @staticmethod
    def _times_of(rec: tuple) -> Optional[Tuple[float, ...]]:
        times = rec[5:5 + N_TIMES]
        return None if times == _ZERO_TIMES else times

    @staticmethod
    def _owner_ok(rec: tuple, expect_worker: int) -> bool:
        """The duplicate-lease ownership rule (DESIGN §21): a record is
        'owned' by its claimant AND, while a shadow lease is TAKEN, by
        the speculative worker — either may land the one commit; the
        status CAS (only one RUNNING|FINISHED→WRITTEN transition can
        ever succeed under the flock) arbitrates first-commit-wins."""
        if rec[2] == expect_worker:
            return True
        return (rec[_I_SPECS] == SPEC_TAKEN
                and rec[_I_SPECW] == expect_worker)

    @classmethod
    def _read_all(cls, fd) -> List[Tuple[int, int, int, float, float]]:
        """Every record in ONE read syscall — scan-shaped operations
        (claim, counts, snapshot, scavenge, requeue) pay one IO round
        trip under the flock instead of one pread per record (on network
        filesystems the per-record scan dominated claim latency at the
        ~2,000-job reference fan-in)."""
        count = cls._read_count(fd)
        if count <= 0:
            return []
        os.lseek(fd, HEADER_SIZE, os.SEEK_SET)
        want = count * RECORD_SIZE
        buf = b""
        while len(buf) < want:
            chunk = os.read(fd, want - len(buf))
            if not chunk:
                break
            buf += chunk
        full = len(buf) - (len(buf) % RECORD_SIZE)
        return list(_REC.iter_unpack(buf[:full]))

    # -- operations (mirror native/jobstore.cpp exports) -------------------

    def insert(self, n: int) -> int:
        """Append ``n`` WAITING records; returns the first new id."""
        fd = self._open_locked(create=True)
        try:
            count = self._read_count(fd) if os.fstat(fd).st_size else 0
            for i in range(n):
                self._write_rec(fd, count + i, Status.WAITING, 0, 0, 0.0)
            self._write_count(fd, count + n)
            return count
        finally:
            os.close(fd)

    def count(self) -> int:
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            return self._read_count(fd)
        finally:
            os.close(fd)

    def claim(self, worker: int, now: float,
              preferred: Optional[Sequence[int]] = None,
              steal: bool = True) -> int:
        """First WAITING|BROKEN → RUNNING. Returns claimed id or -1.
        ``steal=False`` restricts the scan to ``preferred``."""
        got = self.claim_batch(worker, now, 1, preferred, steal)
        return got[0][0] if got else -1

    def claim_batch(self, worker: int, now: float, k: int,
                    preferred: Optional[Sequence[int]] = None,
                    steal: bool = True) -> List[Tuple[int, int]]:
        """Claim up to ``k`` WAITING|BROKEN records → RUNNING in ONE
        locked pass over ONE bulk read (the batch-lease amortization of
        the claim round trip). Returns [(job_id, repetitions), ...] in
        claim order — enough to build the claimed docs without re-reading
        each record under a fresh flock. Preferred ids are tried first;
        ``steal=False`` restricts the scan to them, exactly like the
        single claim (which is this with k=1)."""
        if k <= 0 or not os.path.exists(self.path):
            return []
        fd = self._open_locked()
        try:
            recs = self._read_all(fd)
            count = len(recs)
            out: List[Tuple[int, int]] = []
            taken = set()

            def try_id(jid: int) -> None:
                status, reps = recs[jid][0], recs[jid][1]
                if (1 << status) & _CLAIM_MASK:
                    # fresh claim: fresh silence clock AND fresh times
                    # (a retry's record must not carry the dead
                    # attempt's timing into the stats fold)
                    self._write_rec(fd, jid, Status.RUNNING, reps, worker,
                                    now)
                    out.append((jid, reps))
                    taken.add(jid)

            for jid in (preferred or ()):
                if len(out) >= k:
                    break
                if 0 <= jid < count and jid not in taken:
                    try_id(jid)
            if steal:
                for jid in range(count):
                    if len(out) >= k:
                        break
                    if jid not in taken:
                        try_id(jid)
            return out
        finally:
            os.close(fd)

    def cas_status(self, job_id: int, to: Status, expect_mask: int = 0,
                   expect_worker: int = 0) -> bool:
        """Set status iff current status is in ``expect_mask`` (bitmask of
        ``1 << status``; 0 = unconditional) AND, when ``expect_worker`` is
        nonzero, the record's claim owner matches. Moving to BROKEN
        increments ``repetitions`` (job.lua:322-342). A missing index
        (namespace dropped under a straggler) is a False, not an error."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            rec = self._read_rec(fd, job_id)
            status, reps, w = rec[0], rec[1], rec[2]
            if expect_mask and not ((1 << status) & expect_mask):
                return False
            if expect_worker and not self._owner_ok(rec, expect_worker):
                return False
            if to == Status.BROKEN:
                reps += 1
            # leaving the leased states (release/requeue) dissolves any
            # shadow lease: a re-claimed job must never be committable
            # by a stale speculative worker
            sw, ss = ((0, SPEC_NONE)
                      if to in (Status.WAITING, Status.BROKEN)
                      else (rec[_I_SPECW], rec[_I_SPECS]))
            self._write_rec(fd, job_id, int(to), reps, w, rec[3], rec[4],
                            rec[5:5 + N_TIMES], sw, ss)
            return True
        finally:
            os.close(fd)

    def cas_status_batch(self, ids: Sequence[int], to: Status,
                         expect_mask: int = 0,
                         expect_worker: int = 0) -> List[bool]:
        """:meth:`cas_status` over many records under ONE flock — the
        batch-commit amortization (a k-job batch retires in one locked
        pass instead of k lock/IO round trips). Per-id success flags in
        input order; each id's CAS is judged independently, so one lost
        claim never blocks the rest of the batch."""
        out = [False] * len(ids)
        if not ids or not os.path.exists(self.path):
            return out
        fd = self._open_locked()
        try:
            count = self._read_count(fd)
            for i, job_id in enumerate(ids):
                if not (0 <= job_id < count):
                    continue
                rec = self._read_rec(fd, job_id)
                status, reps, w = rec[0], rec[1], rec[2]
                if expect_mask and not ((1 << status) & expect_mask):
                    continue
                if expect_worker and not self._owner_ok(rec, expect_worker):
                    continue
                if to == Status.BROKEN:
                    reps += 1
                sw, ss = ((0, SPEC_NONE)
                          if to in (Status.WAITING, Status.BROKEN)
                          else (rec[_I_SPECW], rec[_I_SPECS]))
                self._write_rec(fd, job_id, int(to), reps, w, rec[3],
                                rec[4], rec[5:5 + N_TIMES], sw, ss)
                out[i] = True
            return out
        finally:
            os.close(fd)

    def commit_batch(self, entries: Sequence[tuple],
                     worker: int) -> List[bool]:
        """Retire a batch in ONE flock cycle: for each ``(job_id,
        times5)`` entry, iff the record is RUNNING|FINISHED and ``worker``
        owns the claim, write the job times INTO the record and flip it
        WRITTEN. The v1 protocol spent two status CASes plus a times-
        sidecar rename per job here; embedding times in the record
        (JSIX0002) folds all three into this one locked pass. Per-entry
        success flags in input order."""
        out = [False] * len(entries)
        if not entries or not os.path.exists(self.path):
            return out
        commit_mask = (1 << Status.RUNNING) | (1 << Status.FINISHED)
        fd = self._open_locked()
        try:
            count = self._read_count(fd)
            for i, (job_id, times) in enumerate(entries):
                if not (0 <= job_id < count):
                    continue
                rec = self._read_rec(fd, job_id)
                status, reps, w = rec[0], rec[1], rec[2]
                if not ((1 << status) & commit_mask):
                    continue
                if worker and not self._owner_ok(rec, worker):
                    continue
                # the ONE commit (first-commit-wins): WRITTEN is outside
                # commit_mask, so the losing duplicate's entry fails the
                # status check above and is skipped without any state
                # change — never a double commit, never a rep bump
                self._write_rec(fd, job_id, Status.WRITTEN, reps, w,
                                rec[3], rec[4], times or _ZERO_TIMES,
                                rec[_I_SPECW], rec[_I_SPECS])
                out[i] = True
            return out
        finally:
            os.close(fd)

    def set_times(self, job_id: int, times: Sequence[float]) -> bool:
        """Record a job's times without touching its status (the single-
        job set_job_times path; commit_batch is the amortized route)."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            rec = self._read_rec(fd, job_id)
            self._write_rec(fd, job_id, rec[0], rec[1], rec[2], rec[3],
                            rec[4], times, rec[_I_SPECW], rec[_I_SPECS])
            return True
        finally:
            os.close(fd)

    def get(self, job_id: int) -> Optional[tuple]:
        """(status, reps, worker, started, times5 | None, spec_state,
        spec_worker) or None when missing/out of bounds."""
        if not os.path.exists(self.path):
            return None
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return None
            rec = self._read_rec(fd, job_id)
            return (rec[0], rec[1], rec[2], rec[3], self._times_of(rec),
                    rec[_I_SPECS], rec[_I_SPECW])
        finally:
            os.close(fd)

    def counts(self) -> Dict[Status, int]:
        out = {s: 0 for s in Status}
        if not os.path.exists(self.path):
            return out
        fd = self._open_locked()
        try:
            for status, *_ in self._read_all(fd):
                out[Status(status)] += 1
            return out
        finally:
            os.close(fd)

    def scavenge(self, max_retries: int = MAX_JOB_RETRIES) -> int:
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            n = 0
            for jid, rec in enumerate(self._read_all(fd)):
                status, reps = rec[0], rec[1]
                if status == Status.BROKEN and reps >= max_retries:
                    self._write_rec(fd, jid, Status.FAILED, reps, rec[2],
                                    rec[3], rec[4], rec[5:5 + N_TIMES],
                                    rec[_I_SPECW], rec[_I_SPECS])
                    n += 1
            return n
        finally:
            os.close(fd)

    def requeue_stale(self, cutoff: float) -> int:
        """RUNNING|FINISHED records whose last liveness signal (claim
        time or worker heartbeat) predates ``cutoff`` → BROKEN (+1 rep).
        FINISHED is included so a worker killed between its FINISHED and
        WRITTEN transitions cannot wedge the barrier; a heartbeating
        worker's long job is never requeued."""
        if not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            n = 0
            for jid, rec in enumerate(self._read_all(fd)):
                status, reps, w, st, hb = rec[:5]
                if (status in (Status.RUNNING, Status.FINISHED) and
                        max(st, hb) < cutoff):
                    # requeue dissolves any shadow lease (the clone's
                    # beats count as liveness, so reaching here means
                    # BOTH holders went silent)
                    self._write_rec(fd, jid, Status.BROKEN, reps + 1, w,
                                    st, hb, rec[5:5 + N_TIMES])
                    n += 1
            return n
        finally:
            os.close(fd)

    def heartbeat(self, job_id: int, worker: int, now: float) -> bool:
        """Refresh a RUNNING|FINISHED record's liveness timestamp iff
        ``worker`` still owns the claim (0 skips the ownership check)."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            rec = self._read_rec(fd, job_id)
            status, reps, w, st = rec[:4]
            if status not in (Status.RUNNING, Status.FINISHED):
                return False
            if worker and not self._owner_ok(rec, worker):
                return False
            self._write_rec(fd, job_id, status, reps, w, st, now,
                            rec[5:5 + N_TIMES], rec[_I_SPECW],
                            rec[_I_SPECS])
            return True
        finally:
            os.close(fd)

    def heartbeat_batch(self, ids: Sequence[int], worker: int,
                        now: float) -> int:
        """:meth:`heartbeat` over many records under ONE flock — the
        batch lease's single heartbeat thread beats every leased job in
        one lock cycle. Returns how many beats landed."""
        if not ids or not os.path.exists(self.path):
            return 0
        fd = self._open_locked()
        try:
            count = self._read_count(fd)
            n = 0
            for job_id in ids:
                if not (0 <= job_id < count):
                    continue
                rec = self._read_rec(fd, job_id)
                status, reps, w, st = rec[:4]
                if status not in (Status.RUNNING, Status.FINISHED):
                    continue
                if worker and not self._owner_ok(rec, worker):
                    continue
                self._write_rec(fd, job_id, status, reps, w, st, now,
                                rec[5:5 + N_TIMES], rec[_I_SPECW],
                                rec[_I_SPECS])
                n += 1
            return n
        finally:
            os.close(fd)

    # -- duplicate leases (speculative execution, DESIGN §21) --------------

    def speculate(self, job_id: int) -> bool:
        """Mark a RUNNING record speculation-OPEN: a shadow lease may be
        taken by :meth:`claim_spec`. The straggler detector's op — CASed
        on (RUNNING, no existing speculation), so repeated detector
        passes over the same straggler are idempotent and a job can
        carry at most ONE shadow lease at a time."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            rec = self._read_rec(fd, job_id)
            if rec[0] != Status.RUNNING or rec[_I_SPECS] != SPEC_NONE:
                return False
            self._write_rec(fd, job_id, rec[0], rec[1], rec[2], rec[3],
                            rec[4], rec[5:5 + N_TIMES], 0, SPEC_OPEN)
            return True
        finally:
            os.close(fd)

    def claim_spec(self, worker: int) -> Optional[Tuple[int, int]]:
        """Take ONE speculation-open shadow lease → (job_id, reps), or
        None. A worker never shadows its own job, and records whose
        claimant sits on a DIFFERENT placement tag than the claimer are
        preferred (a straggler's slowness is often its failure domain's;
        a clone sharing the domain would likely share the fate) — same
        scan order (lowest id first) within each preference class, so
        both engines and the protocol model agree on who wins."""
        if not os.path.exists(self.path):
            return None
        fd = self._open_locked()
        try:
            recs = self._read_all(fd)
            my_tag = worker_tag(worker)
            candidates = [jid for jid, rec in enumerate(recs)
                          if rec[0] == Status.RUNNING
                          and rec[_I_SPECS] == SPEC_OPEN
                          and rec[2] != worker]
            ordered = ([j for j in candidates
                        if worker_tag(recs[j][2]) != my_tag]
                       + [j for j in candidates
                          if worker_tag(recs[j][2]) == my_tag])
            for jid in ordered[:1]:
                rec = recs[jid]
                self._write_rec(fd, jid, rec[0], rec[1], rec[2], rec[3],
                                rec[4], rec[5:5 + N_TIMES], worker,
                                SPEC_TAKEN)
                return jid, rec[1]
            return None
        finally:
            os.close(fd)

    def cancel_spec(self, job_id: int, worker: int) -> bool:
        """Dissolve a shadow lease this worker holds (the loser /
        failure path — the job's status and repetitions are NEVER
        touched: the original claimant still owns the lease). CASed on
        (TAKEN, spec owner == worker); with worker == 0 any OPEN or
        TAKEN speculation is cleared (the detector's retraction)."""
        if not os.path.exists(self.path):
            return False
        fd = self._open_locked()
        try:
            if not (0 <= job_id < self._read_count(fd)):
                return False
            rec = self._read_rec(fd, job_id)
            if worker:
                if (rec[_I_SPECS] != SPEC_TAKEN
                        or rec[_I_SPECW] != worker):
                    return False
            elif rec[_I_SPECS] == SPEC_NONE:
                return False
            self._write_rec(fd, job_id, rec[0], rec[1], rec[2], rec[3],
                            rec[4], rec[5:5 + N_TIMES], 0, SPEC_NONE)
            return True
        finally:
            os.close(fd)

    def snapshot(self) -> List[tuple]:
        """All records (status, reps, worker, started, times5 | None,
        spec_state, spec_worker) in one locked pass over one bulk read —
        the stats/jobs() read path (v1 additionally opened one
        times-sidecar JSON per job here)."""
        if not os.path.exists(self.path):
            return []
        fd = self._open_locked()
        try:
            return [rec[:4] + (self._times_of(rec), rec[_I_SPECS],
                               rec[_I_SPECW])
                    for rec in self._read_all(fd)]
        finally:
            os.close(fd)
