// Native job-status index — the coordination client's hot path.
//
// TPU-native equivalent of the reference's native C++ layer (SURVEY.md
// §2.4): where lua-mapreduce links luamongo + mongo-cxx-driver to talk to a
// MongoDB control plane, this framework's control plane is a shared-file
// compare-and-swap index, and this library is its native engine. The Python
// fallback (coord/idx_py.py) implements the identical on-disk format; both
// may operate on the same files concurrently.
//
// Concurrency model: every operation opens the index file, takes an
// exclusive flock, operates with pread/pwrite, and releases on close. flock
// is process-crash-safe (the OS drops the lock when the holder dies), which
// is what makes worker failure recovery sound with no lease machinery.
//
// Layout (little-endian, matching idx_py.py):
//   header: char magic[8] = "JSIX0003"; int64 count;
//   record: int32 status; int32 repetitions; int64 worker; double started;
//           double reserved;   // reserved = last heartbeat time
//                              // (0.0 = never beaten)
//           double times[5];   // job times (started, finished, written,
//                              // cpu, real); all-zero = not recorded.
//           int64 spec_worker; // shadow-lease holder (duplicate lease)
//           int32 spec_state;  // 0 none | 1 open | 2 taken
//           int32 spec_pad;    // reserved (alignment)
//                              // 88 bytes total. JSIX0002 embedded the
//                              // times so a batch commit retires status
//                              // AND timing in one flock cycle; JSIX0003
//                              // adds the duplicate-lease fields so the
//                              // first-commit-wins arbitration is one
//                              // CAS under the same flock (DESIGN §21).

#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr char kMagic[8] = {'J', 'S', 'I', 'X', '0', '0', '0', '3'};
constexpr int64_t kHeaderSize = 16;
constexpr int64_t kRecordSize = 88;
constexpr int kNTimes = 5;

// Status values mirror core/constants.py (reference utils.lua:33-40).
enum Status : int32_t {
  kWaiting = 0,
  kRunning = 1,
  kBroken = 2,
  kFinished = 3,
  kWritten = 4,
  kFailed = 5,
};

constexpr uint32_t kClaimMask = (1u << kWaiting) | (1u << kBroken);

// spec_state values (DESIGN §21), mirrored by coord/idx_py.py
enum SpecState : int32_t {
  kSpecNone = 0,
  kSpecOpen = 1,   // straggler detector marked: shadow lease claimable
  kSpecTaken = 2,  // spec_worker holds the shadow lease
};

#pragma pack(push, 1)
struct Header {
  char magic[8];
  int64_t count;
};
struct Record {
  int32_t status;
  int32_t repetitions;
  int64_t worker;
  double started;
  double reserved;
  double times[kNTimes];
  int64_t spec_worker;
  int32_t spec_state;
  int32_t spec_pad;
};
#pragma pack(pop)

static_assert(sizeof(Header) == kHeaderSize, "header layout");
static_assert(sizeof(Record) == kRecordSize, "record layout");
// the status values ARE the wire format (both engines write them into
// shared index files); drift against core/constants.py corrupts live
// coordination state, so they are pinned here and re-checked from the
// Python side at library load via jsx_abi()
static_assert(kWaiting == 0 && kRunning == 1 && kBroken == 2 &&
                  kFinished == 3 && kWritten == 4 && kFailed == 5,
              "status enum drifted from core/constants.py");

class LockedIndex {
 public:
  explicit LockedIndex(const char* path, bool create)
      : fd_(open(path, O_RDWR | (create ? O_CREAT : 0), 0666)) {
    if (fd_ >= 0 && flock(fd_, LOCK_EX) != 0) {
      close(fd_);
      fd_ = -1;
    }
  }
  ~LockedIndex() {
    if (fd_ >= 0) close(fd_);  // close releases the flock
  }
  bool ok() const { return fd_ >= 0; }

  int64_t count() const {
    Header h;
    if (pread(fd_, &h, sizeof h, 0) != (ssize_t)sizeof h) return 0;
    if (memcmp(h.magic, kMagic, sizeof kMagic) != 0) return -1;
    return h.count;
  }

  bool set_count(int64_t n) const {
    Header h;
    memcpy(h.magic, kMagic, sizeof kMagic);
    h.count = n;
    return pwrite(fd_, &h, sizeof h, 0) == (ssize_t)sizeof h;
  }

  bool read(int64_t id, Record* rec) const {
    return pread(fd_, rec, sizeof *rec, kHeaderSize + id * kRecordSize) ==
           (ssize_t)sizeof *rec;
  }

  bool write(int64_t id, const Record& rec) const {
    return pwrite(fd_, &rec, sizeof rec, kHeaderSize + id * kRecordSize) ==
           (ssize_t)sizeof rec;
  }

  // One bulk pread of every record — scan-shaped operations (claim,
  // counts, snapshot, scavenge, requeue) pay ONE IO round trip under the
  // flock instead of one pread per record; mutated records are written
  // back individually (few per pass).
  bool read_all(std::vector<Record>* out) const {
    const int64_t n = count();
    if (n < 0) return false;
    out->resize((size_t)n);
    if (n == 0) return true;
    const ssize_t want = (ssize_t)(n * kRecordSize);
    return pread(fd_, out->data(), want, kHeaderSize) == want;
  }

 private:
  int fd_;
};

// the duplicate-lease ownership rule (DESIGN §21): the claimant owns
// the record, and while a shadow lease is TAKEN so does the speculative
// worker — either may land the ONE commit; the status CAS arbitrates
// first-commit-wins under the flock.
bool owner_ok(const Record& rec, int64_t expect_worker) {
  if (rec.worker == expect_worker) return true;
  return rec.spec_state == kSpecTaken && rec.spec_worker == expect_worker;
}

// placement tag of a worker from its stable name hash (the fleet-side
// twin of engine/placement.py's 8 virtual failure domains; unsigned so
// Python and C++ agree on negative hashes)
uint64_t worker_tag(int64_t worker) { return (uint64_t)worker % 8; }

// leaving the leased states (release/requeue) dissolves any shadow
// lease: a re-claimed job must never be committable by a stale
// speculative worker.
void clear_spec_on_unlease(Record* rec, int32_t to) {
  if (to == kWaiting || to == kBroken) {
    rec->spec_worker = 0;
    rec->spec_state = kSpecNone;
    rec->spec_pad = 0;
  }
}

double now_seconds() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

int64_t jsx_claim_batch(const char* path, int64_t worker,
                        const int64_t* preferred, int64_t n_preferred,
                        int32_t steal, int64_t* out_ids, int32_t* out_reps,
                        int64_t k);

// ABI self-description: the on-disk layout THIS build writes. The Python
// loader (coord/idx.py) calls it once per process and refuses the native
// engine if anything disagrees with idx_py.py — a version skew between
// the two engines must fail at load time, never as silent corruption of
// a shared index file. Fills magic_out[8], sizes_out[2] = {header,
// record}, statuses_out[6] in core/constants.py order; returns 1.
int32_t jsx_abi(char* magic_out, int64_t* sizes_out,
                int32_t* statuses_out) {
  memcpy(magic_out, kMagic, sizeof kMagic);
  sizes_out[0] = kHeaderSize;
  sizes_out[1] = kRecordSize;
  const int32_t statuses[6] = {kWaiting, kRunning, kBroken,
                               kFinished, kWritten, kFailed};
  memcpy(statuses_out, statuses, sizeof statuses);
  return 1;
}

// Append n WAITING records; returns first new id, or -1 on error.
int64_t jsx_insert(const char* path, int64_t n) {
  LockedIndex idx(path, /*create=*/true);
  if (!idx.ok()) return -1;
  int64_t count = idx.count();  // 0 for a freshly created empty file
  if (count < 0) return -1;
  Record rec{kWaiting, 0, 0, 0.0, 0.0, {}, 0, kSpecNone, 0};
  for (int64_t i = 0; i < n; ++i) {
    if (!idx.write(count + i, rec)) return -1;
  }
  if (!idx.set_count(count + n)) return -1;
  return count;
}

// Number of records, 0 if missing, -1 on corruption.
int64_t jsx_count(const char* path) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  return idx.count();
}

// Claim first WAITING|BROKEN record for worker (preferred ids first; when
// steal == 0 only the preferred ids are considered — map-affinity mode).
// Returns claimed id or -1. Thin wrapper over the batch path (k = 1), so
// both share the one-bulk-read scan.
int64_t jsx_claim(const char* path, int64_t worker, const int64_t* preferred,
                  int64_t n_preferred, int32_t steal) {
  int64_t id = -1;
  int32_t reps = 0;
  const int64_t n = jsx_claim_batch(path, worker, preferred, n_preferred,
                                    steal, &id, &reps, 1);
  return n == 1 ? id : -1;
}

// Claim up to k WAITING|BROKEN records for worker in ONE locked pass (the
// batch-lease amortization of jsx_claim). Fills out_ids/out_reps with the
// claimed ids and their pre-claim repetition counts; returns how many were
// claimed (0 when nothing is claimable), or -1 on error. Preferred ids are
// tried first; steal == 0 restricts the scan to them.
int64_t jsx_claim_batch(const char* path, int64_t worker,
                        const int64_t* preferred, int64_t n_preferred,
                        int32_t steal, int64_t* out_ids, int32_t* out_reps,
                        int64_t k) {
  if (k <= 0) return 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -1;
  const int64_t count = (int64_t)recs.size();
  if (count <= 0) return 0;
  const double now = now_seconds();
  int64_t taken = 0;

  // scan in memory; a claimed record's in-buffer status flips to RUNNING,
  // which also makes it unclaimable again this pass (a preferred id later
  // reachable by the steal scan)
  auto try_id = [&](int64_t id) -> bool {
    Record& rec = recs[(size_t)id];
    if (!((1u << rec.status) & kClaimMask)) return false;
    out_ids[taken] = id;
    out_reps[taken] = rec.repetitions;
    rec.status = kRunning;
    rec.worker = worker;
    rec.started = now;
    rec.reserved = 0.0;  // fresh claim: fresh silence clock, fresh
    for (int t = 0; t < kNTimes; ++t) rec.times[t] = 0.0;  // times,
    rec.spec_worker = 0;                 // and no carried shadow lease
    rec.spec_state = kSpecNone;
    rec.spec_pad = 0;
    if (!idx.write(id, rec)) return false;
    ++taken;
    return true;
  };

  for (int64_t i = 0; i < n_preferred && taken < k; ++i) {
    const int64_t id = preferred[i];
    if (id >= 0 && id < count) try_id(id);
  }
  if (steal) {
    for (int64_t id = 0; id < count && taken < k; ++id) try_id(id);
  }
  return taken;
}

// CAS status; expect_mask is a bitmask of (1<<status), 0 = unconditional;
// expect_worker != 0 additionally requires the record's claim owner to
// match (a stale claimant must not clobber a re-claimed job). Moving to
// BROKEN increments repetitions. Returns 1 on success, 0 on
// mismatch/bounds, -1 on error.
int jsx_cas_status(const char* path, int64_t id, int32_t to,
                   uint32_t expect_mask, int64_t expect_worker) {
  if (access(path, F_OK) != 0) return 0;  // namespace dropped: CAS misses
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  if (id < 0 || id >= count) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (expect_mask && !((1u << rec.status) & expect_mask)) return 0;
  if (expect_worker != 0 && !owner_ok(rec, expect_worker)) return 0;
  if (to == kBroken) rec.repetitions += 1;
  rec.status = to;
  clear_spec_on_unlease(&rec, to);
  return idx.write(id, rec) ? 1 : -1;
}

// jsx_cas_status over n ids under ONE flock — the batch-commit
// amortization. ok_out[i] = 1 where the CAS landed; each id is judged
// independently (one lost claim never blocks the rest of the batch).
// Returns how many landed, or -1 on IO error.
int64_t jsx_cas_status_batch(const char* path, const int64_t* ids, int64_t n,
                             int32_t to, uint32_t expect_mask,
                             int64_t expect_worker, int32_t* ok_out) {
  for (int64_t i = 0; i < n; ++i) ok_out[i] = 0;
  if (n <= 0) return 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  int64_t landed = 0;
  Record rec;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t id = ids[i];
    if (id < 0 || id >= count) continue;
    if (!idx.read(id, &rec)) return -1;
    if (expect_mask && !((1u << rec.status) & expect_mask)) continue;
    if (expect_worker != 0 && !owner_ok(rec, expect_worker)) continue;
    if (to == kBroken) rec.repetitions += 1;
    rec.status = to;
    clear_spec_on_unlease(&rec, to);
    if (!idx.write(id, rec)) return -1;
    ok_out[i] = 1;
    ++landed;
  }
  return landed;
}

// Retire a batch in ONE flock cycle: for each id, iff the record is
// RUNNING|FINISHED and `worker` owns the claim (0 = skip the check),
// write its 5 job times (times + i*5) into the record and flip it
// WRITTEN. ok_out[i] = 1 where the commit landed. Returns how many
// landed, or -1 on IO error. The v1 protocol spent two status CASes plus
// a times-sidecar rename per job here.
int64_t jsx_commit_batch(const char* path, const int64_t* ids, int64_t n,
                         int64_t worker, const double* times,
                         int32_t* ok_out) {
  for (int64_t i = 0; i < n; ++i) ok_out[i] = 0;
  if (n <= 0) return 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  int64_t landed = 0;
  Record rec;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t id = ids[i];
    if (id < 0 || id >= count) continue;
    if (!idx.read(id, &rec)) return -1;
    // first-commit-wins: WRITTEN fails this status check, so the
    // losing duplicate's entry is skipped without any state change
    if (rec.status != kRunning && rec.status != kFinished) continue;
    if (worker != 0 && !owner_ok(rec, worker)) continue;
    rec.status = kWritten;
    for (int t = 0; t < kNTimes; ++t) rec.times[t] = times[i * kNTimes + t];
    if (!idx.write(id, rec)) return -1;
    ok_out[i] = 1;
    ++landed;
  }
  return landed;
}

// Record a job's times without touching its status (the single-job
// set_job_times path). Returns 1 on success, 0 on bounds/missing, -1 on
// IO error.
int jsx_set_times(const char* path, int64_t id, const double* times5) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  for (int t = 0; t < kNTimes; ++t) rec.times[t] = times5[t];
  return idx.write(id, rec) ? 1 : -1;
}

// Read one record (times5 gets the 5 job times; all-zero = none
// recorded; spec_state/spec_worker describe any duplicate lease).
// Returns 1 on success, 0 if out of bounds, -1 on error.
int jsx_get(const char* path, int64_t id, int32_t* status,
            int32_t* repetitions, int64_t* worker, double* started,
            double* times5, int32_t* spec_state, int64_t* spec_worker) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  *status = rec.status;
  *repetitions = rec.repetitions;
  *worker = rec.worker;
  *started = rec.started;
  for (int t = 0; t < kNTimes; ++t) times5[t] = rec.times[t];
  *spec_state = rec.spec_state;
  *spec_worker = rec.spec_worker;
  return 1;
}

// Per-status counts into out[6]. Returns total count or -1.
int64_t jsx_counts(const char* path, int64_t* out6) {
  for (int i = 0; i < 6; ++i) out6[i] = 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -1;
  for (const Record& rec : recs) {
    if (rec.status >= 0 && rec.status < 6) out6[rec.status] += 1;
  }
  return (int64_t)recs.size();
}

// RUNNING|FINISHED records whose last liveness signal — claim time or
// worker heartbeat (record.reserved, see jsx_heartbeat) — predates cutoff
// → BROKEN (+1 repetition). Covers hard-killed workers, including a kill
// between the FINISHED and WRITTEN transitions (no analog in the
// reference; see jobstore.py). A legitimately long job whose worker keeps
// heartbeating is never requeued, however long it runs.
int64_t jsx_requeue_stale(const char* path, double cutoff) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -1;
  int64_t n = 0;
  for (int64_t id = 0; id < (int64_t)recs.size(); ++id) {
    Record& rec = recs[(size_t)id];
    const double live =
        rec.reserved > rec.started ? rec.reserved : rec.started;
    if ((rec.status == kRunning || rec.status == kFinished) &&
        live < cutoff) {
      rec.status = kBroken;
      rec.repetitions += 1;
      clear_spec_on_unlease(&rec, kBroken);
      if (!idx.write(id, rec)) return -1;
      ++n;
    }
  }
  return n;
}

// Refresh the liveness timestamp (record.reserved) of a RUNNING|FINISHED
// record, iff `worker` still owns the claim (0 = skip the ownership
// check). Returns 1 on success, 0 on mismatch/bounds/missing, -1 on
// error. The worker runtime beats this during long map/reduce jobs so
// the server's stale-requeue measures silence, not elapsed time.
int jsx_heartbeat(const char* path, int64_t id, int64_t worker, double now) {
  if (access(path, F_OK) != 0) return 0;  // namespace dropped: miss
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (rec.status != kRunning && rec.status != kFinished) return 0;
  if (worker != 0 && !owner_ok(rec, worker)) return 0;
  rec.reserved = now;
  return idx.write(id, rec) ? 1 : -1;
}

// jsx_heartbeat over n ids under ONE flock — the batch lease's single
// heartbeat thread beats every leased job in one lock cycle. Returns how
// many beats landed, or -1 on IO error.
int64_t jsx_heartbeat_batch(const char* path, const int64_t* ids, int64_t n,
                            int64_t worker, double now) {
  if (n <= 0) return 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  int64_t landed = 0;
  Record rec;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t id = ids[i];
    if (id < 0 || id >= count) continue;
    if (!idx.read(id, &rec)) return -1;
    if (rec.status != kRunning && rec.status != kFinished) continue;
    if (worker != 0 && !owner_ok(rec, worker)) continue;
    rec.reserved = now;
    if (!idx.write(id, rec)) return -1;
    ++landed;
  }
  return landed;
}

// Bulk snapshot: fill caller arrays (capacity cap) with every record's
// state in one locked pass. Returns the number filled, or -1 on error.
int64_t jsx_snapshot(const char* path, int32_t* statuses, int32_t* reps,
                     int64_t* workers, double* started, double* times,
                     int32_t* spec_states, int64_t* spec_workers,
                     int64_t cap) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -1;
  int64_t count = (int64_t)recs.size();
  if (count > cap) count = cap;
  for (int64_t id = 0; id < count; ++id) {
    const Record& rec = recs[(size_t)id];
    statuses[id] = rec.status;
    reps[id] = rec.repetitions;
    workers[id] = rec.worker;
    started[id] = rec.started;
    for (int t = 0; t < kNTimes; ++t)
      times[id * kNTimes + t] = rec.times[t];
    spec_states[id] = rec.spec_state;
    spec_workers[id] = rec.spec_worker;
  }
  return count;
}

// BROKEN records with repetitions >= max_retries → FAILED. Returns how many.
int64_t jsx_scavenge(const char* path, int32_t max_retries) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -1;
  int64_t n = 0;
  for (int64_t id = 0; id < (int64_t)recs.size(); ++id) {
    Record& rec = recs[(size_t)id];
    if (rec.status == kBroken && rec.repetitions >= max_retries) {
      rec.status = kFailed;
      if (!idx.write(id, rec)) return -1;
      ++n;
    }
  }
  return n;
}

// -- duplicate leases (speculative execution, DESIGN §21) -------------------

// Mark a RUNNING record speculation-OPEN (a shadow lease may be taken by
// jsx_claim_spec). CASed on (RUNNING, no existing speculation) so the
// detector's repeated passes are idempotent and a job carries at most ONE
// shadow lease. Returns 1 landed, 0 refused, -1 on error.
int jsx_speculate(const char* path, int64_t id) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (rec.status != kRunning || rec.spec_state != kSpecNone) return 0;
  rec.spec_worker = 0;
  rec.spec_state = kSpecOpen;
  return idx.write(id, rec) ? 1 : -1;
}

// Take ONE speculation-open shadow lease for `worker`. A worker never
// shadows its own job; records whose claimant sits on a DIFFERENT
// placement tag are preferred, lowest id first within each preference
// class (same scan order as the Python engine). Fills *out_reps;
// returns the job id, -1 when nothing is open, or -2 on IO error —
// "no lease" and "the index is broken" must stay distinguishable, or
// speculation dies silently on a sick disk.
int64_t jsx_claim_spec(const char* path, int64_t worker, int32_t* out_reps) {
  if (access(path, F_OK) != 0) return -1;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -2;
  std::vector<Record> recs;
  if (!idx.read_all(&recs)) return -2;
  const uint64_t my_tag = worker_tag(worker);
  int64_t fallback = -1;
  for (int64_t id = 0; id < (int64_t)recs.size(); ++id) {
    const Record& rec = recs[(size_t)id];
    if (rec.status != kRunning || rec.spec_state != kSpecOpen ||
        rec.worker == worker)
      continue;
    if (worker_tag(rec.worker) != my_tag) {
      Record take = rec;
      take.spec_worker = worker;
      take.spec_state = kSpecTaken;
      if (!idx.write(id, take)) return -2;
      *out_reps = take.repetitions;
      return id;
    }
    if (fallback < 0) fallback = id;
  }
  if (fallback >= 0) {
    Record take = recs[(size_t)fallback];
    take.spec_worker = worker;
    take.spec_state = kSpecTaken;
    if (!idx.write(fallback, take)) return -2;
    *out_reps = take.repetitions;
    return fallback;
  }
  return -1;
}

// Dissolve a shadow lease `worker` holds — the loser / failure path; the
// job's status and repetitions are never touched (the original claimant
// still owns the lease). worker == 0 clears any OPEN or TAKEN speculation
// (the detector's retraction). Returns 1 cleared, 0 refused, -1 on error.
int jsx_cancel_spec(const char* path, int64_t id, int64_t worker) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (worker != 0) {
    if (rec.spec_state != kSpecTaken || rec.spec_worker != worker) return 0;
  } else if (rec.spec_state == kSpecNone) {
    return 0;
  }
  rec.spec_worker = 0;
  rec.spec_state = kSpecNone;
  rec.spec_pad = 0;
  return idx.write(id, rec) ? 1 : -1;
}

}  // extern "C"
