// Native job-status index — the coordination client's hot path.
//
// TPU-native equivalent of the reference's native C++ layer (SURVEY.md
// §2.4): where lua-mapreduce links luamongo + mongo-cxx-driver to talk to a
// MongoDB control plane, this framework's control plane is a shared-file
// compare-and-swap index, and this library is its native engine. The Python
// fallback (coord/idx_py.py) implements the identical on-disk format; both
// may operate on the same files concurrently.
//
// Concurrency model: every operation opens the index file, takes an
// exclusive flock, operates with pread/pwrite, and releases on close. flock
// is process-crash-safe (the OS drops the lock when the holder dies), which
// is what makes worker failure recovery sound with no lease machinery.
//
// Layout (little-endian, matching idx_py.py):
//   header: char magic[8] = "JSIX0001"; int64 count;
//   record: int32 status; int32 repetitions; int64 worker; double started;
//           double reserved;   // 32 bytes; reserved = last heartbeat
//                              // time (0.0 = never beaten)

#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'J', 'S', 'I', 'X', '0', '0', '0', '1'};
constexpr int64_t kHeaderSize = 16;
constexpr int64_t kRecordSize = 32;

// Status values mirror core/constants.py (reference utils.lua:33-40).
enum Status : int32_t {
  kWaiting = 0,
  kRunning = 1,
  kBroken = 2,
  kFinished = 3,
  kWritten = 4,
  kFailed = 5,
};

constexpr uint32_t kClaimMask = (1u << kWaiting) | (1u << kBroken);

#pragma pack(push, 1)
struct Header {
  char magic[8];
  int64_t count;
};
struct Record {
  int32_t status;
  int32_t repetitions;
  int64_t worker;
  double started;
  double reserved;
};
#pragma pack(pop)

static_assert(sizeof(Header) == kHeaderSize, "header layout");
static_assert(sizeof(Record) == kRecordSize, "record layout");

class LockedIndex {
 public:
  explicit LockedIndex(const char* path, bool create)
      : fd_(open(path, O_RDWR | (create ? O_CREAT : 0), 0666)) {
    if (fd_ >= 0 && flock(fd_, LOCK_EX) != 0) {
      close(fd_);
      fd_ = -1;
    }
  }
  ~LockedIndex() {
    if (fd_ >= 0) close(fd_);  // close releases the flock
  }
  bool ok() const { return fd_ >= 0; }

  int64_t count() const {
    Header h;
    if (pread(fd_, &h, sizeof h, 0) != (ssize_t)sizeof h) return 0;
    if (memcmp(h.magic, kMagic, sizeof kMagic) != 0) return -1;
    return h.count;
  }

  bool set_count(int64_t n) const {
    Header h;
    memcpy(h.magic, kMagic, sizeof kMagic);
    h.count = n;
    return pwrite(fd_, &h, sizeof h, 0) == (ssize_t)sizeof h;
  }

  bool read(int64_t id, Record* rec) const {
    return pread(fd_, rec, sizeof *rec, kHeaderSize + id * kRecordSize) ==
           (ssize_t)sizeof *rec;
  }

  bool write(int64_t id, const Record& rec) const {
    return pwrite(fd_, &rec, sizeof rec, kHeaderSize + id * kRecordSize) ==
           (ssize_t)sizeof rec;
  }

 private:
  int fd_;
};

double now_seconds() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

}  // namespace

extern "C" {

// Append n WAITING records; returns first new id, or -1 on error.
int64_t jsx_insert(const char* path, int64_t n) {
  LockedIndex idx(path, /*create=*/true);
  if (!idx.ok()) return -1;
  int64_t count = idx.count();  // 0 for a freshly created empty file
  if (count < 0) return -1;
  Record rec{kWaiting, 0, 0, 0.0, 0.0};
  for (int64_t i = 0; i < n; ++i) {
    if (!idx.write(count + i, rec)) return -1;
  }
  if (!idx.set_count(count + n)) return -1;
  return count;
}

// Number of records, 0 if missing, -1 on corruption.
int64_t jsx_count(const char* path) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  return idx.count();
}

// Claim first WAITING|BROKEN record for worker (preferred ids first; when
// steal == 0 only the preferred ids are considered — map-affinity mode).
// Returns claimed id or -1.
int64_t jsx_claim(const char* path, int64_t worker, const int64_t* preferred,
                  int64_t n_preferred, int32_t steal) {
  if (access(path, F_OK) != 0) return -1;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  if (count <= 0) return -1;

  auto try_id = [&](int64_t id) -> bool {
    Record rec;
    if (!idx.read(id, &rec)) return false;
    if (!((1u << rec.status) & kClaimMask)) return false;
    rec.status = kRunning;
    rec.worker = worker;
    rec.started = now_seconds();
    rec.reserved = 0.0;  // fresh claim, fresh silence clock (= idx_py)
    return idx.write(id, rec);
  };

  for (int64_t i = 0; i < n_preferred; ++i) {
    const int64_t id = preferred[i];
    if (id >= 0 && id < count && try_id(id)) return id;
  }
  if (steal) {
    for (int64_t id = 0; id < count; ++id) {
      if (try_id(id)) return id;
    }
  }
  return -1;
}

// CAS status; expect_mask is a bitmask of (1<<status), 0 = unconditional;
// expect_worker != 0 additionally requires the record's claim owner to
// match (a stale claimant must not clobber a re-claimed job). Moving to
// BROKEN increments repetitions. Returns 1 on success, 0 on
// mismatch/bounds, -1 on error.
int jsx_cas_status(const char* path, int64_t id, int32_t to,
                   uint32_t expect_mask, int64_t expect_worker) {
  if (access(path, F_OK) != 0) return 0;  // namespace dropped: CAS misses
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  if (id < 0 || id >= count) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (expect_mask && !((1u << rec.status) & expect_mask)) return 0;
  if (expect_worker != 0 && rec.worker != expect_worker) return 0;
  if (to == kBroken) rec.repetitions += 1;
  rec.status = to;
  return idx.write(id, rec) ? 1 : -1;
}

// Read one record. Returns 1 on success, 0 if out of bounds, -1 on error.
int jsx_get(const char* path, int64_t id, int32_t* status,
            int32_t* repetitions, int64_t* worker, double* started) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  *status = rec.status;
  *repetitions = rec.repetitions;
  *worker = rec.worker;
  *started = rec.started;
  return 1;
}

// Per-status counts into out[6]. Returns total count or -1.
int64_t jsx_counts(const char* path, int64_t* out6) {
  for (int i = 0; i < 6; ++i) out6[i] = 0;
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  Record rec;
  for (int64_t id = 0; id < count; ++id) {
    if (!idx.read(id, &rec)) return -1;
    if (rec.status >= 0 && rec.status < 6) out6[rec.status] += 1;
  }
  return count;
}

// RUNNING|FINISHED records whose last liveness signal — claim time or
// worker heartbeat (record.reserved, see jsx_heartbeat) — predates cutoff
// → BROKEN (+1 repetition). Covers hard-killed workers, including a kill
// between the FINISHED and WRITTEN transitions (no analog in the
// reference; see jobstore.py). A legitimately long job whose worker keeps
// heartbeating is never requeued, however long it runs.
int64_t jsx_requeue_stale(const char* path, double cutoff) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  int64_t n = 0;
  Record rec;
  for (int64_t id = 0; id < count; ++id) {
    if (!idx.read(id, &rec)) return -1;
    const double live =
        rec.reserved > rec.started ? rec.reserved : rec.started;
    if ((rec.status == kRunning || rec.status == kFinished) &&
        live < cutoff) {
      rec.status = kBroken;
      rec.repetitions += 1;
      if (!idx.write(id, rec)) return -1;
      ++n;
    }
  }
  return n;
}

// Refresh the liveness timestamp (record.reserved) of a RUNNING|FINISHED
// record, iff `worker` still owns the claim (0 = skip the ownership
// check). Returns 1 on success, 0 on mismatch/bounds/missing, -1 on
// error. The worker runtime beats this during long map/reduce jobs so
// the server's stale-requeue measures silence, not elapsed time.
int jsx_heartbeat(const char* path, int64_t id, int64_t worker, double now) {
  if (access(path, F_OK) != 0) return 0;  // namespace dropped: miss
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  if (id < 0 || id >= idx.count()) return 0;
  Record rec;
  if (!idx.read(id, &rec)) return -1;
  if (rec.status != kRunning && rec.status != kFinished) return 0;
  if (worker != 0 && rec.worker != worker) return 0;
  rec.reserved = now;
  return idx.write(id, rec) ? 1 : -1;
}

// Bulk snapshot: fill caller arrays (capacity cap) with every record's
// state in one locked pass. Returns the number filled, or -1 on error.
int64_t jsx_snapshot(const char* path, int32_t* statuses, int32_t* reps,
                     int64_t* workers, double* started, int64_t cap) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  int64_t count = idx.count();
  if (count > cap) count = cap;
  Record rec;
  for (int64_t id = 0; id < count; ++id) {
    if (!idx.read(id, &rec)) return -1;
    statuses[id] = rec.status;
    reps[id] = rec.repetitions;
    workers[id] = rec.worker;
    started[id] = rec.started;
  }
  return count;
}

// BROKEN records with repetitions >= max_retries → FAILED. Returns how many.
int64_t jsx_scavenge(const char* path, int32_t max_retries) {
  if (access(path, F_OK) != 0) return 0;
  LockedIndex idx(path, false);
  if (!idx.ok()) return -1;
  const int64_t count = idx.count();
  int64_t n = 0;
  Record rec;
  for (int64_t id = 0; id < count; ++id) {
    if (!idx.read(id, &rec)) return -1;
    if (rec.status == kBroken && rec.repetitions >= max_retries) {
      rec.status = kFailed;
      if (!idx.write(id, rec)) return -1;
      ++n;
    }
  }
  return n;
}

}  // extern "C"
