"""Job-index engine selection: native C++ via ctypes, or pure Python.

The native library (native/jobstore.cpp) is compiled on first use with the
host toolchain and cached next to the source; if compilation or loading
fails the pure-Python engine (idx_py.py) takes over — both speak the same
on-disk format, so the choice is per-process, not per-cluster.

One deliberate exception to the silent fallback: a native library that
LOADS but whose on-disk layout disagrees with idx_py.py (or that lacks
the ``jsx_abi`` self-description export — only possible for a
hand-placed binary, since the build cache is keyed on a source hash)
RAISES instead of falling back. Both engines write the same index
files, so an ABI drift is corruption, not a degraded mode; delete the
cached .so to rebuild, or set LMR_DISABLE_NATIVE=1 to force Python.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.core.constants import MAX_JOB_RETRIES, Status
from lua_mapreduce_tpu.core.native_build import load_native
from lua_mapreduce_tpu.coord.idx_py import PyJobIndex
from lua_mapreduce_tpu.faults.errors import (NativeEngineError,
                                             NativeIndexError)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "jobstore.cpp")
_SO = os.path.join(_NATIVE_DIR, "libjobstore.so")


def _abi_check(lib: ctypes.CDLL) -> None:
    """Refuse a native engine whose on-disk layout drifted from
    idx_py.py — both engines write the SAME index files, so a mismatch
    would silently corrupt live coordination state. Native builds
    without the export (a stale cached .so from before the guard) are
    rejected the same way: unverifiable is as bad as wrong."""
    from lua_mapreduce_tpu.coord import idx_py

    try:
        lib.jsx_abi.restype = ctypes.c_int32
        lib.jsx_abi.argtypes = [ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.POINTER(ctypes.c_int32)]
    except AttributeError:
        raise NativeEngineError(
            f"native job index {_SO} predates the ABI guard — rebuild it "
            "(delete the cached .so) or set LMR_DISABLE_NATIVE=1")
    magic = ctypes.create_string_buffer(8)
    sizes = (ctypes.c_int64 * 2)()
    statuses = (ctypes.c_int32 * 6)()
    lib.jsx_abi(magic, sizes, statuses)
    native = (magic.raw, sizes[0], sizes[1], list(statuses))
    python = (idx_py.MAGIC, idx_py.HEADER_SIZE, idx_py.RECORD_SIZE,
              [int(s) for s in Status])
    if native != python:
        raise NativeEngineError(
            "native job index ABI drifted from coord/idx_py.py: native "
            f"{native} vs python {python} — the engines share index "
            "files byte-for-byte and must agree exactly")


def _load() -> Optional[ctypes.CDLL]:
    lib = load_native(_SRC, _SO)
    if lib is None or getattr(lib, "_jsx_configured", False):
        return lib
    _abi_check(lib)
    lib._jsx_configured = True
    lib.jsx_insert.restype = ctypes.c_int64
    lib.jsx_insert.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.jsx_count.restype = ctypes.c_int64
    lib.jsx_count.argtypes = [ctypes.c_char_p]
    lib.jsx_claim.restype = ctypes.c_int64
    lib.jsx_claim.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                              ctypes.POINTER(ctypes.c_int64),
                              ctypes.c_int64, ctypes.c_int32]
    lib.jsx_claim_batch.restype = ctypes.c_int64
    lib.jsx_claim_batch.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.c_int64, ctypes.c_int32,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int64]
    lib.jsx_cas_status_batch.restype = ctypes.c_int64
    lib.jsx_cas_status_batch.argtypes = [ctypes.c_char_p,
                                         ctypes.POINTER(ctypes.c_int64),
                                         ctypes.c_int64, ctypes.c_int32,
                                         ctypes.c_uint32, ctypes.c_int64,
                                         ctypes.POINTER(ctypes.c_int32)]
    lib.jsx_commit_batch.restype = ctypes.c_int64
    lib.jsx_commit_batch.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.c_int64, ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_double),
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.jsx_set_times.restype = ctypes.c_int
    lib.jsx_set_times.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_double)]
    lib.jsx_heartbeat_batch.restype = ctypes.c_int64
    lib.jsx_heartbeat_batch.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_int64, ctypes.c_int64,
                                        ctypes.c_double]
    lib.jsx_cas_status.restype = ctypes.c_int
    lib.jsx_cas_status.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_int32, ctypes.c_uint32,
                                   ctypes.c_int64]
    lib.jsx_get.restype = ctypes.c_int
    lib.jsx_get.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                            ctypes.POINTER(ctypes.c_int32),
                            ctypes.POINTER(ctypes.c_int32),
                            ctypes.POINTER(ctypes.c_int64),
                            ctypes.POINTER(ctypes.c_double),
                            ctypes.POINTER(ctypes.c_double),
                            ctypes.POINTER(ctypes.c_int32),
                            ctypes.POINTER(ctypes.c_int64)]
    lib.jsx_speculate.restype = ctypes.c_int
    lib.jsx_speculate.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.jsx_claim_spec.restype = ctypes.c_int64
    lib.jsx_claim_spec.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int32)]
    lib.jsx_cancel_spec.restype = ctypes.c_int
    lib.jsx_cancel_spec.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64]
    lib.jsx_counts.restype = ctypes.c_int64
    lib.jsx_counts.argtypes = [ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_int64)]
    lib.jsx_scavenge.restype = ctypes.c_int64
    lib.jsx_scavenge.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.jsx_requeue_stale.restype = ctypes.c_int64
    lib.jsx_requeue_stale.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.jsx_heartbeat.restype = ctypes.c_int
    lib.jsx_heartbeat.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_double]
    lib.jsx_snapshot.restype = ctypes.c_int64
    lib.jsx_snapshot.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int32),
                                 ctypes.POINTER(ctypes.c_int32),
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.POINTER(ctypes.c_double),
                                 ctypes.POINTER(ctypes.c_double),
                                 ctypes.POINTER(ctypes.c_int32),
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.c_int64]
    return lib


class NativeJobIndex:
    """ctypes facade over native/jobstore.cpp with PyJobIndex's API."""

    def __init__(self, path: str, lib: ctypes.CDLL):
        self.path = path
        self._p = path.encode()
        self._lib = lib

    def insert(self, n: int) -> int:
        r = self._lib.jsx_insert(self._p, n)
        if r < 0:
            raise NativeIndexError(f"jsx_insert failed on {self.path}")
        return r

    def count(self) -> int:
        r = self._lib.jsx_count(self._p)
        if r < 0:
            raise NativeIndexError(f"jsx_count failed on {self.path}")
        return r

    def claim(self, worker: int, now: float,
              preferred: Optional[Sequence[int]] = None,
              steal: bool = True) -> int:
        # ``now`` is taken by the native side's own clock; the arg keeps the
        # engines' signatures identical.
        pref = preferred or ()
        arr = (ctypes.c_int64 * len(pref))(*pref)
        return self._lib.jsx_claim(self._p, worker, arr, len(pref),
                                   1 if steal else 0)

    def claim_batch(self, worker: int, now: float, k: int,
                    preferred: Optional[Sequence[int]] = None,
                    steal: bool = True) -> List[Tuple[int, int]]:
        if k <= 0:
            return []
        pref = preferred or ()
        arr = (ctypes.c_int64 * len(pref))(*pref)
        out_ids = (ctypes.c_int64 * k)()
        out_reps = (ctypes.c_int32 * k)()
        n = self._lib.jsx_claim_batch(self._p, worker, arr, len(pref),
                                      1 if steal else 0, out_ids, out_reps, k)
        if n < 0:
            raise NativeIndexError(f"jsx_claim_batch failed on {self.path}")
        return [(out_ids[i], out_reps[i]) for i in range(n)]

    def cas_status_batch(self, ids: Sequence[int], to: Status,
                         expect_mask: int = 0,
                         expect_worker: int = 0) -> List[bool]:
        if not ids:
            return []
        arr = (ctypes.c_int64 * len(ids))(*ids)
        ok = (ctypes.c_int32 * len(ids))()
        n = self._lib.jsx_cas_status_batch(self._p, arr, len(ids), int(to),
                                           expect_mask, expect_worker, ok)
        if n < 0:
            raise NativeIndexError(f"jsx_cas_status_batch failed on {self.path}")
        return [bool(ok[i]) for i in range(len(ids))]

    def commit_batch(self, entries: Sequence[tuple],
                     worker: int) -> List[bool]:
        if not entries:
            return []
        n = len(entries)
        ids = (ctypes.c_int64 * n)(*[jid for jid, _ in entries])
        flat = []
        for _, times in entries:
            flat.extend(times if times is not None else (0.0,) * 5)
        times_arr = (ctypes.c_double * (n * 5))(*flat)
        ok = (ctypes.c_int32 * n)()
        r = self._lib.jsx_commit_batch(self._p, ids, n, worker, times_arr,
                                       ok)
        if r < 0:
            raise NativeIndexError(f"jsx_commit_batch failed on {self.path}")
        return [bool(ok[i]) for i in range(n)]

    def set_times(self, job_id: int, times: Sequence[float]) -> bool:
        arr = (ctypes.c_double * 5)(*times)
        r = self._lib.jsx_set_times(self._p, job_id, arr)
        if r < 0:
            raise NativeIndexError(f"jsx_set_times failed on {self.path}")
        return bool(r)

    def heartbeat_batch(self, ids: Sequence[int], worker: int,
                        now: float) -> int:
        if not ids:
            return 0
        arr = (ctypes.c_int64 * len(ids))(*ids)
        n = self._lib.jsx_heartbeat_batch(self._p, arr, len(ids), worker, now)
        if n < 0:
            raise NativeIndexError(f"jsx_heartbeat_batch failed on {self.path}")
        return n

    def cas_status(self, job_id: int, to: Status, expect_mask: int = 0,
                   expect_worker: int = 0) -> bool:
        r = self._lib.jsx_cas_status(self._p, job_id, int(to), expect_mask,
                                     expect_worker)
        if r < 0:
            raise NativeIndexError(f"jsx_cas_status failed on {self.path}")
        return bool(r)

    def get(self, job_id: int) -> Optional[tuple]:
        status = ctypes.c_int32()
        reps = ctypes.c_int32()
        worker = ctypes.c_int64()
        started = ctypes.c_double()
        times = (ctypes.c_double * 5)()
        spec_state = ctypes.c_int32()
        spec_worker = ctypes.c_int64()
        r = self._lib.jsx_get(self._p, job_id, ctypes.byref(status),
                              ctypes.byref(reps), ctypes.byref(worker),
                              ctypes.byref(started), times,
                              ctypes.byref(spec_state),
                              ctypes.byref(spec_worker))
        if r < 0:
            raise NativeIndexError(f"jsx_get failed on {self.path}")
        if r == 0:
            return None
        t = tuple(times)
        return (status.value, reps.value, worker.value, started.value,
                None if t == (0.0,) * 5 else t, spec_state.value,
                spec_worker.value)

    def speculate(self, job_id: int) -> bool:
        r = self._lib.jsx_speculate(self._p, job_id)
        if r < 0:
            raise NativeIndexError(f"jsx_speculate failed on {self.path}")
        return bool(r)

    def claim_spec(self, worker: int) -> Optional[Tuple[int, int]]:
        reps = ctypes.c_int32()
        jid = self._lib.jsx_claim_spec(self._p, worker, ctypes.byref(reps))
        if jid <= -2:
            # -1 means "nothing open"; anything below is a real IO
            # failure and must surface classified, not as a silent
            # speculation blackout
            raise NativeIndexError(f"jsx_claim_spec failed on {self.path}")
        return None if jid < 0 else (jid, reps.value)

    def cancel_spec(self, job_id: int, worker: int) -> bool:
        r = self._lib.jsx_cancel_spec(self._p, job_id, worker)
        if r < 0:
            raise NativeIndexError(f"jsx_cancel_spec failed on {self.path}")
        return bool(r)

    def counts(self) -> Dict[Status, int]:
        out = (ctypes.c_int64 * 6)()
        r = self._lib.jsx_counts(self._p, out)
        if r < 0:
            raise NativeIndexError(f"jsx_counts failed on {self.path}")
        return {Status(i): out[i] for i in range(6)}

    def scavenge(self, max_retries: int = MAX_JOB_RETRIES) -> int:
        r = self._lib.jsx_scavenge(self._p, max_retries)
        if r < 0:
            raise NativeIndexError(f"jsx_scavenge failed on {self.path}")
        return r

    def requeue_stale(self, cutoff: float) -> int:
        r = self._lib.jsx_requeue_stale(self._p, cutoff)
        if r < 0:
            raise NativeIndexError(f"jsx_requeue_stale failed on {self.path}")
        return r

    def heartbeat(self, job_id: int, worker: int, now: float) -> bool:
        r = self._lib.jsx_heartbeat(self._p, job_id, worker, now)
        if r < 0:
            raise NativeIndexError(f"jsx_heartbeat failed on {self.path}")
        return bool(r)

    def snapshot(self):
        cap = self.count()
        if cap == 0:
            return []
        statuses = (ctypes.c_int32 * cap)()
        reps = (ctypes.c_int32 * cap)()
        workers = (ctypes.c_int64 * cap)()
        started = (ctypes.c_double * cap)()
        times = (ctypes.c_double * (cap * 5))()
        spec_states = (ctypes.c_int32 * cap)()
        spec_workers = (ctypes.c_int64 * cap)()
        n = self._lib.jsx_snapshot(self._p, statuses, reps, workers,
                                   started, times, spec_states,
                                   spec_workers, cap)
        if n < 0:
            raise NativeIndexError(f"jsx_snapshot failed on {self.path}")
        out = []
        zero = (0.0,) * 5
        for i in range(n):
            t = tuple(times[i * 5:(i + 1) * 5])
            out.append((statuses[i], reps[i], workers[i], started[i],
                        None if t == zero else t, spec_states[i],
                        spec_workers[i]))
        return out


def open_index(path: str, engine: str = "auto"):
    """Open a job index at ``path``.

    engine: "auto" (native if it builds, else python), "native", "python".
    """
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown index engine {engine!r}")
    if engine in ("auto", "native"):
        lib = _load()
        if lib is not None:
            return NativeJobIndex(path, lib)
        if engine == "native":
            cause = ("LMR_DISABLE_NATIVE=1 is set"
                     if os.environ.get("LMR_DISABLE_NATIVE") == "1"
                     else "g++ build failed")
            raise NativeEngineError(
                f"native job index unavailable ({cause})")
    return PyJobIndex(path)


def native_available() -> bool:
    return _load() is not None
