"""Thread-spawn graph: which thread roots may execute each function.

The call graph (analysis/callgraph.py) answers *who calls whom*; this
module answers *who RUNS whom*.  Every ``threading.Thread(target=...)``
construction, every ``pool.submit(fn, ...)`` hand-off, and every
``FleetSupervisor(spawn=...)`` elastic hook is a **spawn site**: the
target resolves to an entry function, and everything reachable from
that entry (over the same call edges the dataflow pass follows) runs on
that spawned thread.  A function's **root set** is then:

- ``"main"`` when it is reachable from any top-of-graph function that
  is not itself a thread entry (public API, module import-time code,
  utest drivers) — the spawning side of every hand-off;
- one label per spawn entry whose closure contains it (the label is the
  entry function's fid, so diagnostics read ``engine/worker.py::
  Worker._beating.beat``).

A shared attribute is *contested* when the union of its accessors'
root sets spans at least two roots — or one root marked **multi**
(spawned in a loop, through a pool, or through the elastic supervisor:
many instances of the same entry race each other).  That contested-ness
test is what keeps the lockset rules (analysis/lockset.py, LMR026+)
quiet on the large majority of fields that only one thread ever sees.

Deliberate limits (the callgraph's, inherited): targets aliased through
locals (``fn = self._loop; Thread(target=fn)``) resolve only when the
local was assigned a constructor result or a def in the same function;
``setattr``-installed entries contribute nothing.  Unresolved targets
are kept (``entry=None``) so the shutdown audit still sees the site.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                                  build_callgraph)
from lua_mapreduce_tpu.analysis.rules import _chain

MAIN = "main"

# call kinds a thread's closure follows: what the entry can actually
# execute. ``param`` stays out (a callback handed *to* the thread body
# is the caller's code — the spawn-site rules handle the hand-off).
_FOLLOW = {"direct", "method", "ctor", "interface"}


@dataclasses.dataclass(frozen=True)
class SpawnSite:
    """One place a new executing thread (or pool task / fleet member)
    is minted."""
    spawner: str             # fid of the constructing function
    rel: str
    line: int
    via: str                 # "thread" | "submit" | "fleet"
    entry: Optional[str]     # resolved entry fid (None = unresolvable)
    daemon: bool             # daemon=True on the Thread ctor
    multi: bool              # in a loop / pool / fleet: many instances
    target_src: str          # diagnostic: the target expression's text


class ThreadGraph:
    """Spawn sites + the per-function root sets derived from them."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.spawns: List[SpawnSite] = []
        self.entries: Set[str] = set()        # resolved entry fids
        self.multi_entries: Set[str] = set()  # entries with many instances
        self.roots: Dict[str, Set[str]] = {}  # fid -> {"main", entry fids}

    def roots_of(self, fid: str) -> Set[str]:
        """Root labels that may execute ``fid`` ({"main"} when the graph
        knows nothing — an unreached function is assumed caller-side)."""
        return self.roots.get(fid) or {MAIN}

    def contested(self, fids: Iterable[str]) -> bool:
        """Can two of these functions run concurrently? True when their
        root union spans >= 2 roots, or any shared root is multi-
        instance (the entry races itself)."""
        union: Set[str] = set()
        for fid in fids:
            union |= self.roots_of(fid)
        if len(union) >= 2:
            return True
        return bool(union & self.multi_entries)


# -- spawn-site detection -----------------------------------------------------


def _own_nodes(fi: FunctionInfo) -> Iterable[ast.AST]:
    """The function's own AST (lambdas included, nested defs/classes
    not) — mirrors CallGraph._own_calls' attribution."""
    if fi.qual == "<module>":
        roots = [n for n in fi.node.body
                 if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.ClassDef))]
    else:
        roots = list(fi.node.body)
    stack = list(roots)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _loop_lines(fi: FunctionInfo) -> Set[int]:
    """Line numbers inside for/while bodies of this function — a spawn
    there mints many instances."""
    lines: Set[int] = set()
    for n in _own_nodes(fi):
        if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
            for c in ast.walk(n):
                if hasattr(c, "lineno"):
                    lines.add(c.lineno)
    return lines


def _ctor_class_of(call: ast.Call) -> Optional[str]:
    """Class name a call mints: a direct ``Worker(...)`` ctor, or the
    base of a fluent builder chain ``Worker(...).configure(...)`` (the
    configure-returns-self idiom every engine object uses)."""
    c = _chain(call.func)
    if c and c[-1][:1].isupper():
        return c[-1]
    node = call.func
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Call):
        return _ctor_class_of(node)
    return None


def _returned_class(g: CallGraph, fid: str) -> Optional[str]:
    """The class a factory function returns: ``return Worker(...)`` or
    ``return w`` where ``w`` is a ctor-typed local (one level deep —
    enough for the CLI ``mint()`` worker factories)."""
    fi = g.functions.get(fid)
    if fi is None:
        return None
    locals_: Dict[str, str] = {}
    for n in _own_nodes(fi):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Call):
            cls = _ctor_class_of(n.value)
            if cls:
                locals_[n.targets[0].id] = cls
    for n in _own_nodes(fi):
        if isinstance(n, ast.Return) and n.value is not None:
            if isinstance(n.value, ast.Name) and n.value.id in locals_:
                return locals_[n.value.id]
            if isinstance(n.value, ast.Call):
                cls = _ctor_class_of(n.value)
                if cls:
                    return cls
    return None


def _local_ctor_types(fi: FunctionInfo,
                      g: Optional[CallGraph] = None) -> Dict[str, str]:
    """``w = Worker(...)`` locals: name -> class name (the minimal alias
    tracking spawn targets like ``Thread(target=w.execute)`` need).
    With a graph, also follows fluent builders and local factory calls
    (``w = mint(...)`` where mint returns a ctor-typed local)."""
    out: Dict[str, str] = {}
    for n in _own_nodes(fi):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)):
            continue
        cls = _ctor_class_of(n.value)
        if cls is None and g is not None \
                and isinstance(n.value.func, ast.Name):
            target = _resolve_local_fn(g, fi, n.value.func.id)
            if target:
                cls = _returned_class(g, target)
        if cls:
            out[n.targets[0].id] = cls
    return out


def _resolve_local_fn(g: CallGraph, fi: FunctionInfo,
                      name: str) -> Optional[str]:
    nested = f"{fi.rel}::{fi.qual}.{name}"
    if nested in g.functions:
        return nested
    qual = fi.qual
    while "." in qual:
        qual = qual.rsplit(".", 1)[0]
        cand = f"{fi.rel}::{qual}.{name}"
        if cand in g.functions:
            return cand
    m = g.modules.get(fi.rel)
    if m is not None and name in m.functions:
        return m.functions[name]
    return None


def _resolve_class_method(g: CallGraph, rel: str, cls: str,
                          meth: str) -> Optional[str]:
    """``cls.meth`` resolved first in ``rel``'s module, else in any
    module defining a class of that name (unique match only)."""
    m = g.modules.get(rel)
    if m is not None:
        fid = g._resolve_method(m, cls, meth)
        if fid:
            return fid
    hits = []
    for om in g.modules.values():
        if cls in om.classes:
            fid = g._resolve_method(om, cls, meth)
            if fid:
                hits.append(fid)
    return hits[0] if len(set(hits)) == 1 else None


def _resolve_target(g: CallGraph, fi: FunctionInfo,
                    expr: ast.AST) -> List[Optional[str]]:
    """Entry fids a spawn-target expression can name.  A lambda target
    yields every function its body calls (the call graph attributes
    those call sites to the spawner, so the edges are already there).
    ``[None]`` = a site the graph cannot resolve."""
    m = g.modules[fi.rel]
    if isinstance(expr, ast.Lambda):
        lines = {c.lineno for c in ast.walk(expr)
                 if isinstance(c, ast.Call)}
        found = []
        for e in g.callees(fi.fid):
            if e.line in lines and e.kind in _FOLLOW:
                found.extend(_expand(g, e))
        return sorted(set(found)) or [None]
    if isinstance(expr, ast.Name):
        name = expr.id
        nested = f"{fi.rel}::{fi.qual}.{name}"
        if nested in g.functions:
            return [nested]
        # a def in an ENCLOSING function (Thread built in a helper of
        # the scope that defined the target)
        qual = fi.qual
        while "." in qual:
            qual = qual.rsplit(".", 1)[0]
            cand = f"{fi.rel}::{qual}.{name}"
            if cand in g.functions:
                return [cand]
        if name in m.functions:
            return [m.functions[name]]
        if name in m.from_imports:
            mod, attr = m.from_imports[name]
            rel = g._find_module(mod)
            if rel and attr in g.modules[rel].functions:
                return [g.modules[rel].functions[attr]]
        return [None]
    c = _chain(expr)
    if c and len(c) == 2:
        recv, meth = c
        if recv in ("self", "cls") and fi.cls:
            fid = g._resolve_method(m, fi.cls, meth)
            return [fid] if fid else [None]
        cls = _local_ctor_types(fi, g).get(recv)
        if cls:
            fid = _resolve_class_method(g, fi.rel, cls, meth)
            return [fid] if fid else [None]
    return [None]


def _expand(g: CallGraph, e) -> List[str]:
    if e.kind == "interface":
        return list(g.iface_targets(e.callee[len("<iface:"):-1]))
    if e.callee.startswith("<"):
        return []
    return [e.callee] if e.callee in g.functions else []


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _src(expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:
        return "<expr>"


def _spawn_sites(g: CallGraph, fi: FunctionInfo) -> Iterable[SpawnSite]:
    loops = _loop_lines(fi)
    for n in _own_nodes(fi):
        if not isinstance(n, ast.Call):
            continue
        c = _chain(n.func)
        if not c:
            continue
        line = n.lineno
        if c[-1] == "Thread" and (len(c) == 1 or c[-2] == "threading"):
            target = _kw(n, "target")
            if target is None:
                continue
            d = _kw(n, "daemon")
            daemon = isinstance(d, ast.Constant) and bool(d.value)
            for entry in _resolve_target(g, fi, target):
                yield SpawnSite(fi.fid, fi.rel, line, "thread", entry,
                                daemon, line in loops, _src(target))
        elif c[-1] == "submit" and len(c) >= 2 and n.args:
            # executor pool hand-off: many tasks share each pool thread
            for entry in _resolve_target(g, fi, n.args[0]):
                yield SpawnSite(fi.fid, fi.rel, line, "submit", entry,
                                False, True, _src(n.args[0]))
        elif c[-1] == "FleetSupervisor":
            target = _kw(n, "spawn") or (n.args[0] if n.args else None)
            if target is None:
                continue
            for entry in _resolve_target(g, fi, target):
                yield SpawnSite(fi.fid, fi.rel, line, "fleet", entry,
                                False, True, _src(target))


# -- root computation ---------------------------------------------------------


def _bfs(g: CallGraph, seeds: Sequence[str]) -> Set[str]:
    seen = set(seeds)
    frontier = list(seeds)
    while frontier:
        cur = frontier.pop()
        for e in g.callees(cur):
            if e.kind not in _FOLLOW:
                continue
            for callee in _expand(g, e):
                if callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
    return seen


def build_thread_graph(g: Optional[CallGraph] = None,
                       paths: Optional[Sequence[str]] = None) -> ThreadGraph:
    """The full pass: find spawn sites, resolve entries, compute root
    sets (main reachability + one closure per entry)."""
    if g is None:
        g = build_callgraph(paths)
    tg = ThreadGraph(g)
    for fid, fi in sorted(g.functions.items()):
        tg.spawns.extend(_spawn_sites(g, fi))
    for s in tg.spawns:
        if s.entry is not None:
            tg.entries.add(s.entry)
            if s.multi:
                tg.multi_entries.add(s.entry)
    # an entry spawned from two distinct sites also races itself
    by_entry: Dict[str, Set[Tuple[str, int]]] = {}
    for s in tg.spawns:
        if s.entry is not None:
            by_entry.setdefault(s.entry, set()).add((s.rel, s.line))
    for entry, sites in by_entry.items():
        if len(sites) > 1:
            tg.multi_entries.add(entry)

    # main reachability: BFS from every top-of-graph function that is
    # not a spawn-only entry. An entry somebody ALSO calls normally
    # keeps its main root through that caller; a target nobody calls
    # (the daemon loop pattern) stays thread-only.
    called: Set[str] = set()
    for edges in g.edges_from.values():
        for e in edges:
            called.update(_expand(g, e))
    spawn_only = {e for e in tg.entries if e not in called}
    seeds = [fid for fid in g.functions
             if fid not in called and fid not in spawn_only]
    main_set = _bfs(g, seeds)
    for fid in g.functions:
        r: Set[str] = set()
        if fid in main_set:
            r.add(MAIN)
        tg.roots[fid] = r
    for entry in sorted(tg.entries):
        for fid in _bfs(g, [entry]):
            tg.roots[fid].add(entry)
    for fid, r in tg.roots.items():
        if not r:
            r.add(MAIN)          # unreached: assume caller-side
    return tg


def shutdown_report(tg: ThreadGraph) -> List[dict]:
    """The thread-shutdown audit's input: every Thread spawn site with
    its daemon flag and whether the spawning module joins a thread at
    all (``.join(`` anywhere in the module — the bounded-stop check the
    leak test enforces dynamically)."""
    out = []
    for s in tg.spawns:
        if s.via != "thread":
            continue          # pool/fleet lifecycles are owner-managed
        mod = tg.graph.modules.get(s.rel)
        joins = mod is not None and ".join(" in mod.source
        out.append({"rel": s.rel, "line": s.line, "entry": s.entry,
                    "daemon": s.daemon, "module_joins": joins,
                    "target": s.target_src})
    return out


def utest() -> None:
    """Self-test: every spawn-site kind resolves on a fixture, root
    sets separate thread-only code from main code, and the real
    package's known daemon loops classify thread-only."""
    g = CallGraph.from_sources([
        ("engine/fx.py", (
            "import threading\n"
            "from sched.controller import FleetSupervisor\n"
            "class W:\n"
            "    def go(self):\n"
            "        def loop():\n"
            "            self.tick()\n"
            "        t = threading.Thread(target=loop, daemon=True)\n"
            "        t.start()\n"
            "        for i in range(3):\n"
            "            threading.Thread(target=self.run_one).start()\n"
            "        pool.submit(self.reduce_one, 1)\n"
            "        sup = FleetSupervisor(spawn=self.mint, retire=print,\n"
            "                              baseline=1, cap=2)\n"
            "    def tick(self):\n"
            "        self.shared = 1\n"
            "    def run_one(self):\n"
            "        pass\n"
            "    def reduce_one(self, i):\n"
            "        pass\n"
            "    def mint(self, i):\n"
            "        pass\n"
            "def main():\n"
            "    W().go()\n"
        )),
        ("sched/controller.py", (
            "class FleetSupervisor:\n"
            "    def __init__(self, spawn, retire, baseline, cap):\n"
            "        pass\n"
        )),
    ])
    tg = build_thread_graph(g)
    by = {(s.via, s.entry): s for s in tg.spawns}
    loop_fid = "engine/fx.py::W.go.loop"
    assert ("thread", loop_fid) in by
    assert by[("thread", loop_fid)].daemon
    assert not by[("thread", loop_fid)].multi
    assert ("thread", "engine/fx.py::W.run_one") in by
    assert by[("thread", "engine/fx.py::W.run_one")].multi  # in a loop
    assert ("submit", "engine/fx.py::W.reduce_one") in by
    assert ("fleet", "engine/fx.py::W.mint") in by
    # roots: loop + tick are thread-only; go/main are main-rooted;
    # tick is reachable ONLY from the loop entry
    assert tg.roots_of(loop_fid) == {loop_fid}
    assert tg.roots_of("engine/fx.py::W.tick") == {loop_fid}
    assert MAIN in tg.roots_of("engine/fx.py::W.go")
    # contested: go (main) vs tick (thread) span two roots; run_one is
    # multi — contested with itself
    assert tg.contested(["engine/fx.py::W.go", "engine/fx.py::W.tick"])
    assert tg.contested(["engine/fx.py::W.run_one"])
    assert not tg.contested(["engine/fx.py::W.go"])

    real = build_thread_graph()
    entries = {s.entry for s in real.spawns if s.entry}
    assert "engine/worker.py::Worker._beating.beat" in entries, entries
    assert "store/sharedfs.py::_writer_loop" in entries, entries
    beat = real.roots_of("engine/worker.py::Worker._beating.beat")
    assert MAIN not in beat, beat      # the daemon loop is thread-only
    # every Thread spawn in the package is daemon or its module joins
    for row in shutdown_report(real):
        assert row["daemon"] or row["module_joins"], row
