"""SARIF 2.1.0 export for lint/deep/contract findings.

SARIF (Static Analysis Results Interchange Format) is the one shape
both CI annotators (GitHub code scanning) and editors (the SARIF viewer
extensions) already speak — emitting it means file:line findings land
as inline annotations with zero glue code.  This is the minimal valid
subset: one run, one driver, the full rule catalog (so viewers can show
the rationale for an id), one result per finding.

``python -m lua_mapreduce_tpu.analysis lint --format sarif`` et al.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from lua_mapreduce_tpu.analysis.lint import Finding, rule_catalog

SARIF_VERSION = "2.1.0"
_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
           "master/Schemata/sarif-schema-2.1.0.json")

_LEVELS = {"error": "error", "warning": "warning"}


def to_sarif(findings: Sequence[Finding],
             tool_name: str = "lmr-analyze") -> Dict:
    """Findings -> a SARIF 2.1.0 log dict (json.dumps-ready)."""
    rules = [{
        "id": r["id"],
        "shortDescription": {"text": r["title"]},
        "fullDescription": {"text": r["rationale"]},
        "defaultConfiguration": {
            "level": _LEVELS.get(r["severity"], "warning")},
    } for r in rule_catalog()]
    index = {r["id"]: i for i, r in enumerate(rules)}
    known = set(index)
    results: List[Dict] = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
            }],
        }
        if f.rule in known:
            res["ruleIndex"] = index[f.rule]
        results.append(res)
    return {
        "$schema": _SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri":
                    "https://example.invalid/lua_mapreduce_tpu",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def format_sarif(findings: Sequence[Finding]) -> str:
    return json.dumps(to_sarif(findings), indent=2)


def validate_sarif(doc: Dict) -> None:
    """Shape assertions over the subset we emit — the export test's
    oracle (mirrors trace/collect.py's validate_chrome role)."""
    assert doc["version"] == SARIF_VERSION
    assert isinstance(doc["runs"], list) and len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"]
    ids = [r["id"] for r in driver["rules"]]
    assert ids == sorted(ids) and len(ids) == len(set(ids))
    for res in run["results"]:
        assert res["level"] in ("error", "warning", "note")
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        if "ruleIndex" in res:
            assert driver["rules"][res["ruleIndex"]]["id"] == res["ruleId"]


def utest() -> None:
    fs = [Finding("LMR005", "error", "train/x.py", 7, 4, "swallowed"),
          Finding("LMR013", "error", "coord/y.py", 3, 0, "deep IO"),
          Finding("LMR022", "error", "task.py", 0, 0, "emit arity")]
    doc = to_sarif(fs)
    validate_sarif(doc)
    assert len(doc["runs"][0]["results"]) == 3
    # zero-line module findings clamp into SARIF's 1-based regions
    assert doc["runs"][0]["results"][2]["locations"][0][
        "physicalLocation"]["region"]["startLine"] == 1
    # catalog covers per-function, deep, and contract bands
    ids = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"LMR001", "LMR013", "LMR020"} <= ids
    json.loads(format_sarif(fs))        # round-trips as JSON
