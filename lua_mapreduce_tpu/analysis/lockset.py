"""Interprocedural lockset + lock-order analysis: the LMR026+ band.

The per-function rules can see one ``with self._lock:`` block; this
pass sees the whole locking *plane*.  It discovers every lock object
the package creates (instance / class / module / local scope), walks
each function once to summarize which locks guard which shared-field
accesses, then closes the summaries over the call graph
(analysis/callgraph.py) two ways:

- **may-held** (union): the locks some caller may hold when a function
  runs — feeds the global lock-acquisition-order graph (an acquisition
  under a may-held lock is an inter-procedural order edge) and LMR029
  (blocking work reachable while a lock is held).
- **must-held** (intersection): the locks every caller provably holds —
  feeds the per-access *lockset* (intra-procedurally held locks union
  must-held), so a helper only ever called under the guard counts as
  guarded.

Thread identity comes from the spawn graph (analysis/threads.py): a
field group is only *contested* when its accessors' root sets span two
thread roots (or one multi-instance root), which keeps the rules quiet
on single-threaded state.  ``Condition`` objects are lock-like — a
``with self._cond:`` region counts as guarded, so the Waiter's
notify/wait hand-off is modeled as happens-before rather than flagged.

The rule band (each fixture-paired in utest, all SARIF-exported):

- **LMR026** — unguarded write/mutate of a multi-thread-reachable field
  that is lock-guarded elsewhere (the classic dropped-lock race).
- **LMR027** — inconsistent lockset: one field guarded by two disjoint
  locks in different places (each access is "locked", no pair excludes).
- **LMR028** — lock-order cycle across call boundaries (extends the
  per-function LMR003 ordering discipline interprocedurally), plus
  re-acquisition of a non-reentrant module/class-scope lock.
- **LMR029** — blocking store/coord RPC, ``time.sleep`` or an injected
  callback reachable while an in-process lock is held (the convoy /
  reentrancy hazard: IO latency multiplied into every waiter).
- **LMR030** — a mutable local published to a spawned thread (closure
  or ``args=``) and read back without a join/wait/queue hand-off.

Deliberate limits (documented, tested): ``lock.acquire()``/``release()``
call pairs are not modeled (the package uses ``with`` exclusively —
LMR001's no-bare-acquire discipline); lambda bodies defer execution and
are skipped when attributing held regions; instance locks are keyed by
creation site, so two *instances* of a class are one label (sound for
order edges, deliberately coarse).

``static_lock_model()`` exports the lock-site map, order edges and
cyclic labels for the runtime sanitizer (utils/lockcheck.py): the
LMR_LOCKCHECK=1 watchdog replays real acquisition orders against this
model during the chaos suite — the same static<->dynamic discipline the
protocol checker applies to its seeded races, here via KNOWN_RACES.
"""

from __future__ import annotations

import ast
import dataclasses
import time
from collections import deque
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis import rules as _r
from lua_mapreduce_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                                  build_callgraph)
from lua_mapreduce_tpu.analysis.dataflow import _DATA_PLANE_CALLS
from lua_mapreduce_tpu.analysis.lint import (Finding, _baseline_match,
                                             _line_disables_in,
                                             load_baseline)
from lua_mapreduce_tpu.analysis.threads import (MAIN, ThreadGraph, _chain,
                                                _local_ctor_types, _own_nodes,
                                                build_thread_graph)

# call kinds the lock closures follow (same plane the thread graph runs)
_FOLLOW = {"direct", "method", "ctor", "interface"}

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}

# method names that mutate the receiver collection in place
_MUTATORS = {"append", "extend", "insert", "pop", "popleft", "appendleft",
             "remove", "clear", "discard", "setdefault", "update", "add"}

# blocking surface for LMR029: the store/JobStore RPC set plus the
# data-plane calls LMR013 polices — minus names that collide with
# builtin collection mutators (list.remove is not store IO) and minus
# bare "write" (every file handle has one; write_bytes/build/read_range
# are the distinctive store spellings)
_BLOCKING_CALLS = (_r._RETRY_BOUNDARY_METHODS
                   | _DATA_PLANE_CALLS) - _MUTATORS - {"write"}

# spawn-site synchronization: a call to one of these between publish
# and read-back is the hand-off LMR030 wants to see
_SYNC_CALLS = {"join", "wait", "get", "result", "shutdown"}


@dataclasses.dataclass(frozen=True)
class ConcRule:
    id: str
    severity: str
    title: str
    rationale: str
    paths: Tuple[str, ...]


CONC_RULES: Tuple[ConcRule, ...] = (
    ConcRule(
        "LMR026", "error",
        "no unguarded writes to lock-guarded multi-thread fields",
        "A field that is written under a lock somewhere and plainly "
        "elsewhere has no lock at all: the unguarded write races every "
        "guarded reader the moment two thread roots can reach the "
        "accessors. The heartbeat/eviction/supervisor planes all share "
        "state this way — one dropped guard silently un-serializes "
        "them.", ()),
    ConcRule(
        "LMR027", "warning",
        "no inconsistent locksets across one field's accesses",
        "Two accesses each dutifully locked — under *different* locks "
        "with an empty intersection — exclude nothing: both critical "
        "sections run concurrently. Usually a refactor split one guard "
        "into two; the fix is picking one lock for the field.", ()),
    ConcRule(
        "LMR028", "error",
        "no interprocedural lock-order cycles",
        "Thread 1 holds A and takes B; thread 2 holds B and — three "
        "calls deep — takes A: a deadlock no single function shows. "
        "This extends the LMR003 ordering discipline across call "
        "boundaries via the global acquisition-order graph; it also "
        "flags re-acquiring a non-reentrant module/class lock on any "
        "call path that already holds it.", ()),
    ConcRule(
        "LMR029", "error",
        "no blocking store/coord RPC reachable while holding a lock",
        "An in-process lock held across store IO, a coord RPC, "
        "time.sleep or an injected callback turns one slow byte into a "
        "convoy: every thread needing the lock waits out the IO, and a "
        "callback that re-enters the lock deadlocks. Snapshot under "
        "the lock, do the IO outside it.", ()),
    ConcRule(
        "LMR030", "warning",
        "no cross-thread publish of a mutable local without a hand-off",
        "A list/dict built locally, handed to a Thread (closure or "
        "args=), then read back with no join/wait/queue in between is "
        "a data race on CPython internals and a logic race everywhere: "
        "the reader sees an arbitrary prefix of the writer's work. "
        "Hand results back through join, an Event, or a Queue.", ()),
)


# -- lock discovery -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LockInfo:
    label: str               # "rel::Cls.attr" | "rel::name" | "rel::qual.x"
    rel: str
    line: int                # creation-site line (0 = synthesized)
    kind: str                # "lock" | "rlock" | "cond"
    scope: str               # "instance" | "class" | "module" | "local"
    cls: Optional[str]
    name: str                # bare attribute/variable name


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    if not isinstance(value, ast.Call):
        return None
    c = _chain(value.func)
    if not c or c[-1] not in _LOCK_CTORS:
        return None
    if len(c) == 1 or c[-2] == "threading":
        return _LOCK_CTORS[c[-1]]
    return None


def _lockish_name(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "cond" in low or "mutex" in low


class _Pass:
    """One full concurrency analysis over a call graph + thread graph."""

    def __init__(self, g: CallGraph, tg: ThreadGraph):
        self.g = g
        self.tg = tg
        self.locks: Dict[str, LockInfo] = {}
        # (rel, cls) -> {attr: (rel2, cls2)} ctor-typed attributes
        self.attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        # (rel, cls) -> attrs assigned from a bare __init__ parameter
        self.ctor_params: Dict[Tuple[str, str], Set[str]] = {}
        # (rel, cls) -> lock attribute names (excluded from field groups)
        self.lock_attrs: Dict[Tuple[str, str], Set[str]] = {}
        self.summaries: Dict[str, "_FnSummary"] = {}
        self.must: Dict[str, FrozenSet[str]] = {}
        self.may: Dict[str, Set[str]] = {}
        self.may_gen: Dict[str, Set[str]] = {}
        self.may_via: Dict[str, Tuple[str, int]] = {}
        self.order_edges: Dict[Tuple[str, str], "Acq"] = {}
        self.edges_gen: Set[Tuple[str, str]] = set()
        self.reacq: List["Acq"] = []
        self.cyclic: Set[str] = set()
        self.sccs: List[List[str]] = []
        self.raw: List[Finding] = []
        self._ident_cache: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {}

    # -- identities -----------------------------------------------------------

    def class_ident(self, rel: str, name: str) -> Optional[Tuple[str, str]]:
        key = (rel, name)
        if key in self._ident_cache:
            return self._ident_cache[key]
        out: Optional[Tuple[str, str]] = None
        m = self.g.modules.get(rel)
        if m is not None:
            if name in m.classes:
                out = (rel, name)
            elif name in m.from_imports:
                mod, attr = m.from_imports[name]
                r2 = self.g._find_module(mod)
                if r2 and attr in self.g.modules[r2].classes:
                    out = (r2, attr)
        if out is None:
            hits = [r for r, mm in self.g.modules.items()
                    if name in mm.classes]
            if len(hits) == 1:
                out = (hits[0], name)
        self._ident_cache[key] = out
        return out

    # -- phase 1: discovery ---------------------------------------------------

    def discover(self) -> None:
        for rel, m in sorted(self.g.modules.items()):
            for st in m.tree.body:
                self._try_lock_assign(st, rel, scope="module", cls=None,
                                      qual=None)
            for n in ast.walk(m.tree):
                if isinstance(n, ast.ClassDef):
                    for st in n.body:
                        self._try_lock_assign(st, rel, scope="class",
                                              cls=n.name, qual=None)
        for fid, fi in sorted(self.g.functions.items()):
            for n in _own_nodes(fi):
                if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                    continue
                kind = _lock_ctor_kind(n.value)
                t = n.targets[0]
                if kind and isinstance(t, ast.Attribute):
                    c = _chain(t)
                    if c and len(c) == 2 and c[0] == "self" and fi.cls:
                        self._add_lock(f"{fi.rel}::{fi.cls}.{c[1]}", fi.rel,
                                       n.lineno, kind, "instance", fi.cls,
                                       c[1])
                elif kind and isinstance(t, ast.Name) \
                        and fi.qual != "<module>":
                    self._add_lock(f"{fi.rel}::{fi.qual}.{t.id}", fi.rel,
                                   n.lineno, kind, "local", None, t.id)
                # ctor-typed attribute / ctor-param attribute maps
                if isinstance(t, ast.Attribute) and fi.cls:
                    c = _chain(t)
                    if c and len(c) == 2 and c[0] == "self":
                        key = (fi.rel, fi.cls)
                        if isinstance(n.value, ast.Call):
                            vc = _chain(n.value.func)
                            if vc and vc[-1][:1].isupper():
                                ident = self.class_ident(fi.rel, vc[-1])
                                if ident:
                                    self.attr_types.setdefault(
                                        key, {})[c[1]] = ident
                        if fi.name == "__init__" \
                                and isinstance(n.value, ast.Name) \
                                and n.value.id in fi.params:
                            self.ctor_params.setdefault(key, set()).add(c[1])

    def _try_lock_assign(self, st: ast.AST, rel: str, scope: str,
                         cls: Optional[str], qual: Optional[str]) -> None:
        if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)):
            return
        kind = _lock_ctor_kind(st.value)
        if kind is None:
            return
        name = st.targets[0].id
        if scope == "class":
            self._add_lock(f"{rel}::{cls}.{name}", rel, st.lineno, kind,
                           "class", cls, name)
        else:
            self._add_lock(f"{rel}::{name}", rel, st.lineno, kind,
                           "module", None, name)

    def _add_lock(self, label: str, rel: str, line: int, kind: str,
                  scope: str, cls: Optional[str], name: str) -> None:
        if label not in self.locks:
            self.locks[label] = LockInfo(label, rel, line, kind, scope,
                                         cls, name)
            if cls is not None:
                self.lock_attrs.setdefault((rel, cls), set()).add(name)

    # -- lock-use resolution --------------------------------------------------

    def resolve_lock(self, fi: FunctionInfo,
                     expr: ast.AST) -> Optional[str]:
        """The label a with-context expression holds, or None when it
        is not an in-process lock (``_FLock(...)`` calls, files...)."""
        if isinstance(expr, ast.Call):
            return None                  # flock/ctx-manager ctors
        c = _chain(expr)
        if not c or not _lockish_name(c[-1]):
            return None
        last = c[-1]
        if len(c) == 2 and c[0] in ("self", "cls") and fi.cls:
            lbl = f"{fi.rel}::{fi.cls}.{last}"
            if lbl in self.locks:
                return lbl
            hit = self._base_lock(fi.rel, fi.cls, last, set())
            if hit:
                return hit
            cands = {L.label for L in self.locks.values()
                     if L.name == last and L.scope in ("instance", "class")}
            if len(cands) == 1:
                return cands.pop()
            return self._synth(lbl, fi.rel, "instance", fi.cls, last)
        if len(c) == 1:
            qual = fi.qual
            while True:
                lbl = f"{fi.rel}::{qual}.{last}"
                if lbl in self.locks:
                    return lbl
                if "." not in qual:
                    break
                qual = qual.rsplit(".", 1)[0]
            lbl = f"{fi.rel}::{last}"
            if lbl in self.locks:
                return lbl
            cands = {L.label for L in self.locks.values()
                     if L.name == last and L.scope == "module"}
            if len(cands) == 1:
                return cands.pop()
            return self._synth(f"{fi.rel}::{fi.qual}.{last}", fi.rel,
                               "local", None, last)
        if len(c) == 3 and c[0] == "self" and fi.cls:
            ident = self.attr_types.get((fi.rel, fi.cls), {}).get(c[1])
            if ident:
                lbl = f"{ident[0]}::{ident[1]}.{last}"
                if lbl in self.locks:
                    return lbl
                return self._synth(lbl, ident[0], "instance", ident[1], last)
        if len(c) == 2:
            ident = self.class_ident(fi.rel, c[0])
            if ident:                    # Cls._class_lock
                lbl = f"{ident[0]}::{ident[1]}.{last}"
                if lbl in self.locks:
                    return lbl
                hit = self._base_lock(ident[0], ident[1], last, set())
                if hit:
                    return hit
        return self._synth(f"{fi.rel}::{'.'.join(c)}", fi.rel, "local",
                           None, last)

    def _base_lock(self, rel: str, cls: str, name: str,
                   seen: Set[Tuple[str, str]]) -> Optional[str]:
        if (rel, cls) in seen:
            return None
        seen.add((rel, cls))
        m = self.g.modules.get(rel)
        ci = m.classes.get(cls) if m else None
        if ci is None:
            return None
        for bc in ci.bases:
            ident = self.class_ident(rel, bc[-1])
            if ident is None:
                continue
            lbl = f"{ident[0]}::{ident[1]}.{name}"
            if lbl in self.locks:
                return lbl
            hit = self._base_lock(ident[0], ident[1], name, seen)
            if hit:
                return hit
        return None

    def _synth(self, label: str, rel: str, scope: str, cls: Optional[str],
               name: str) -> str:
        # a lock-ish with-context we never saw created: keep it as a
        # site-less label (line 0 — absent from the runtime model)
        self._add_lock(label, rel, 0, "lock", scope, cls, name)
        return label

    # -- phase 2: per-function summaries -------------------------------------

    def summarize(self) -> None:
        for fid, fi in sorted(self.g.functions.items()):
            s = _FnSummary(self, fi)
            s.run()
            self.summaries[fid] = s

    # -- phase 3: propagation -------------------------------------------------

    def _succ(self) -> Dict[str, List[Tuple[str, int, str]]]:
        succ: Dict[str, List[Tuple[str, int, str]]] = {}
        for fid in self.g.functions:
            out: List[Tuple[str, int, str]] = []
            for e in self.g.callees(fid):
                if e.kind not in _FOLLOW:
                    continue
                for callee in self._expand(e):
                    out.append((callee, e.line, e.kind))
            succ[fid] = out
        return succ

    def _expand(self, e) -> Iterable[str]:
        if e.kind == "interface":
            return self.g.iface_targets(e.callee[len("<iface:"):-1])
        if e.callee.startswith("<"):
            return ()
        return (e.callee,) if e.callee in self.g.functions else ()

    def propagate(self) -> None:
        succ = self._succ()
        incoming: Dict[str, List[Tuple[str, int]]] = {}
        for fid, outs in succ.items():
            for callee, line, _kind in outs:
                incoming.setdefault(callee, []).append((fid, line))

        # must-held: intersection over incoming call sites; thread
        # entries and top-of-graph functions run with nothing held
        must: Dict[str, Optional[FrozenSet[str]]] = {
            fid: None for fid in self.g.functions}
        entries = set(self.tg.entries)
        seeds = {fid for fid in self.g.functions
                 if fid not in incoming} | entries
        wl = deque(sorted(seeds))
        for fid in seeds:
            must[fid] = frozenset()
        while wl:
            cur = wl.popleft()
            base = must[cur] or frozenset()
            s = self.summaries[cur]
            for callee, line, _kind in succ[cur]:
                if callee in entries:
                    continue             # spawned: starts lock-free
                contrib = base | s.call_held_must.get(line, frozenset())
                old = must[callee]
                new = contrib if old is None else (old & contrib)
                if new != old:
                    must[callee] = new
                    wl.append(callee)
        self.must = {fid: (v or frozenset()) for fid, v in must.items()}

        # may-held, twice. The PRECISE set (findings, order cycles)
        # skips interface edges: the callgraph resolves any bare
        # ``f.write(...)``-shaped call by storage-interface name
        # fan-out, and one such edge from inside a locked region would
        # smear that lock over every store implementation in the
        # package. The GENEROUS set (interface edges included) feeds
        # only the runtime model's edge list, where over-approximation
        # is the sound direction — the watchdog checks observed orders
        # by SUBSET against it.
        self.may = self._may_fixpoint(succ, with_iface=False,
                                      via=self.may_via)
        self.may_gen = self._may_fixpoint(succ, with_iface=True)

    def _may_fixpoint(self, succ, with_iface: bool,
                      via: Optional[Dict[str, Tuple[str, int]]] = None,
                      ) -> Dict[str, Set[str]]:
        may: Dict[str, Set[str]] = {fid: set() for fid in self.g.functions}
        wl = deque(sorted(self.g.functions))
        while wl:
            cur = wl.popleft()
            base = may[cur]
            s = self.summaries[cur]
            for callee, line, kind in succ[cur]:
                if kind == "interface" and not with_iface:
                    continue
                add = base | s.call_held_may.get(line, frozenset())
                if not add <= may[callee]:
                    may[callee] |= add
                    if via is not None:
                        via.setdefault(callee, (cur, line))
                    wl.append(callee)
        return may

    # -- phase 4: order graph -------------------------------------------------

    def order_graph(self) -> None:
        for fid in sorted(self.summaries):
            s = self.summaries[fid]
            ctx = self.may.get(fid, set())
            gen = self.may_gen.get(fid, set())
            for acq in s.acquisitions:
                for held in sorted(set(acq.held_before) | gen):
                    if held != acq.label:
                        self.edges_gen.add((held, acq.label))
                for held in sorted(set(acq.held_before) | ctx):
                    if held == acq.label:
                        L = self.locks.get(held)
                        if L and L.kind == "lock" \
                                and L.scope in ("module", "class"):
                            self.reacq.append(acq)
                        continue
                    self.order_edges.setdefault((held, acq.label), acq)
        # Tarjan SCC over the label digraph
        adj: Dict[str, List[str]] = {}
        for a, b in self.order_edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        counter = [0]

        def strong(v0: str) -> None:
            work = [(v0, 0)]
            while work:
                v, pi = work.pop()
                if pi == 0:
                    index[v] = low[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    on.add(v)
                recurse = False
                for i in range(pi, len(adj[v])):
                    w = adj[v][i]
                    if w not in index:
                        work.append((v, i + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on:
                        low[v] = min(low[v], index[w])
                if recurse:
                    continue
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        self.sccs.append(sorted(comp))
                        self.cyclic.update(comp)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])

        for v in sorted(adj):
            if v not in index:
                strong(v)

    # -- phase 5: checks ------------------------------------------------------

    def lockset_of(self, acc: "FieldAccess") -> FrozenSet[str]:
        return acc.locks | self.must.get(acc.fid, frozenset())

    def check(self) -> None:
        self._check_fields()
        self._check_cycles()
        self._check_blocking()
        self._check_publish()

    def _check_fields(self) -> None:
        groups: Dict[Tuple[Tuple[str, str], str], List[FieldAccess]] = {}
        for s in self.summaries.values():
            for acc in s.accesses:
                groups.setdefault((acc.ident, acc.attr), []).append(acc)
        for (ident, attr), accs in sorted(groups.items()):
            eff = [a for a in accs if not a.in_init]
            if not eff:
                continue
            sets = {id(a): self.lockset_of(a) for a in eff}
            guarded = [a for a in eff if sets[id(a)]]
            if not guarded:
                continue                 # never locked: not this band's
            if not self.tg.contested({a.fid for a in eff}):
                continue                 # one thread root: no race
            guard_names = sorted({lbl for a in guarded
                                  for lbl in sets[id(a)]})
            for a in eff:
                if a.kind in ("write", "mutate") and not sets[id(a)]:
                    self.raw.append(Finding(
                        "LMR026", "error", a.rel, a.line, 0,
                        f"unguarded {a.kind} of {ident[1]}.{attr} — the "
                        f"field is guarded by {guard_names[0]} elsewhere "
                        f"and reachable from multiple thread roots"))
            distinct = {sets[id(a)] for a in guarded}
            if len(distinct) >= 2 \
                    and not frozenset.intersection(*distinct):
                counts: Dict[str, int] = {}
                for a in guarded:
                    for lbl in sets[id(a)]:
                        counts[lbl] = counts.get(lbl, 0) + 1
                modal = sorted(counts, key=lambda k: (-counts[k], k))[0]
                for a in guarded:
                    if modal not in sets[id(a)]:
                        self.raw.append(Finding(
                            "LMR027", "warning", a.rel, a.line, 0,
                            f"inconsistent lockset for {ident[1]}.{attr}: "
                            f"this access holds "
                            f"{sorted(sets[id(a)])[0]} but the field is "
                            f"mostly guarded by {modal} — the two "
                            f"critical sections do not exclude"))

    def _check_cycles(self) -> None:
        for acq in self.reacq:
            self.raw.append(Finding(
                "LMR028", "error", acq.rel, acq.line, 0,
                f"re-acquisition of non-reentrant {acq.label} on a call "
                f"path that already holds it (self-deadlock)"))
        for (a, b), acq in sorted(self.order_edges.items()):
            if a in self.cyclic and b in self.cyclic \
                    and any(a in comp and b in comp for comp in self.sccs):
                cyc = next(comp for comp in self.sccs
                           if a in comp and b in comp)
                self.raw.append(Finding(
                    "LMR028", "error", acq.rel, acq.line, 0,
                    f"lock-order cycle: acquiring {b} while holding {a} "
                    f"closes the cycle {' -> '.join(cyc)} — deadlock "
                    f"when two threads interleave the orders"))

    def _check_blocking(self) -> None:
        for fid in sorted(self.summaries):
            if "utest" in fid:
                continue
            s = self.summaries[fid]
            for blk in s.blocking:
                labels = blk.held or frozenset(self.may.get(fid, ()))
                if not labels:
                    continue
                lbl = sorted(labels)[0]
                via = ""
                if not blk.held:
                    w = self.may_via.get(fid)
                    if w:
                        via = f" (lock held by caller — via {w[0]}:{w[1]})"
                self.raw.append(Finding(
                    "LMR029", "error", blk.rel, blk.line, 0,
                    f"{blk.desc} while {lbl} is held{via} — blocking "
                    f"work under an in-process lock convoys every "
                    f"waiter; snapshot under the lock, block outside"))

    def _check_publish(self) -> None:
        for site in self.tg.spawns:
            if site.via != "thread":
                continue
            fi = self.g.functions.get(site.spawner)
            if fi is None or "utest" in fi.qual:
                continue
            call = None
            for n in _own_nodes(fi):
                if isinstance(n, ast.Call) and n.lineno == site.line:
                    c = _chain(n.func)
                    if c and c[-1] == "Thread":
                        call = n
                        break
            if call is None:
                continue
            shared = self._shared_names(fi, call, site)
            if not shared:
                continue
            mutable = set()
            for n in _own_nodes(fi):
                if isinstance(n, ast.Assign) and n.lineno <= site.line \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and self._is_mutable_ctor(n.value):
                    mutable.add(n.targets[0].id)
            hot = shared & mutable
            if not hot:
                continue
            syncs = sorted(c.lineno for c in _r._calls(list(fi.node.body))
                           if (_chain(c.func) or ("",))[-1] in _SYNC_CALLS)
            for n in _own_nodes(fi):
                if isinstance(n, ast.Name) and n.id in hot \
                        and isinstance(n.ctx, ast.Load) \
                        and n.lineno > site.line \
                        and not any(site.line < ln <= n.lineno
                                    for ln in syncs):
                    self.raw.append(Finding(
                        "LMR030", "warning", fi.rel, n.lineno, 0,
                        f"reading {n.id!r} after publishing it to the "
                        f"thread spawned at line {site.line} with no "
                        f"join/wait/queue hand-off — the reader sees an "
                        f"arbitrary prefix of the writer's work"))

    def _shared_names(self, fi: FunctionInfo, call: ast.Call,
                      site) -> Set[str]:
        names: Set[str] = set()
        target = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "args" and isinstance(kw.value,
                                                 (ast.Tuple, ast.List)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Name):
                        names.add(el.id)
        if isinstance(target, ast.Lambda):
            names.update(n.id for n in ast.walk(target.body)
                         if isinstance(n, ast.Name))
        elif site.entry and site.entry in self.g.functions:
            entry = self.g.functions[site.entry]
            if entry.qual.startswith(fi.qual + "."):    # nested closure
                names.update(n.id for n in ast.walk(entry.node)
                             if isinstance(n, ast.Name))
        return names

    @staticmethod
    def _is_mutable_ctor(value: ast.AST) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            c = _chain(value.func)
            return bool(c) and c[-1] in ("list", "dict", "set", "deque",
                                         "defaultdict", "bytearray")
        return False


# -- per-function summary -----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FieldAccess:
    ident: Tuple[str, str]   # (rel, Cls) owning-class identity
    attr: str
    kind: str                # "read" | "write" | "mutate"
    rel: str
    line: int
    fid: str
    locks: FrozenSet[str]    # intra-procedurally held at the access
    in_init: bool


@dataclasses.dataclass(frozen=True)
class Acq:
    label: str
    rel: str
    line: int
    held_before: Tuple[str, ...]
    fid: str


@dataclasses.dataclass(frozen=True)
class BlockingCall:
    rel: str
    line: int
    desc: str
    held: FrozenSet[str]
    fid: str


class _FnSummary:
    def __init__(self, pass_: _Pass, fi: FunctionInfo):
        self.p = pass_
        self.fi = fi
        self.acquisitions: List[Acq] = []
        self.accesses: List[FieldAccess] = []
        self.blocking: List[BlockingCall] = []
        self.call_held_must: Dict[int, FrozenSet[str]] = {}
        self.call_held_may: Dict[int, FrozenSet[str]] = {}
        self._locals = {name: self.p.class_ident(fi.rel, cls)
                        for name, cls in _local_ctor_types(fi).items()}
        # module-level code and utest harnesses are single-threaded
        # drivers: they contribute call edges but not field groups
        self._track_fields = fi.cls is not None or fi.qual != "<module>"
        if "utest" in fi.qual or fi.qual == "<module>":
            self._track_fields = False

    def run(self) -> None:
        self._walk(list(self.fi.node.body), ())

    def _walk(self, stmts: Sequence[ast.AST], held: Tuple[str, ...]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in st.items:
                    self._expr(item.context_expr, tuple(new))
                    lbl = self.p.resolve_lock(self.fi, item.context_expr)
                    if lbl:
                        self.acquisitions.append(Acq(
                            lbl, self.fi.rel, st.lineno, tuple(new),
                            self.fi.fid))
                        new.append(lbl)
                self._walk(st.body, tuple(new))
                continue
            for c in ast.iter_child_nodes(st):
                if not isinstance(c, ast.stmt):
                    self._expr(c, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(st, field, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._walk(sub, held)
            for h in getattr(st, "handlers", ()):
                self._walk(h.body, held)

    # -- expression scan ------------------------------------------------------

    def _expr(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, ast.Lambda):
            return                       # deferred execution
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._field(node.value, "mutate", node, held)
            self._expr(node.slice, held)
            return
        if isinstance(node, ast.Attribute):
            if isinstance(node.ctx, ast.Store):
                kind = "write"
            elif isinstance(node.ctx, ast.Del):
                kind = "mutate"
            else:
                kind = "read"
            if not self._field(node, kind, node, held):
                self._expr(node.value, held)
            return
        for c in ast.iter_child_nodes(node):
            if not isinstance(c, ast.stmt):
                self._expr(c, held)

    def _call(self, node: ast.Call, held: Tuple[str, ...]) -> None:
        hs = frozenset(held)
        line = node.lineno
        if line in self.call_held_must:
            self.call_held_must[line] &= hs
            self.call_held_may[line] |= hs
        else:
            self.call_held_must[line] = hs
            self.call_held_may[line] = hs
        c = _chain(node.func)
        desc = None
        if c:
            if c == ("time", "sleep"):
                desc = "time.sleep()"
            elif len(c) >= 2 and c[-1] in _BLOCKING_CALLS and c[0] != "os" \
                    and not (len(c) == 2 and c[0] in ("self", "cls")):
                desc = f"store/RPC call {'.'.join(c)}()"
            elif len(c) == 1 and c[0] in self.fi.params:
                desc = f"call to parameter {c[0]!r} (injected callback)"
            elif len(c) == 2 and c[0] == "self" and self.fi.cls \
                    and c[1] in self.p.ctor_params.get(
                        (self.fi.rel, self.fi.cls), ()) \
                    and "clock" not in c[1].lower() \
                    and "now" not in c[1].lower():
                # injected clocks are exempt: LMR010 makes every clock
                # injectable repo-wide, and a clock read is a pure,
                # bounded callback — not a reentrancy/IO hazard
                desc = f"constructor-injected callback self.{c[1]}()"
        if desc:
            self.blocking.append(BlockingCall(self.fi.rel, line, desc, hs,
                                              self.fi.fid))
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _MUTATORS:
                if not self._field(f.value, "mutate", f, held):
                    self._expr(f.value, held)
            else:
                self._expr(f.value, held)
        else:
            self._expr(f, held)
        for a in node.args:
            self._expr(a, held)
        for kw in node.keywords:
            self._expr(kw.value, held)

    def _field(self, expr: ast.AST, kind: str, anchor: ast.AST,
               held: Tuple[str, ...]) -> bool:
        if not self._track_fields:
            return False
        c = _chain(expr)
        if not c:
            return False
        fi = self.fi
        ident: Optional[Tuple[str, str]] = None
        attr: Optional[str] = None
        if len(c) == 2 and c[0] == "self" and fi.cls:
            ident, attr = (fi.rel, fi.cls), c[1]
        elif len(c) == 3 and c[0] == "self" and fi.cls:
            ident = self.p.attr_types.get((fi.rel, fi.cls), {}).get(c[1])
            attr = c[2]
        elif len(c) == 2:
            ident = self._locals.get(c[0])
            attr = c[1]
        if ident is None or attr is None:
            return False
        if attr in self.p.lock_attrs.get(ident, ()):
            return False                 # the lock itself, not a field
        self.accesses.append(FieldAccess(
            ident, attr, kind, fi.rel, getattr(anchor, "lineno", fi.lineno),
            fi.fid, frozenset(held), fi.name == "__init__"))
        return True


# -- driver -------------------------------------------------------------------


@dataclasses.dataclass
class ConcResult:
    findings: List[Finding]              # post-suppression
    raw: List[Finding]                   # pre-suppression (audit input)
    graph: CallGraph
    tgraph: ThreadGraph
    locks: Dict[str, LockInfo]
    order_edges: List[Tuple[str, str]]
    edges_gen: List[Tuple[str, str]]     # interface fan-out included
    cycles: List[List[str]]
    wall_s: float


def analyze_conc(paths: Optional[Sequence[str]] = None,
                 baseline: Optional[str] = None,
                 graph: Optional[CallGraph] = None,
                 tgraph: Optional[ThreadGraph] = None) -> ConcResult:
    """The full concurrency pass: locks, summaries, propagation, order
    graph, LMR026-030, suppression — one call."""
    t0 = time.perf_counter()
    if graph is None:
        graph = build_callgraph(paths)
    if tgraph is None:
        tgraph = build_thread_graph(graph)
    p = _Pass(graph, tgraph)
    p.discover()
    p.summarize()
    p.propagate()
    p.order_graph()
    p.check()
    best: Dict[tuple, Finding] = {}
    for f in p.raw:
        best.setdefault(f.key(), f)
    raw = sorted(best.values(), key=Finding.key)
    base = load_baseline(baseline)
    out = []
    for f in raw:
        m = graph.modules.get(f.path)
        if m is not None and f.rule in _line_disables_in(m.lines, f.line):
            continue
        if any(_baseline_match(e, f) for e in base):
            continue
        out.append(f)
    return ConcResult(out, raw, graph, tgraph, p.locks,
                      sorted(p.order_edges),
                      sorted(p.edges_gen | set(p.order_edges)),
                      sorted(p.sccs), time.perf_counter() - t0)


def run_conc(paths: Optional[Sequence[str]] = None,
             baseline: Optional[str] = None) -> List[Finding]:
    """Conc findings surviving suppression — the CLI/gate entry point."""
    return analyze_conc(paths, baseline).findings


def conc_rule_catalog() -> List[Dict[str, object]]:
    return [{"id": r.id, "severity": r.severity, "title": r.title,
             "rationale": r.rationale, "paths": list(r.paths) or ["<all>"]}
            for r in CONC_RULES]


def static_lock_model(res: Optional[ConcResult] = None) -> dict:
    """The runtime sanitizer's ground truth: creation-site -> label for
    every real Lock/RLock (Conditions wrap internal stdlib locks the
    watchdog never sees; synthesized labels have no site), the distinct-
    label order edges, and the labels on any static cycle."""
    if res is None:
        res = analyze_conc()
    sites = {f"{L.rel}:{L.line}": L.label for L in res.locks.values()
             if L.line > 0 and L.kind in ("lock", "rlock")}
    return {"locks": sites,
            "edges": sorted([a, b] for a, b in res.edges_gen),
            "cyclic": sorted({lbl for comp in res.cycles for lbl in comp})}


# -- seeded races (the protocol checker's discipline, applied here) ----------

KNOWN_RACES: Dict[str, Tuple[str, str, str]] = {
    # name -> (rel, expected rule, source)
    "dropped-lock-write": ("engine/fx_ledger.py", "LMR026", (
        "import threading\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.total = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.add, daemon=True).start()\n"
        "    def add(self):\n"
        "        with self._lock:\n"
        "            self.total += 1\n"
        "    def drain(self):\n"
        "        out = self.total\n"
        "        self.total = 0\n"
        "        return out\n"
    )),
    "abba-deadlock": ("engine/fx_pair.py", "LMR028", (
        "import threading\n"
        "class Pair:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def ab(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                self.n += 1\n"
        "    def ba(self):\n"
        "        with self._b_lock:\n"
        "            self._steal()\n"
        "    def _steal(self):\n"
        "        with self._a_lock:\n"
        "            self.n -= 1\n"
    )),
}


def find_seeded(name: str) -> List[Finding]:
    """Run the pass on one seeded-race fixture; the expected rule's
    findings (the conc gate fails when this comes back empty — a pass
    that stops seeing a planted race has quietly lost its teeth)."""
    rel, rule, src = KNOWN_RACES[name]
    g = CallGraph.from_sources([(rel, src)])
    res = analyze_conc(graph=g, baseline="/nonexistent")
    return [f for f in res.findings if f.rule == rule]


def _fx(*files: Tuple[str, str]) -> ConcResult:
    g = CallGraph.from_sources(list(files))
    return analyze_conc(graph=g, baseline="/nonexistent")


def utest() -> None:
    """Self-test: each rule fires on its fixture and stays quiet on the
    clean twin, both seeded races re-find, suppression works, and the
    real package analyzes clean inside the wall budget."""
    # LMR026 via the seeded fixture; the unguarded-everywhere twin and
    # the queue-handoff twin stay quiet (no guard anywhere = not this
    # band's business; join-before-read = proper hand-off)
    hits = find_seeded("dropped-lock-write")
    assert hits and all(f.rule == "LMR026" for f in hits), hits
    assert any(f.line == 13 for f in hits), hits   # self.total = 0
    quiet = _fx(("engine/fx_solo.py", (
        "import threading\n"
        "class Solo:\n"
        "    def __init__(self):\n"
        "        self.v = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.bump, daemon=True).start()\n"
        "    def bump(self):\n"
        "        self.v += 1\n"
    )))
    assert not [f for f in quiet.findings if f.rule == "LMR026"], \
        quiet.findings

    # LMR027: one field, two disjoint guards, two thread roots
    mix = _fx(("engine/fx_mix.py", (
        "import threading\n"
        "class Mix:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "        self.q = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.w1, daemon=True).start()\n"
        "    def w1(self):\n"
        "        with self._a_lock:\n"
        "            self.q += 1\n"
        "    def w2(self):\n"
        "        with self._b_lock:\n"
        "            self.q -= 1\n"
    )))
    assert any(f.rule == "LMR027" for f in mix.findings), mix.findings
    consistent = _fx(("engine/fx_ok.py", (
        "import threading\n"
        "class Ok:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.q = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.w1, daemon=True).start()\n"
        "    def w1(self):\n"
        "        with self._lock:\n"
        "            self.q += 1\n"
        "    def w2(self):\n"
        "        with self._lock:\n"
        "            self.q -= 1\n"
    )))
    assert not [f for f in consistent.findings
                if f.rule in ("LMR026", "LMR027")], consistent.findings

    # LMR028: the seeded ABBA cycle (interprocedural — ba holds B and
    # takes A one call deep), plus module-lock re-acquisition; the
    # consistently-ordered twin stays quiet
    hits = find_seeded("abba-deadlock")
    assert hits and all(f.rule == "LMR028" for f in hits), hits
    re_acq = _fx(("engine/fx_re.py", (
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def a():\n"
        "    with _lock:\n"
        "        b()\n"
        "def b():\n"
        "    with _lock:\n"
        "        pass\n"
    )))
    assert any(f.rule == "LMR028" and f.line == 7
               for f in re_acq.findings), re_acq.findings
    ordered = _fx(("engine/fx_ord.py", (
        "import threading\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                pass\n"
        "    def ab2(self):\n"
        "        with self._a_lock:\n"
        "            self._inner()\n"
        "    def _inner(self):\n"
        "        with self._b_lock:\n"
        "            pass\n"
    )))
    assert not [f for f in ordered.findings if f.rule == "LMR028"], \
        ordered.findings

    # LMR029: store IO under the lock (direct AND one call deep); the
    # hoisted twin stays quiet
    io = _fx(("engine/fx_io.py", (
        "import threading\n"
        "class Sink:\n"
        "    def __init__(self, store):\n"
        "        self._lock = threading.Lock()\n"
        "        self.store = store\n"
        "    def flush(self, name):\n"
        "        with self._lock:\n"
        "            return self.store.read_range(name, 0, 10)\n"
        "    def flush2(self, name):\n"
        "        with self._lock:\n"
        "            self._emit(name)\n"
        "    def _emit(self, name):\n"
        "        return self.store.read_range(name, 0, 10)\n"
    )))
    got = [f for f in io.findings if f.rule == "LMR029"]
    assert {f.line for f in got} == {8, 13}, io.findings
    hoisted = _fx(("engine/fx_ho.py", (
        "import threading\n"
        "class Sink:\n"
        "    def __init__(self, store):\n"
        "        self._lock = threading.Lock()\n"
        "        self.store = store\n"
        "        self.cache = None\n"
        "    def flush(self, name):\n"
        "        data = self.store.read_range(name, 0, 10)\n"
        "        with self._lock:\n"
        "            self.cache = data\n"
    )))
    assert not [f for f in hoisted.findings if f.rule == "LMR029"], \
        hoisted.findings
    # constructor-injected callback called under the lock
    cb = _fx(("engine/fx_cb.py", (
        "import threading\n"
        "class Sup:\n"
        "    def __init__(self, spawn):\n"
        "        self._lock = threading.Lock()\n"
        "        self.spawn = spawn\n"
        "    def grow(self):\n"
        "        with self._lock:\n"
        "            return self.spawn(1)\n"
    )))
    assert any(f.rule == "LMR029" and f.line == 8
               for f in cb.findings), cb.findings

    # LMR030: publish-without-handoff fires; the joined twin is quiet
    pub = _fx(("engine/fx_pub.py", (
        "import threading\n"
        "def run():\n"
        "    box = []\n"
        "    def fill():\n"
        "        box.append(1)\n"
        "    t = threading.Thread(target=fill)\n"
        "    t.start()\n"
        "    return box[0]\n"
    )))
    assert any(f.rule == "LMR030" and f.line == 8
               for f in pub.findings), pub.findings
    joined = _fx(("engine/fx_j.py", (
        "import threading\n"
        "def run():\n"
        "    box = []\n"
        "    def fill():\n"
        "        box.append(1)\n"
        "    t = threading.Thread(target=fill)\n"
        "    t.start()\n"
        "    t.join()\n"
        "    return box[0]\n"
    )))
    assert not [f for f in joined.findings if f.rule == "LMR030"], \
        joined.findings

    # inline suppression holds for conc findings too
    rel, _rule, src = KNOWN_RACES["dropped-lock-write"]
    sup = _fx((rel, src.replace(
        "        self.total = 0\n",
        "        self.total = 0  # lmr: disable=LMR026\n")))
    assert not [f for f in sup.findings if f.rule == "LMR026"], sup.findings

    # the real package: clean, deadlock-free, inside the wall budget,
    # with the known lock plane discovered and the model exportable
    res = analyze_conc()
    assert res.wall_s < 30.0, res.wall_s
    assert "trace/span.py::Tracer._lock" in res.locks, sorted(res.locks)
    assert "engine/push.py::BufferPool._lock" in res.locks
    assert res.findings == [], [str(f.__dict__) for f in res.findings[:8]]
    assert not any(len(c) > 1 for c in res.cycles), res.cycles
    model = static_lock_model(res)
    assert model["locks"] and not model["cyclic"], model
    assert all(":" in site for site in model["locks"])
    print("lockset utest ok")
