"""Static analysis for the framework itself (DESIGN §18).

Three PRs of concurrency-heavy growth (pipelined shuffle, batched claim
leases, framed binary segments) left the correctness story resting on
stochastic churn tests — SIGKILL loops that catch races only when the
scheduler cooperates.  This subsystem adds *checked invariants*:

- :mod:`lint` — a framework-aware AST lint pass with a registry of
  rules encoding the conventions the engine's correctness depends on
  (builder lifecycle, flock discipline, swallow-except hygiene, the
  raw-bytes store contract, JAX tracing purity).  Each rule carries an
  id, a severity, and fixture tests; suppressions are explicit (inline
  ``# lmr: disable=LMR00x`` or the checked-in baseline file).

- :mod:`threads` + :mod:`lockset` — whole-package concurrency analysis
  on the same call graph (DESIGN §30): the thread-spawn graph says
  which functions run off the main thread, the interprocedural lockset
  pass propagates may/must-held locks through every call edge, and the
  lock-order graph's SCCs surface static deadlocks (LMR026-030).  The
  runtime lock-order sanitizer (:mod:`..utils.lockcheck`,
  ``LMR_LOCKCHECK=1``) cross-validates: every acquisition order
  observed while the chaos suite runs must already be an edge of
  :func:`lockset.static_lock_model`.

- :mod:`protocol` — a small-scope model checker for the JobStore lease
  lifecycle (claim_batch → heartbeat → commit/release, scavenger
  requeue, worker death at any step): a deterministic virtual-clock
  scheduler exhaustively enumerates the interleavings of a few workers
  over a few jobs, asserts the safety invariants (no double commit, no
  lost job, no job stuck FINISHED+unclaimed, repetitions monotone), and
  on violation yields a replayable trace that the same harness can run
  against the *real* MemJobStore / FileJobStore to confirm.

CLI: ``python -m lua_mapreduce_tpu.analysis`` (see ``--help``).
"""

from lua_mapreduce_tpu.analysis.callgraph import CallGraph, build_callgraph
from lua_mapreduce_tpu.analysis.contracts import TaskReport, check_task
from lua_mapreduce_tpu.analysis.dataflow import run_deep
from lua_mapreduce_tpu.analysis.lint import (AuditReport, Finding, all_rules,
                                             format_text, run_audit,
                                             run_lint)
from lua_mapreduce_tpu.analysis.lockset import (ConcResult, analyze_conc,
                                                run_conc, static_lock_model)
from lua_mapreduce_tpu.analysis.protocol import (LeaseModel, ModelConfig,
                                                 check_protocol, replay_trace)
from lua_mapreduce_tpu.analysis.threads import ThreadGraph, build_thread_graph

__all__ = [
    "Finding", "run_lint", "run_audit", "AuditReport", "all_rules",
    "format_text",
    "CallGraph", "build_callgraph", "run_deep",
    "ThreadGraph", "build_thread_graph",
    "ConcResult", "analyze_conc", "run_conc", "static_lock_model",
    "TaskReport", "check_task",
    "ModelConfig", "LeaseModel", "check_protocol", "replay_trace",
    "utest",
]


def utest() -> None:
    """Self-test: the lint engine finds a seeded fixture violation and
    the repo's own package is lint-clean; the call graph resolves every
    edge kind; each interprocedural rule re-finds its seeded
    helper-indirection race and the package is deep-clean with no stale
    suppressions; the contract checker classifies its fixtures; the
    thread-spawn graph and lockset pass re-find their seeded races and
    the package is conc-clean; the protocol model passes a tiny
    exhaustive run and re-finds a seeded race."""
    import os

    from lua_mapreduce_tpu.analysis import (callgraph, contracts, dataflow,
                                            lint, lockset, protocol, sarif,
                                            threads)

    lint.utest()
    callgraph.utest()
    dataflow.utest()
    contracts.utest()
    sarif.utest()
    threads.utest()
    lockset.utest()
    protocol.utest()

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    audit = run_audit([pkg])
    assert audit.findings == [], (
        "package must ship lint+deep clean:\n"
        + format_text(audit.findings))
    assert not audit.stale, (
        "suppressions must not outlive the code they excused: "
        f"{audit.stale_pragmas} {audit.stale_baseline}")
