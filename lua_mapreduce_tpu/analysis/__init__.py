"""Static analysis for the framework itself (DESIGN §18).

Three PRs of concurrency-heavy growth (pipelined shuffle, batched claim
leases, framed binary segments) left the correctness story resting on
stochastic churn tests — SIGKILL loops that catch races only when the
scheduler cooperates.  This subsystem adds *checked invariants*:

- :mod:`lint` — a framework-aware AST lint pass with a registry of
  rules encoding the conventions the engine's correctness depends on
  (builder lifecycle, flock discipline, swallow-except hygiene, the
  raw-bytes store contract, JAX tracing purity).  Each rule carries an
  id, a severity, and fixture tests; suppressions are explicit (inline
  ``# lmr: disable=LMR00x`` or the checked-in baseline file).

- :mod:`protocol` — a small-scope model checker for the JobStore lease
  lifecycle (claim_batch → heartbeat → commit/release, scavenger
  requeue, worker death at any step): a deterministic virtual-clock
  scheduler exhaustively enumerates the interleavings of a few workers
  over a few jobs, asserts the safety invariants (no double commit, no
  lost job, no job stuck FINISHED+unclaimed, repetitions monotone), and
  on violation yields a replayable trace that the same harness can run
  against the *real* MemJobStore / FileJobStore to confirm.

CLI: ``python -m lua_mapreduce_tpu.analysis`` (see ``--help``).
"""

from lua_mapreduce_tpu.analysis.lint import (Finding, all_rules, format_text,
                                             run_lint)
from lua_mapreduce_tpu.analysis.protocol import (LeaseModel, ModelConfig,
                                                 check_protocol, replay_trace)

__all__ = [
    "Finding", "run_lint", "all_rules", "format_text",
    "ModelConfig", "LeaseModel", "check_protocol", "replay_trace",
    "utest",
]


def utest() -> None:
    """Self-test: the lint engine finds a seeded fixture violation and
    the repo's own package is lint-clean; the protocol model passes a
    tiny exhaustive run and re-finds a seeded race."""
    import os

    from lua_mapreduce_tpu.analysis import lint, protocol

    lint.utest()
    protocol.utest()

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run_lint([pkg])
    assert findings == [], (
        "package must ship lint-clean:\n" + format_text(findings))
