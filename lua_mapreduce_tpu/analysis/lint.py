"""Framework-aware AST lint engine.

Generic linters cannot see this framework's contracts: that a
``store.builder()`` left unbuilt leaks a writer thread and a tempfile on
a long-lived elastic worker, that wall-clock reads under a coordination
lock skew lease math, or that a ``shard_map``-traced function with a
numpy RNG silently computes per-trace garbage.  Each rule here encodes
one such contract as an AST check; the registry keeps rules declarative
(id, severity, rationale, path scope) so the catalog in DESIGN §18 is
generated from the same objects the engine runs.

Suppression is explicit and auditable:

- inline: a ``# lmr: disable=LMR001`` (comma-separated ids) comment on
  the offending line;
- baseline: entries in ``analysis/baseline.json`` — the checked-in
  suppression file the CI gate reads.  The repo ships with an EMPTY
  baseline; anything added must carry a reason.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DISABLE_RE = re.compile(r"#\s*lmr:\s*disable=([A-Z0-9, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule: str
    severity: str            # "error" | "warning"
    path: str                # package-relative posix path
    line: int
    col: int
    message: str

    def key(self) -> tuple:
        return (self.path, self.line, self.rule)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class: subclasses set the metadata and implement check().

    ``paths`` scopes the rule to package-relative prefixes (empty =
    every file).  Registration is by subclassing — the registry is the
    set of Rule subclasses, instantiated fresh per run.
    """

    id: str = ""
    severity: str = "error"
    title: str = ""
    rationale: str = ""
    paths: Sequence[str] = ()

    def applies(self, rel: str) -> bool:
        return not self.paths or any(rel.startswith(p) for p in self.paths)

    def check(self, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, self.severity, ctx.rel,
                       getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


class FileContext:
    """One parsed source file handed to every applicable rule."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)

    def line_disables(self, lineno: int) -> set:
        """Rule ids suppressed inline on ``lineno``."""
        return _line_disables_in(self.lines, lineno)


def all_rules() -> List[Rule]:
    """Fresh instances of every registered rule, id order."""
    from lua_mapreduce_tpu.analysis import rules as _rules  # registers

    def leaves(cls):
        subs = cls.__subclasses__()
        if not subs:
            yield cls
        for s in subs:
            yield from leaves(s)

    del _rules
    out = [cls() for cls in set(leaves(Rule)) if cls.id]
    out.sort(key=lambda r: r.id)
    return out


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def _iter_rel_files(paths: Sequence[str]):
    """(abs path, rel) pairs, anchoring each input directory the way
    the call graph does: package files keep the package-relative path,
    files under an explicit directory root are relative to it (so
    fixture trees carry their ``coord/``-style scope prefixes), bare
    files fall back to their basename."""
    for p in paths:
        root = p if os.path.isdir(p) else (os.path.dirname(p) or ".")
        for f in _iter_py_files([p]):
            ap = os.path.abspath(f)
            if ap.startswith(_PKG_ROOT + os.sep):
                rel = os.path.relpath(ap, _PKG_ROOT)
            else:
                rel = os.path.relpath(ap, os.path.abspath(root))
            yield f, rel.replace(os.sep, "/")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    """The checked-in suppression entries: [{rule, path, line?, reason}].
    ``line`` is optional (a file-wide suppression for one rule); every
    entry must carry a non-empty ``reason`` — the audit trail."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.json")
    try:
        with open(path) as f:
            entries = json.load(f)
    except FileNotFoundError:
        return []
    for e in entries:
        if not e.get("reason"):
            raise ValueError(
                f"baseline entry {e!r} has no reason — suppressions must "
                "be justified")
    return entries


def _baseline_match(entry: dict, f: Finding) -> bool:
    if entry.get("rule") != f.rule or entry.get("path") != f.path:
        return False
    return "line" not in entry or int(entry["line"]) == f.line


def _collect_raw(paths: Sequence[str], rules: Sequence[Rule]):
    """Pre-suppression findings + the pragma inventory + per-file line
    maps: the shared substrate of run_lint and the stale-suppression
    audit."""
    raw: List[Finding] = []
    pragmas: List[dict] = []               # {path, line, rule}
    lines_by_rel: Dict[str, List[str]] = {}
    sources: List[tuple] = []              # (rel, source) — graph input
    for path, rel in _iter_rel_files(paths):
        # a file the gate cannot read or parse cannot be verified — that
        # is itself a finding (LMR000), never a crash of the gate
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (UnicodeDecodeError, OSError) as e:
            raw.append(Finding("LMR000", "error", rel, 0, 0,
                               f"file is not readable utf-8: {e}"))
            continue
        try:
            ctx = FileContext(path, rel, source)
        except SyntaxError as e:
            raw.append(Finding("LMR000", "error", rel,
                               e.lineno or 0, e.offset or 0,
                               f"file does not parse: {e.msg}"))
            continue
        except ValueError as e:     # ast.parse on NUL bytes
            raw.append(Finding("LMR000", "error", rel, 0, 0,
                               f"file does not parse: {e}"))
            continue
        lines_by_rel[ctx.rel] = ctx.lines
        sources.append((ctx.rel, source, ctx.tree))
        # the pragma INVENTORY comes from real comment tokens only —
        # a ``# lmr: disable=`` mention inside a docstring or a test
        # fixture string is documentation, not a suppression
        pragmas.extend(_comment_pragmas(ctx.rel, source))
        for rule in rules:
            if not rule.applies(ctx.rel):
                continue
            raw.extend(rule.check(ctx))
    return raw, pragmas, lines_by_rel, sources


def _comment_pragmas(rel: str, source: str) -> List[dict]:
    import io
    import tokenize
    out: List[dict] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _DISABLE_RE.search(tok.string)
            if not m:
                continue
            for rid in (s.strip() for s in m.group(1).split(",")):
                if rid:
                    out.append({"path": rel, "line": tok.start[0],
                                "rule": rid})
    except (tokenize.TokenError, IndentationError):
        pass          # unparseable tails already surfaced as LMR000
    return out


def _line_disables_in(lines: Sequence[str], lineno: int) -> set:
    if not (1 <= lineno <= len(lines)):
        return set()
    m = _DISABLE_RE.search(lines[lineno - 1])
    if not m:
        return set()
    return {s.strip() for s in m.group(1).split(",") if s.strip()}


def run_lint(paths: Optional[Sequence[str]] = None,
             baseline: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint ``paths`` (default: the whole package) and return the
    findings that survive inline + baseline suppression, sorted by
    (path, line, rule)."""
    if paths is None:
        paths = [_PKG_ROOT]
    if rules is None:
        rules = all_rules()
    base = load_baseline(baseline)
    raw, _pragmas, lines_by_rel, _sources = _collect_raw(paths, rules)
    out: List[Finding] = []
    for finding in raw:
        if finding.rule in _line_disables_in(
                lines_by_rel.get(finding.path, ()), finding.line):
            continue
        if any(_baseline_match(e, finding) for e in base):
            continue
        out.append(finding)
    out.sort(key=Finding.key)
    return out


@dataclasses.dataclass
class AuditReport:
    """run_audit's result: surviving findings from BOTH passes plus the
    suppressions that excused nothing — a pragma or baseline entry that
    no longer fires has outlived the code it excused and must go."""
    findings: List[Finding]
    stale_pragmas: List[dict]       # {path, line, rule}
    stale_baseline: List[dict]      # the unmatched baseline entries

    @property
    def stale(self) -> bool:
        return bool(self.stale_pragmas or self.stale_baseline)


def run_audit(paths: Optional[Sequence[str]] = None,
              baseline: Optional[str] = None,
              deep: bool = True) -> AuditReport:
    """Lint + (optionally) the interprocedural deep pass, with the
    stale-suppression audit: every inline ``# lmr: disable=`` pragma and
    every baseline entry must still suppress at least one raw finding."""
    if paths is None:
        paths = [_PKG_ROOT]
    rules = all_rules()
    base = load_baseline(baseline)
    raw, pragmas, lines_by_rel, sources = _collect_raw(paths, rules)
    if deep:
        # lazy imports: dataflow imports this module. The deep pass
        # reuses the sources just read — one file walk, one parse set
        from lua_mapreduce_tpu.analysis import dataflow, lockset
        from lua_mapreduce_tpu.analysis.callgraph import CallGraph
        graph = CallGraph.from_sources(sources)
        raw = raw + dataflow.analyze(baseline=baseline, graph=graph).raw
        raw = raw + lockset.analyze_conc(baseline=baseline, graph=graph).raw
    used_pragmas = set()
    used_baseline = set()
    out: List[Finding] = []
    for f in raw:
        dis = _line_disables_in(lines_by_rel.get(f.path, ()), f.line)
        if f.rule in dis:
            used_pragmas.add((f.path, f.line, f.rule))
            continue
        matched = [i for i, e in enumerate(base) if _baseline_match(e, f)]
        if matched:
            used_baseline.update(matched)
            continue
        out.append(f)
    out.sort(key=Finding.key)
    stale_pragmas = [p for p in pragmas
                     if (p["path"], p["line"], p["rule"])
                     not in used_pragmas]
    stale_baseline = [e for i, e in enumerate(base)
                      if i not in used_baseline]
    return AuditReport(out, stale_pragmas, stale_baseline)


def format_text(findings: Sequence[Finding]) -> str:
    return "\n".join(f"{f.path}:{f.line}:{f.col}: {f.rule} "
                     f"[{f.severity}] {f.message}" for f in findings)


def report_dict(findings: Sequence[Finding]) -> dict:
    """The one report shape every consumer uses (CLI JSON included)."""
    return {"findings": [f.to_json() for f in findings],
            "count": len(findings)}


def format_json(findings: Sequence[Finding]) -> str:
    return json.dumps(report_dict(findings), indent=2)


def rule_catalog() -> List[Dict[str, str]]:
    """Every rule id the analyzer can emit: the per-function registry,
    the interprocedural (deep) rules, the task-contract rules, and the
    concurrency (conc) band — one catalog, id order (DESIGN §25)."""
    from lua_mapreduce_tpu.analysis import contracts, dataflow, lockset
    out = [{"id": r.id, "severity": r.severity, "title": r.title,
            "rationale": r.rationale,
            "paths": list(r.paths) or ["<all>"]} for r in all_rules()]
    out.extend(dataflow.deep_rule_catalog())
    out.extend(contracts.contract_rule_catalog())
    out.extend(lockset.conc_rule_catalog())
    out.sort(key=lambda r: r["id"])
    return out


def utest() -> None:
    """Self-test: engine plumbing — suppression, baselines, ordering —
    against an in-memory fixture (rule behavior itself is fixture-tested
    per rule in tests/test_analysis.py)."""
    import tempfile

    src = ("import time\n"
           "try:\n"
           "    pass\n"
           "except BaseException:\n"
           "    pass\n"
           "try:\n"
           "    pass\n"
           "except BaseException:  # lmr: disable=LMR005\n"
           "    pass\n")
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "fx.py")
        with open(p, "w") as f:
            f.write(src)
        got = run_lint([p], baseline="/nonexistent")
        assert [f.rule for f in got] == ["LMR005"], got
        assert got[0].line == 4
        # file-wide baseline entry silences it; empty reason is rejected
        bl = os.path.join(d, "b.json")
        with open(bl, "w") as f:
            json.dump([{"rule": "LMR005", "path": "fx.py",
                        "reason": "utest"}], f)
        assert run_lint([p], baseline=bl) == []
        with open(bl, "w") as f:
            json.dump([{"rule": "LMR005", "path": "fx.py"}], f)
        try:
            run_lint([p], baseline=bl)
        except ValueError:
            pass
        else:
            raise AssertionError("reason-less baseline entry must fail")
    ids = [r.id for r in all_rules()]
    assert len(ids) == len(set(ids)) and ids == sorted(ids)
