"""Static task-contract checker + lowerability oracle (DESIGN §25).

``python -m lua_mapreduce_tpu.analysis task <module>`` validates a USER
task module before a fleet ever runs it — statically, from the AST, no
import executed (a task module with a side-effecting import must not
fire during validation).

Three layers, in increasing strictness:

1. **Contract** (LMR020-022): the six-function surface TaskSpec
   enforces at configure time (engine/contract.py), checked without
   importing — required functions present, plugin arities right
   (``taskfn(emit)``, ``mapfn(key, value, emit)``, ``partitionfn(key)``,
   ``reducefn(key, values)``), and every ``emit(...)`` call inside
   taskfn/mapfn passing exactly the (key, value) pair the engine
   serializes.

2. **Determinism** (LMR023-025): the engine *assumes* replayable user
   code — speculation's first-commit-wins races two executions of the
   same mapfn and keeps either result; chaos byte-identity re-runs
   whole phases; replica loss re-executes producers.  Wall-clock
   reads, unseeded RNG draws, salted ``hash()`` in a partitionfn (a
   per-PROCESS salt: two workers disagree on every key's partition),
   and unordered iteration (sets, unsorted ``os.listdir``/``glob``)
   all break that assumption silently.

3. **Lowerability** — the three-way verdict the in-graph engine
   (``engine/ingraph.py``, DESIGN §26) consumes at task-load time for
   its ``engine="auto"`` selection, per function:

   - ``in-graph``     — a pure array/numeric program (arithmetic,
     subscripts, numeric builtins, jnp/np/math calls, eligible local
     helpers, ``emit`` of computed values): liftable to the compiled
     jit/shard_map plane (map = vmapped shard compute, partition =
     device-axis sharding, reduce = psum/segment-sum — DrJAX).
   - ``store-plane``  — valid, deterministic, but host-bound (file IO,
     string processing, arbitrary library calls): runs on the
     distributed store plane only.
   - ``invalid``      — violates the contract; no plane will run it.

   The TASK verdict folds the data-plane functions only (mapfn,
   partitionfn, reducefn, combinerfn): taskfn/finalfn are control-plane
   by construction (they enumerate jobs / collect results host-side)
   and never block in-graph execution.

Module forms accepted (the same forms TaskSpec loads): a single module
defining several functions (examples/extsort/sorttask.py), or a package
directory with one module per function (examples/wordcount/mapfn.py...).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis.lint import Finding
from lua_mapreduce_tpu.analysis.rules import _chain
# the one source of truth for the plugin surface: the engine's own
# contract module (the no-import rule covers analyzed TARGET modules,
# not the analyzer's package) — a slot added there is checked here
from lua_mapreduce_tpu.engine.contract import _REQUIRED, FN_NAMES

# expected positional arity per plugin (engine/contract.py's surface)
_ARITY = {"taskfn": 1, "mapfn": 3, "partitionfn": 1, "reducefn": 2,
          "combinerfn": 2, "finalfn": 1}

# which functions must be deterministic (re-executed by speculation /
# chaos / replica recovery) — taskfn too: job enumeration re-runs on
# server restart; finalfn runs once on the server, exempt
_DETERMINISTIC_FNS = ("taskfn", "mapfn", "partitionfn", "reducefn",
                      "combinerfn")

VERDICT_INGRAPH = "in-graph"
VERDICT_STORE = "store-plane"
VERDICT_INVALID = "invalid"

_NUMERIC_BUILTINS = {"int", "float", "bool", "abs", "min", "max", "len",
                     "sum", "round", "pow", "divmod", "range",
                     "enumerate"}
_ARRAY_ROOTS = {"jnp", "np", "numpy", "math", "jax"}

_CLOCK_ROOTS = {("time",), ("datetime",)}
_RNG_DRAWS = {"random", "randint", "randrange", "choice", "choices",
              "shuffle", "sample", "uniform", "gauss", "getrandbits",
              "normal", "randn", "rand", "permutation"}


@dataclasses.dataclass(frozen=True)
class ContractRule:
    id: str
    severity: str
    title: str
    rationale: str


CONTRACT_RULES: Tuple[ContractRule, ...] = (
    ContractRule(
        "LMR020", "error", "required plugin function missing",
        "TaskSpec requires callable taskfn/mapfn/partitionfn/reducefn "
        "(engine/contract.py, reference server.lua:429-445); a missing "
        "one fails at configure time on the SERVER — this catches it "
        "before any fleet is provisioned."),
    ContractRule(
        "LMR021", "error", "plugin signature arity mismatch",
        "The engine calls taskfn(emit), mapfn(key, value, emit), "
        "partitionfn(key), reducefn(key, values), combinerfn(key, "
        "values), finalfn(pairs) positionally; a wrong arity raises "
        "TypeError inside a claimed job body, charging repetitions "
        "until the job marches to FAILED."),
    ContractRule(
        "LMR022", "error", "emit() must pass exactly (key, value)",
        "The emit callback serializes one (key, value) pair per call; "
        "any other arity raises inside the job body at runtime — and "
        "under speculation the clone fails identically, so the job "
        "burns its whole repetition budget."),
    ContractRule(
        "LMR023", "error", "determinism hazard: wall-clock / unseeded RNG",
        "Speculation's first-commit-wins keeps EITHER of two racing "
        "executions, chaos legs byte-compare re-runs, and replica "
        "recovery re-executes producers: user functions must be "
        "deterministic. time.time()/datetime.now()/unseeded RNG/"
        "os.urandom/uuid4 make two executions of the same job "
        "diverge silently."),
    ContractRule(
        "LMR024", "error", "determinism hazard: unordered iteration",
        "Iterating a set (per-process hash salt) or an unsorted "
        "os.listdir()/glob.glob() emits records in a "
        "process-dependent order — two executions of the same job "
        "publish different bytes, breaking replay/speculation "
        "byte-identity. Sort before iterating."),
    ContractRule(
        "LMR025", "error", "partition math must not use builtin hash()",
        "str hashing is salted PER PROCESS (PYTHONHASHSEED): two "
        "workers disagree on every key's partition, scattering one "
        "key's values across reducers. Use a stable hash (zlib.crc32, "
        "FNV, blake2b) — benchmarks/coord_task.py documents exactly "
        "this trap."),
)


@dataclasses.dataclass
class FunctionReport:
    name: str                  # plugin slot: "mapfn", ...
    rel: str                   # file the def lives in
    lineno: int
    verdict: str
    findings: List[Finding]
    reasons: List[str]         # why not in-graph (empty when eligible)


@dataclasses.dataclass
class TaskReport:
    spec: str
    verdict: str
    functions: Dict[str, FunctionReport]
    findings: List[Finding]    # module-level findings + per-function


# -- module resolution (static: never imports) -------------------------------

class _TaskSources:
    """The parsed source set of one task module spec: {fname: (rel,
    tree, def-node or None)} plus per-file module context for helper
    resolution."""

    def __init__(self):
        self.files: Dict[str, Tuple[str, ast.Module]] = {}  # rel->(src,tree)
        self.slots: Dict[str, Tuple[str, Optional[ast.AST]]] = {}

    def add_file(self, rel: str, source: str) -> Optional[ast.Module]:
        try:
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, ValueError):
            return None
        self.files[rel] = (source, tree)
        return tree


def _module_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """Module-level name -> def node (or alias target's def): handles
    ``def reducefn(...)`` and ``combinerfn = reducefn``."""
    defs: Dict[str, ast.AST] = {}
    aliases: Dict[str, str] = {}
    for n in tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[n.name] = n
        elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Name):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    aliases[t.id] = n.value.id
    for alias, target in aliases.items():
        if target in defs and alias not in defs:
            defs[alias] = defs[target]
    return defs


def resolve_spec(spec: str) -> Optional[str]:
    """A module spec to a filesystem path: an existing file/dir wins;
    otherwise the dotted name is searched across cwd + sys.path."""
    if os.path.exists(spec):
        return spec
    parts = spec.split(".")
    for root in [os.getcwd()] + sys.path:
        if not root or not os.path.isdir(root):
            continue
        base = os.path.join(root, *parts)
        if os.path.isfile(base + ".py"):
            return base + ".py"
        if os.path.isdir(base):
            return base
    return None


def _load_sources(spec: str) -> Tuple[Optional[_TaskSources], Optional[str]]:
    path = resolve_spec(spec)
    if path is None:
        return None, f"module {spec!r} not found (as a path or on sys.path)"
    src = _TaskSources()
    if os.path.isfile(path):
        rel = os.path.basename(path)
        try:
            with open(path, encoding="utf-8") as f:
                tree = src.add_file(rel, f.read())
        except OSError as e:
            return None, f"cannot read {path}: {e}"
        if tree is None:
            return None, f"{path} does not parse"
        defs = _module_defs(tree)
        for fname in FN_NAMES:
            if fname in defs:
                src.slots[fname] = (rel, defs[fname])
        return src, None
    # package directory: __init__.py first, then one-module-per-function
    init = os.path.join(path, "__init__.py")
    if os.path.isfile(init):
        with open(init, encoding="utf-8") as f:
            tree = src.add_file("__init__.py", f.read())
        if tree is not None:
            defs = _module_defs(tree)
            for fname in FN_NAMES:
                if fname in defs:
                    src.slots[fname] = ("__init__.py", defs[fname])
    for fname in FN_NAMES:
        if fname in src.slots:
            continue
        sub = os.path.join(path, fname + ".py")
        if not os.path.isfile(sub):
            continue
        with open(sub, encoding="utf-8") as f:
            tree = src.add_file(fname + ".py", f.read())
        if tree is None:
            continue
        defs = _module_defs(tree)
        if fname in defs:
            src.slots[fname] = (fname + ".py", defs[fname])
    return src, None


# -- per-function checks -----------------------------------------------------

def _positional_arity(fn: ast.AST) -> Tuple[int, Optional[int]]:
    """(min, max) positional arity; max None = *args."""
    a = fn.args
    pos = a.posonlyargs + a.args
    n_default = len(a.defaults)
    lo = len(pos) - n_default
    hi = None if a.vararg else len(pos)
    return lo, hi


def _check_signature(fname: str, rel: str, fn: ast.AST) -> List[Finding]:
    want = _ARITY[fname]
    lo, hi = _positional_arity(fn)
    if lo <= want and (hi is None or want <= hi):
        return []
    sig = f"{lo}" if hi == lo else f"{lo}..{hi if hi is not None else '*'}"
    return [Finding("LMR021", "error", rel, fn.lineno, fn.col_offset,
                    f"{fname} takes {sig} positional arg(s); the engine "
                    f"calls it with {want}")]


def _emit_param(fname: str, fn: ast.AST) -> Optional[str]:
    a = fn.args
    pos = [x.arg for x in a.posonlyargs + a.args]
    idx = {"taskfn": 0, "mapfn": 2}.get(fname)
    if idx is None or idx >= len(pos):
        return None
    return pos[idx]


def _check_emit(fname: str, rel: str, fn: ast.AST) -> List[Finding]:
    emit = _emit_param(fname, fn)
    if emit is None:
        return []
    out = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == emit:
            if any(isinstance(a, ast.Starred) for a in n.args):
                continue                     # unknowable statically
            if len(n.args) != 2 or n.keywords:
                out.append(Finding(
                    "LMR022", "error", rel, n.lineno, n.col_offset,
                    f"{fname} calls {emit}() with {len(n.args)} arg(s) "
                    "— the engine serializes exactly (key, value)"))
    return out


def _local_helpers(tree: ast.Module) -> Dict[str, ast.AST]:
    return {n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _reachable_helpers(fn: ast.AST, helpers: Dict[str, ast.AST]) \
        -> List[Tuple[str, ast.AST]]:
    """Module-local functions transitively called from ``fn`` — the
    closure the determinism/lowerability checks walk."""
    seen: Set[str] = set()
    order: List[Tuple[str, ast.AST]] = []
    frontier = [fn]
    while frontier:
        cur = frontier.pop()
        for n in ast.walk(cur):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                name = n.func.id
                if name in helpers and name not in seen:
                    seen.add(name)
                    order.append((name, helpers[name]))
                    frontier.append(helpers[name])
    return order


def _determinism_findings(fname: str, rel: str, fn: ast.AST,
                          tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    helpers = _local_helpers(tree)
    scopes = [(fname, fn)] + _reachable_helpers(fn, helpers)
    for sname, node in scopes:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                c = _chain(n.func)
                if not c:
                    continue
                where = (f"{fname}()" if sname == fname
                         else f"{sname}() (called from {fname})")
                if c[0] == "time" and len(c) == 2:
                    out.append(Finding(
                        "LMR023", "error", rel, n.lineno, n.col_offset,
                        f"{'.'.join(c)}() in {where} — two executions "
                        "of the same job diverge"))
                elif c[:2] in (("datetime", "now"),) or \
                        (len(c) == 3 and c[0] == "datetime"
                         and c[2] in ("now", "today", "utcnow")):
                    out.append(Finding(
                        "LMR023", "error", rel, n.lineno, n.col_offset,
                        f"{'.'.join(c)}() in {where} — wall-clock read"))
                elif (c[0] in ("random",) and len(c) == 2
                      and c[1] in _RNG_DRAWS) or \
                        (len(c) == 3 and c[0] in ("np", "numpy")
                         and c[1] == "random" and c[2] in _RNG_DRAWS):
                    out.append(Finding(
                        "LMR023", "error", rel, n.lineno, n.col_offset,
                        f"{'.'.join(c)}() in {where} — unseeded RNG "
                        "draw (seed an explicit Random(seed)/key "
                        "derived from the job key)"))
                elif c == ("os", "urandom") or c == ("uuid", "uuid4"):
                    out.append(Finding(
                        "LMR023", "error", rel, n.lineno, n.col_offset,
                        f"{'.'.join(c)}() in {where} — entropy source"))
                elif c[-1] in ("listdir", "glob", "iglob", "scandir"):
                    if not _sorted_wrapped(n, node):
                        out.append(Finding(
                            "LMR024", "error", rel, n.lineno,
                            n.col_offset,
                            f"{'.'.join(c)}() in {where} without "
                            "sorted() — directory order is "
                            "filesystem-dependent"))
                elif c == ("hash",) and fname == "partitionfn":
                    out.append(Finding(
                        "LMR025", "error", rel, n.lineno, n.col_offset,
                        f"builtin hash() in {where} — salted per "
                        "process; workers will disagree on partitions"))
            elif isinstance(n, (ast.For, ast.comprehension)):
                it = n.iter
                if isinstance(it, ast.Set) or (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id in ("set", "frozenset")):
                    where = (f"{fname}()" if sname == fname
                             else f"{sname}() (called from {fname})")
                    out.append(Finding(
                        "LMR024", "error", rel, it.lineno, it.col_offset,
                        f"iteration over a set in {where} — per-process "
                        "hash salt reorders it; sort first"))
    return out


def _sorted_wrapped(call: ast.Call, scope: ast.AST) -> bool:
    """Is this listdir/glob call the direct argument of sorted()?"""
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "sorted" and call in n.args:
            return True
    return False


# -- lowerability ------------------------------------------------------------

def _ineligible_reasons(fname: str, fn: ast.AST, tree: ast.Module,
                        _memo: Optional[Dict[str, List[str]]] = None,
                        _stack: Optional[Set[str]] = None) -> List[str]:
    """Why this function is NOT liftable to the compiled plane (empty =
    in-graph eligible). Conservative whitelist walk: anything outside
    the pure-numeric surface disqualifies with a named reason."""
    helpers = _local_helpers(tree)
    emit = _emit_param(fname, fn)
    a = fn.args
    params = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
    reasons: List[str] = []
    _memo = _memo if _memo is not None else {}
    _stack = _stack if _stack is not None else set()

    def deny(node, why):
        if len(reasons) < 4:
            reasons.append(f"{why} (line {getattr(node, 'lineno', '?')})")

    for n in ast.walk(fn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            deny(n, "with-statement (resource IO)")
        elif isinstance(n, (ast.Try, ast.Raise)):
            deny(n, "exception control flow")
        elif isinstance(n, (ast.Global, ast.Nonlocal)):
            deny(n, "writes module state")
        elif isinstance(n, ast.While):
            deny(n, "data-dependent while-loop")
        elif isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await)):
            deny(n, "generator/async")
        elif isinstance(n, ast.JoinedStr):
            deny(n, "string interpolation")
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            deny(n, "local import")
        elif isinstance(n, (ast.For, ast.comprehension)):
            it = n.iter
            ok = (isinstance(it, ast.Call)
                  and isinstance(it.func, ast.Name)
                  and it.func.id in ("range", "enumerate")) \
                or (isinstance(it, ast.Name) and it.id in params)
            if not ok:
                deny(it, "loop over a non-range, non-argument iterable")
        elif isinstance(n, ast.Call):
            c = _chain(n.func)
            if c is None:
                deny(n, "indirect call")
                continue
            if len(c) == 1:
                name = c[0]
                if name == emit or name in _NUMERIC_BUILTINS:
                    continue
                if name in helpers:
                    if name in _stack:
                        deny(n, f"recursive helper {name}()")
                        continue
                    if name not in _memo:
                        _stack.add(name)
                        _memo[name] = _ineligible_reasons(
                            name, helpers[name], tree, _memo, _stack)
                        _stack.discard(name)
                    if _memo[name]:
                        deny(n, f"helper {name}() is not in-graph "
                             f"eligible ({_memo[name][0]})")
                    continue
                if name in params:
                    deny(n, f"call to callback parameter {name!r}")
                    continue
                deny(n, f"call to {name}()")
            else:
                if c[0] in _ARRAY_ROOTS and "random" not in c \
                        and "debug" not in c:
                    continue
                deny(n, f"call to {'.'.join(c)}()")
    return reasons


# -- driver ------------------------------------------------------------------

def check_task(spec: str) -> TaskReport:
    src, err = _load_sources(spec)
    if src is None:
        f = Finding("LMR020", "error", spec, 0, 0, err)
        return TaskReport(spec, VERDICT_INVALID, {}, [f])
    findings: List[Finding] = []
    functions: Dict[str, FunctionReport] = {}
    for fname in FN_NAMES:
        slot = src.slots.get(fname)
        if slot is None:
            if fname in _REQUIRED:
                findings.append(Finding(
                    "LMR020", "error", spec, 0, 0,
                    f"required function {fname!r} not found in {spec} "
                    "(as a module-level def or alias)"))
            continue
        rel, node = slot
        _source, tree = src.files[rel]
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.append(Finding(
                "LMR020", "error", rel, getattr(node, "lineno", 0), 0,
                f"{fname} is not a function definition"))
            functions[fname] = FunctionReport(
                fname, rel, getattr(node, "lineno", 0), VERDICT_INVALID,
                [], ["not a def"])
            continue
        fn_findings = _check_signature(fname, rel, node)
        fn_findings += _check_emit(fname, rel, node)
        invalid = bool(fn_findings)
        if fname in _DETERMINISTIC_FNS:
            fn_findings += _determinism_findings(fname, rel, node, tree)
        reasons = _ineligible_reasons(fname, node, tree)
        hazard = any(f.rule in ("LMR023", "LMR024", "LMR025")
                     for f in fn_findings)
        if invalid:
            verdict = VERDICT_INVALID
        elif not reasons and not hazard:
            verdict = VERDICT_INGRAPH
        else:
            verdict = VERDICT_STORE
            if hazard and not reasons:
                reasons = ["determinism hazard (see findings)"]
        functions[fname] = FunctionReport(fname, rel, node.lineno,
                                          verdict, fn_findings, reasons)
        findings.extend(fn_findings)

    missing = [f for f in _REQUIRED if f not in functions]
    if missing or any(functions[f].verdict == VERDICT_INVALID
                      for f in functions):
        task_verdict = VERDICT_INVALID
    else:
        data_plane = [f for f in ("mapfn", "partitionfn", "reducefn",
                                  "combinerfn") if f in functions]
        task_verdict = (VERDICT_INGRAPH
                        if all(functions[f].verdict == VERDICT_INGRAPH
                               for f in data_plane)
                        else VERDICT_STORE)
    findings.sort(key=Finding.key)
    return TaskReport(spec, task_verdict, functions, findings)


# the hybrid plane's leg membership (DESIGN §28): which functions must
# verdict in-graph for each stage of a store-plane task to compile.
# Mirrors engine/ingraph.py:hybrid_stage_legs — partitionfn is absent on
# purpose (it routes host-side on concrete keys in the shared publish
# tail), and combinerfn only gates the map leg when the task has one.
STAGE_FNS = {"map": ("mapfn", "combinerfn"), "reduce": ("reducefn",)}


def stage_report(rep: TaskReport) -> dict:
    """Per-stage lowering verdicts for the hybrid plane: for each leg,
    whether it compiles, each member function's verdict, and the rule
    ids + oracle reasons blocking it when it does not."""
    out = {}
    for stage, fns in STAGE_FNS.items():
        present = [f for f in fns if f in rep.functions]
        required_ok = fns[0] in rep.functions
        frs = [rep.functions[f] for f in present]
        compiled = required_ok and all(
            fr.verdict == VERDICT_INGRAPH for fr in frs)
        out[stage] = {
            "compiled": compiled,
            "functions": {f: rep.functions[f].verdict for f in present},
            "blocking": sorted({fi.rule for fr in frs
                                if fr.verdict != VERDICT_INGRAPH
                                for fi in fr.findings}),
            "reasons": [r for fr in frs
                        if fr.verdict != VERDICT_INGRAPH
                        for r in fr.reasons],
        }
    return out


def report_dict(rep: TaskReport) -> dict:
    return {
        "spec": rep.spec,
        "verdict": rep.verdict,
        "functions": {
            name: {"file": fr.rel, "line": fr.lineno,
                   "verdict": fr.verdict, "reasons": fr.reasons,
                   "findings": [f.to_json() for f in fr.findings]}
            for name, fr in rep.functions.items()},
        "stages": stage_report(rep),
        "findings": [f.to_json() for f in rep.findings],
        "count": len(rep.findings),
    }


def format_text(rep: TaskReport) -> str:
    lines = [f"task {rep.spec}: {rep.verdict}"]
    for name in FN_NAMES:
        fr = rep.functions.get(name)
        if fr is None:
            continue
        why = f"  ({fr.reasons[0]})" if fr.reasons else ""
        lines.append(f"  {name:<12} {fr.rel}:{fr.lineno:<5} "
                     f"{fr.verdict}{why}")
    for f in rep.findings:
        lines.append(f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
    return "\n".join(lines)


def contract_rule_catalog() -> List[Dict[str, str]]:
    return [{"id": r.id, "severity": r.severity, "title": r.title,
             "rationale": r.rationale, "paths": ["<task modules>"]}
            for r in CONTRACT_RULES]


def utest() -> None:
    """Self-test: contract violations, determinism hazards, and the
    three-way verdict on in-memory fixtures plus the shipped examples."""
    import tempfile

    good = (
        "def taskfn(emit):\n"
        "    for j in range(4):\n"
        "        emit(j, j)\n"
        "def mapfn(key, value, emit):\n"
        "    emit(key % 2, value * value)\n"
        "def partitionfn(key):\n"
        "    return key % 2\n"
        "def reducefn(key, values):\n"
        "    return sum(values)\n"
    )
    bad = (
        "import time, random\n"
        "def taskfn(emit, extra):\n"
        "    emit(1)\n"
        "def mapfn(key, value, emit):\n"
        "    emit(key, value, time.time())\n"
        "    random.shuffle(value)\n"
        "def partitionfn(key):\n"
        "    return hash(key) % 4\n"
        "def reducefn(key, values):\n"
        "    for v in set(values):\n"
        "        pass\n"
        "    return values[0]\n"
    )
    with tempfile.TemporaryDirectory() as d:
        g = os.path.join(d, "goodtask.py")
        with open(g, "w") as f:
            f.write(good)
        rep = check_task(g)
        assert rep.verdict == VERDICT_INGRAPH, report_dict(rep)
        assert all(fr.verdict == VERDICT_INGRAPH
                   for fr in rep.functions.values())
        assert rep.findings == []

        b = os.path.join(d, "badtask.py")
        with open(b, "w") as f:
            f.write(bad)
        rep = check_task(b)
        assert rep.verdict == VERDICT_INVALID
        rules = {f.rule for f in rep.findings}
        assert {"LMR021", "LMR022", "LMR023", "LMR024",
                "LMR025"} <= rules, rules

        # a missing required function is LMR020 + invalid
        m = os.path.join(d, "half.py")
        with open(m, "w") as f:
            f.write("def mapfn(key, value, emit):\n    emit(key, value)\n")
        rep = check_task(m)
        assert rep.verdict == VERDICT_INVALID
        assert sum(1 for f in rep.findings if f.rule == "LMR020") == 3

    assert check_task("no.such.module").verdict == VERDICT_INVALID
