"""Whole-program call graph over the package (DESIGN §25).

The per-function lint (analysis/rules.py) deliberately stops at one
frame: a helper *called* under the index flock is not re-checked inside
the locked region.  That limit is exactly what the interprocedural pass
(analysis/dataflow.py) removes — and it needs a call graph to walk.

This module builds one statically, from the AST alone (no imports are
executed — the analyzer must be runnable against a broken tree):

- **nodes** are functions: module-level defs, methods, nested defs
  (``outer.inner``), plus one ``<module>`` pseudo-function per file for
  import-time code;
- **edges** are resolved call sites, each tagged with a *kind* the
  dataflow pass uses to decide what to follow:

  - ``direct``  — ``f()`` resolved to a module function / nested def /
    ``from x import f`` target;
  - ``ctor``    — ``Cls()`` resolved to ``Cls.__init__``;
  - ``method``  — ``self.m()`` / ``Cls.m()`` resolved through the class
    and its (statically resolvable) bases;
  - ``interface`` — ``obj.m()`` where ``m`` belongs to the Store /
    FileBuilder / JobStore abstract surface: resolved to EVERY
    store-like implementation of ``m`` in the graph (the
    may-dispatch-anywhere approximation for the storage plane);
  - ``param``   — a call to one of the enclosing function's own
    parameters (a user callback — unresolvable, but exactly the thing
    the flock rule needs to see).

Deliberate limits (documented, like the per-function pass's): no alias
tracking through local variables (``g = self.load; g()`` is invisible),
lambdas merge into their enclosing function, and dynamically generated
methods (``setattr(cls, op, ...)``) contribute no edges.  The rules
that consume the graph are written so these limits fail *quiet*, never
noisy.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis.lint import _iter_rel_files, _PKG_ROOT
from lua_mapreduce_tpu.analysis.rules import _chain

# the abstract surfaces whose method names dispatch anywhere in the
# storage plane (store/base.py Store + FileBuilder, coord/jobstore.py
# JobStore). Kept as a literal so fixture graphs resolve identically.
_INTERFACE_BASES = {"Store", "FileBuilder", "JobStore"}

# a class "looks store-like" (eligible as an interface implementation)
# when its own name or any base name carries one of these markers
_IMPL_MARKERS = ("Store", "Builder", "Writer")


@dataclasses.dataclass(frozen=True)
class FunctionInfo:
    """One call-graph node."""
    fid: str                 # "rel::Qual" — globally unique
    rel: str                 # package-relative posix path
    qual: str                # "func", "Cls.method", "outer.inner"
    name: str                # bare name
    cls: Optional[str]       # owning class name, if a method
    lineno: int
    params: Tuple[str, ...]  # parameter names (self/cls dropped)
    node: ast.AST = dataclasses.field(compare=False, hash=False,
                                      repr=False)


@dataclasses.dataclass(frozen=True)
class Edge:
    caller: str
    callee: str
    line: int
    kind: str                # direct | ctor | method | interface | param


class _ClassInfo:
    def __init__(self, name: str, bases: List[Tuple[str, ...]]):
        self.name = name
        self.bases = bases                 # dotted chains, unresolved
        self.methods: Dict[str, str] = {}  # method name -> fid


class _ModuleInfo:
    def __init__(self, rel: str, source: str, tree: ast.Module):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # dotted name derived from the path: "store/base.py" -> "store.base"
        dotted = rel[:-3] if rel.endswith(".py") else rel
        dotted = dotted.replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        self.dotted = dotted
        self.imports: Dict[str, str] = {}          # alias -> dotted module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # alias ->(mod,attr)
        self.functions: Dict[str, str] = {}        # module-level name -> fid
        self.classes: Dict[str, _ClassInfo] = {}


class CallGraph:
    """The resolved whole-program graph plus the per-module source maps
    the deep rules need (line lookup for suppression, AST re-walks)."""

    def __init__(self):
        self.modules: Dict[str, _ModuleInfo] = {}      # rel -> module
        self.functions: Dict[str, FunctionInfo] = {}   # fid -> info
        self.edges_from: Dict[str, List[Edge]] = {}
        self._by_dotted: Dict[str, str] = {}           # dotted -> rel
        self._iface_methods: Set[str] = set()
        self._iface_impls: Dict[str, List[str]] = {}   # method -> [fid]
        self.unresolved = 0

    # -- construction -------------------------------------------------------

    @classmethod
    def from_sources(cls, sources: Sequence[tuple]) -> "CallGraph":
        """Build from ``[(rel, source), ...]`` (the fixture entry point)
        or ``[(rel, source, tree), ...]`` — run_audit hands over the
        trees lint already parsed so the combined pass parses once."""
        g = cls()
        for entry in sources:
            rel, src = entry[0], entry[1]
            tree = entry[2] if len(entry) > 2 else None
            if tree is None:
                try:
                    tree = ast.parse(src, filename=rel)
                except (SyntaxError, ValueError):
                    continue      # unparseable files are LMR000's problem
            g.modules[rel] = _ModuleInfo(rel, src, tree)
        g._index()
        g._resolve()
        return g

    def node_count(self) -> int:
        return len(self.functions)

    def edge_count(self) -> int:
        return sum(len(v) for v in self.edges_from.values())

    def callees(self, fid: str) -> List[Edge]:
        return self.edges_from.get(fid, [])

    def interface_methods(self) -> Set[str]:
        return set(self._iface_methods)

    # -- indexing pass ------------------------------------------------------

    def _index(self) -> None:
        for rel, m in sorted(self.modules.items()):
            self._by_dotted[m.dotted] = rel
        for rel, m in sorted(self.modules.items()):
            self._index_module(m)
        # the interface surface: method names declared on the abstract
        # bases, then every store-like implementation of each
        for m in self.modules.values():
            for ci in m.classes.values():
                if ci.name in _INTERFACE_BASES:
                    self._iface_methods.update(
                        n for n in ci.methods if not n.startswith("__"))
        for m in self.modules.values():
            for ci in m.classes.values():
                if not self._storelike(ci):
                    continue
                for name, fid in ci.methods.items():
                    if name in self._iface_methods:
                        self._iface_impls.setdefault(name, []).append(fid)

    @staticmethod
    def _storelike(ci: _ClassInfo) -> bool:
        names = [ci.name] + ["".join(b) for b in ci.bases]
        return any(mark in n for n in names for mark in _IMPL_MARKERS) \
            or any(b[-1] in _INTERFACE_BASES for b in ci.bases)

    def _index_module(self, m: _ModuleInfo) -> None:
        # imports
        for n in ast.walk(m.tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    if a.asname:
                        m.imports[a.asname] = a.name
                    else:
                        # ``import a.b`` binds ``a``; ``a.b.f`` then
                        # resolves through the chain itself
                        top = a.name.split(".")[0]
                        m.imports[top] = top
            elif isinstance(n, ast.ImportFrom):
                base = n.module or ""
                if n.level:      # relative: anchor at this module's package
                    pkg = m.dotted.rsplit(".", n.level)[0] \
                        if m.dotted.count(".") >= n.level else ""
                    base = f"{pkg}.{base}" if base and pkg else (pkg or base)
                for a in n.names:
                    if a.name == "*":
                        continue
                    m.from_imports[a.asname or a.name] = (base, a.name)

        # the module pseudo-function
        mod_fid = f"{m.rel}::<module>"
        self.functions[mod_fid] = FunctionInfo(
            fid=mod_fid, rel=m.rel, qual="<module>", name="<module>",
            cls=None, lineno=0, params=(), node=m.tree)

        def add_fn(node, qual, cls_name):
            fid = f"{m.rel}::{qual}"
            a = node.args
            params = tuple(x.arg for x in (a.posonlyargs + a.args
                                           + a.kwonlyargs)
                           if x.arg not in ("self", "cls"))
            self.functions[fid] = FunctionInfo(
                fid=fid, rel=m.rel, qual=qual, name=node.name,
                cls=cls_name, lineno=node.lineno, params=params, node=node)
            return fid

        def walk_body(body, prefix, cls_name):
            for n in body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{n.name}"
                    fid = add_fn(n, qual, cls_name)
                    if not prefix:
                        m.functions[n.name] = fid
                    elif cls_name and prefix == f"{cls_name}.":
                        m.classes[cls_name].methods[n.name] = fid
                    walk_body(n.body, f"{qual}.", cls_name)
                elif isinstance(n, ast.ClassDef) and not prefix:
                    bases = [c for c in map(_chain, n.bases) if c]
                    m.classes[n.name] = _ClassInfo(n.name, bases)
                    walk_body(n.body, f"{n.name}.", n.name)
                elif isinstance(n, ast.ClassDef):
                    # nested class: methods indexed under a dotted qual,
                    # not resolvable as self-dispatch — keep the nodes
                    walk_body(n.body, f"{prefix}{n.name}.", None)
                else:
                    # defs behind if/try/except/with at ANY depth: the
                    # recursion walks every nested statement list (an
                    # import-fallback `except ImportError: def helper()`
                    # must still be a graph node)
                    for c in ast.iter_child_nodes(n):
                        if isinstance(c, (ast.stmt, ast.excepthandler)):
                            walk_body([c], prefix, cls_name)

        walk_body(m.tree.body, "", None)

    # -- resolution pass ----------------------------------------------------

    def _resolve(self) -> None:
        for rel, m in sorted(self.modules.items()):
            for fid, fi in list(self.functions.items()):
                if fi.rel != rel:
                    continue
                self._resolve_function(m, fi)

    def _own_calls(self, fi: FunctionInfo) -> Iterable[ast.Call]:
        """Call nodes belonging to this function: its own statements,
        lambdas included, nested defs/classes excluded."""
        if fi.qual == "<module>":
            roots = [n for n in fi.node.body
                     if not isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))]
        else:
            roots = list(fi.node.body)
        stack = list(roots)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def _resolve_function(self, m: _ModuleInfo, fi: FunctionInfo) -> None:
        edges = self.edges_from.setdefault(fi.fid, [])
        nested = {}
        if fi.qual != "<module>":
            for n in fi.node.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested[n.name] = f"{m.rel}::{fi.qual}.{n.name}"
        for call in self._own_calls(fi):
            e = self._resolve_call(m, fi, nested, call)
            if e is not None:
                edges.append(e)
            else:
                self.unresolved += 1

    def _resolve_call(self, m: _ModuleInfo, fi: FunctionInfo,
                      nested: Dict[str, str],
                      call: ast.Call) -> Optional[Edge]:
        line = call.lineno
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in fi.params:
                return Edge(fi.fid, f"<param:{name}>", line, "param")
            if name in nested:
                return Edge(fi.fid, nested[name], line, "direct")
            if name in m.functions:
                return Edge(fi.fid, m.functions[name], line, "direct")
            if name in m.classes:
                init = m.classes[name].methods.get("__init__")
                return Edge(fi.fid, init, line, "ctor") if init else None
            if name in m.from_imports:
                return self._resolve_from_import(fi, m, name, line)
            return None
        if isinstance(func, ast.Attribute):
            meth = func.attr
            chain = _chain(func.value)
            if chain is not None:
                root = chain[0]
                if root in ("self", "cls") and fi.cls and len(chain) == 1:
                    fid = self._resolve_method(m, fi.cls, meth)
                    if fid:
                        return Edge(fi.fid, fid, line, "method")
                elif len(chain) == 1 and chain[0] in m.classes:
                    fid = self._resolve_method(m, chain[0], meth)
                    if fid:
                        return Edge(fi.fid, fid, line, "method")
                else:
                    target = self._module_target(m, chain)
                    if target is not None:
                        tm = self.modules.get(target)
                        if tm and meth in tm.functions:
                            return Edge(fi.fid, tm.functions[meth], line,
                                        "direct")
                        if tm and meth in tm.classes:
                            init = tm.classes[meth].methods.get(
                                "__init__")
                            if init:
                                return Edge(fi.fid, init, line, "ctor")
                        return None
            # fall through: unknown receiver — the interface surface
            if meth in self._iface_methods and self._iface_impls.get(meth):
                # one edge per implementation: dataflow fans out itself
                return Edge(fi.fid, f"<iface:{meth}>", line, "interface")
            return None
        return None

    def iface_targets(self, meth: str) -> List[str]:
        return list(self._iface_impls.get(meth, ()))

    def _resolve_from_import(self, fi: FunctionInfo, m: _ModuleInfo,
                             name: str, line: int) -> Optional[Edge]:
        mod, attr = m.from_imports[name]
        rel = self._find_module(mod)
        if rel is None:
            return None
        tm = self.modules[rel]
        if attr in tm.functions:
            return Edge(fi.fid, tm.functions[attr], line, "direct")
        if attr in tm.classes:
            init = tm.classes[attr].methods.get("__init__")
            if init:
                return Edge(fi.fid, init, line, "ctor")
        return None

    def _module_target(self, m: _ModuleInfo, chain: Tuple[str, ...]) \
            -> Optional[str]:
        """rel of the module a dotted receiver chain names, if any:
        ``import a.b as x; x.f()`` or ``from a import b; b.f()``."""
        root = chain[0]
        dotted = None
        if root in m.imports:
            dotted = m.imports[root]
            if len(chain) > 1:
                dotted = ".".join([dotted] + list(chain[1:]))
        elif root in m.from_imports:
            base, attr = m.from_imports[root]
            dotted = f"{base}.{attr}" if base else attr
            if len(chain) > 1:
                dotted = ".".join([dotted] + list(chain[1:]))
        if dotted is None:
            return None
        return self._find_module(dotted)

    def _find_module(self, dotted: str) -> Optional[str]:
        """Match a dotted import against known modules: exact, then
        suffix on a dot boundary (fixture graphs drop the package
        prefix; package files carry it)."""
        if dotted in self._by_dotted:
            return self._by_dotted[dotted]
        for known, rel in self._by_dotted.items():
            if dotted.endswith("." + known) or known.endswith("." + dotted):
                return rel
        return None

    def _resolve_method(self, m: _ModuleInfo, cls: str, meth: str,
                        _seen: Optional[Set[str]] = None) -> Optional[str]:
        """Resolve ``cls.meth`` through the class and its statically
        reachable bases (same module, or imported by name)."""
        _seen = _seen or set()
        key = f"{m.rel}:{cls}"
        if key in _seen:
            return None
        _seen.add(key)
        ci = m.classes.get(cls)
        if ci is None:
            return None
        if meth in ci.methods:
            return ci.methods[meth]
        for base in ci.bases:
            tail = base[-1]
            if tail in m.classes:
                fid = self._resolve_method(m, tail, meth, _seen)
                if fid:
                    return fid
            elif tail in m.from_imports:
                mod, attr = m.from_imports[tail]
                rel = self._find_module(mod)
                if rel:
                    fid = self._resolve_method(self.modules[rel], attr,
                                               meth, _seen)
                    if fid:
                        return fid
            elif len(base) > 1:
                rel = self._module_target(m, base[:-1])
                if rel:
                    fid = self._resolve_method(self.modules[rel], tail,
                                               meth, _seen)
                    if fid:
                        return fid
        return None


def build_callgraph(paths: Optional[Sequence[str]] = None) -> CallGraph:
    """Parse ``paths`` (default: the whole package) into a CallGraph.
    Path anchoring is lint's (_iter_rel_files): package files ALWAYS
    keep their package-relative path — ``deep lua_mapreduce_tpu/coord``
    must still see ``coord/``-scoped seeds — and fixture trees are
    relative to their root, so they carry the same scope prefixes."""
    if paths is None:
        paths = [_PKG_ROOT]
    sources: List[Tuple[str, str]] = []
    for f, rel in _iter_rel_files(paths):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except (UnicodeDecodeError, OSError):
            continue              # LMR000 territory, not the graph's
        sources.append((rel, src))
    return CallGraph.from_sources(sources)


def utest() -> None:
    """Self-test: resolution of every edge kind on a fixture pair, then
    a sanity pass over the real package."""
    g = CallGraph.from_sources([
        ("coord/a.py", (
            "from coord.b import helper\n"
            "import coord.b\n"
            "class Idx:\n"
            "    def top(self, cb):\n"
            "        self.low()\n"
            "        helper()\n"
            "        coord.b.other()\n"
            "        cb(1)\n"
            "    def low(self):\n"
            "        def inner():\n"
            "            return 1\n"
            "        return inner()\n"
        )),
        ("coord/b.py", (
            "def helper():\n"
            "    return other()\n"
            "def other():\n"
            "    return 2\n"
        )),
        ("store/s.py", (
            "class Store:\n"
            "    def lines(self, name):\n"
            "        raise NotImplementedError\n"
            "class MemStore(Store):\n"
            "    def lines(self, name):\n"
            "        return []\n"
            "def consume(store):\n"
            "    return store.lines('x')\n"
        )),
    ])
    kinds = {(e.caller.split("::")[1], e.callee, e.kind)
             for edges in g.edges_from.values() for e in edges}
    assert ("Idx.top", "coord/a.py::Idx.low", "method") in kinds
    assert ("Idx.top", "coord/b.py::helper", "direct") in kinds
    assert ("Idx.top", "coord/b.py::other", "direct") in kinds
    assert ("Idx.top", "<param:cb>", "param") in kinds
    assert ("Idx.low", "coord/a.py::Idx.low.inner", "direct") in kinds
    assert ("helper", "coord/b.py::other", "direct") in kinds
    assert ("consume", "<iface:lines>", "interface") in kinds
    impls = g.iface_targets("lines")
    assert "store/s.py::MemStore.lines" in impls
    assert "store/s.py::Store.lines" in impls

    real = build_callgraph()
    assert real.node_count() > 500, real.node_count()
    assert real.edge_count() > 1000, real.edge_count()
    # spot checks: the engine's spill factory call and a method edge
    assert any(e.callee.endswith("::Worker.run_one")
               for edges in real.edges_from.values() for e in edges), \
        "worker dispatch edge missing"
    assert "lines" in real.interface_methods()
    assert "claim_batch" in real.interface_methods()
