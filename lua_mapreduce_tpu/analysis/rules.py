"""The rule registry: every framework contract the lint pass enforces.

Each rule is one class (id, severity, title, rationale, path scope,
``check``).  The ids are stable — suppressions and the DESIGN §18
catalog reference them — and new rules append, never renumber.

Known analysis limits (deliberate: simple, predictable checks beat a
dataflow engine that nobody can audit):

- scope is one function at a time; a helper *called* under a lock is
  not re-checked inside the locked region (helpers that themselves
  misbehave are caught when their own body is linted);
- ``self.x = builder`` hands ownership to the object (the wrapper class
  is expected to expose/forward ``close``, as SegmentWriter does).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis.lint import FileContext, Finding, Rule

# --- shared AST helpers ----------------------------------------------------


def _chain(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """Dotted-name parts of a Name/Attribute expr ('a.b.c' → (a, b, c))."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)


def _own_walk(nodes: Sequence[ast.AST]) -> Iterable[ast.AST]:
    """Walk ``nodes`` without entering nested function/class scopes —
    one scope's own statements only (nested scopes are analyzed as
    their own _scopes entries)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _scopes(tree: ast.Module):
    """(scope_node, body) for the module and every function, nested
    included — each analyzed independently."""
    yield tree, tree.body
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n, n.body


def _parent_map(body: Sequence[ast.AST]) -> dict:
    par = {}
    for n in _own_walk(body):
        for c in ast.iter_child_nodes(n):
            par[c] = n
    return par


def _calls(body: Sequence[ast.AST]) -> Iterable[ast.Call]:
    for n in _own_walk(body):
        if isinstance(n, ast.Call):
            yield n


def _is_flock_ctor(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        c = _chain(expr.func)
        return bool(c) and c[-1] == "_FLock"
    return False


def _is_lock_expr(expr: ast.AST) -> bool:
    """A with-context that holds a lock: ``_FLock(...)`` or a dotted
    name whose last part mentions 'lock' (self._lock, _rounds_lock)."""
    if _is_flock_ctor(expr):
        return True
    c = _chain(expr)
    return bool(c) and "lock" in c[-1].lower()


def _locked_regions(body: Sequence[ast.AST]):
    """Locked critical sections in one function body.

    Yields ``(kind, lock_node, stmts)`` where kind is:
      - "lock":  a ``with <lock>:`` block (memory lock or _FLock);
      - "index": everything after ``fd = self._open_locked(...)`` —
        the idx engine's open/flock/operate/close discipline (the
        region runs to the end of the enclosing block, which is how
        the ``try: ... finally: os.close(fd)`` pattern is written).
    """
    for n in _own_walk(body):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            if any(_is_lock_expr(i.context_expr) for i in n.items):
                yield "lock", n, n.body
    # index regions: the function's own statement list plus every
    # nested one (try/if/for bodies)
    lists = [list(body)]
    for n in _own_walk(body):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(n, field, None)
            if isinstance(stmts, list) and stmts:
                lists.append(stmts)
    for stmts in lists:
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
                c = _chain(s.value.func)
                if c and c[-1] == "_open_locked":
                    yield "index", s, stmts[i + 1:]


# --- LMR001: builder / writer lifecycle ------------------------------------

_BUILDER_CTORS = {"writer_for", "SegmentWriter", "TextWriter"}


class BuilderLifecycleRule(Rule):
    id = "LMR001"
    severity = "error"
    title = "builders must be closed on all paths"
    rationale = (
        "A FileBuilder left unbuilt (failed user code, a raise between "
        "creation and build) holds a writer thread, an fd, and a .tmp. "
        "file; on a long-lived elastic worker those leak per retry. "
        "Every store.builder()/writer_for()/SegmentWriter/TextWriter "
        "bound to a name needs a with-block, or a finally/except that "
        "calls .close() on it (directly, or looping a container it was "
        "stored into). Passing the fresh builder straight into a "
        "wrapper call or returning it transfers ownership.")

    @staticmethod
    def _is_creation(call: ast.Call) -> bool:
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "builder" and not call.args
                and not call.keywords):
            return True
        c = _chain(call.func)
        return bool(c) and c[-1] in _BUILDER_CTORS

    @staticmethod
    def _closers(body: Sequence[ast.AST]) -> Set[str]:
        """Names reliably closed in this scope: with-blocks on the name,
        and .close() calls inside finally/except bodies (including the
        for-each-over-container form)."""
        closed: Set[str] = set()

        def scan(stmts):
            for n in _own_walk(stmts):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute) and n.func.attr == "close":
                    c = _chain(n.func.value)
                    if c:
                        closed.add(c[0])
                elif isinstance(n, ast.For):
                    it = n.iter
                    if isinstance(it, ast.Call) and isinstance(
                            it.func, ast.Attribute) \
                            and it.func.attr in ("values", "items"):
                        it = it.func.value
                    c = _chain(it)
                    if c and isinstance(n.target, ast.Name):
                        for m in _own_walk(n.body):
                            if (isinstance(m, ast.Call)
                                    and isinstance(m.func, ast.Attribute)
                                    and m.func.attr == "close"
                                    and isinstance(m.func.value, ast.Name)
                                    and m.func.value.id == n.target.id):
                                closed.add(c[0])

        for n in _own_walk(body):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    c = _chain(item.context_expr)
                    if c and len(c) == 1:
                        closed.add(c[0])
            elif isinstance(n, ast.Try):
                if n.finalbody:
                    scan(n.finalbody)
                for h in n.handlers:
                    scan(h.body)
        return closed

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for _scope, body in _scopes(ctx.tree):
            par = _parent_map(body)
            closed = self._closers(body)
            for call in _calls(body):
                if not self._is_creation(call):
                    continue
                p = par.get(call)
                if isinstance(p, ast.withitem):
                    continue                      # with store.builder() as b
                if isinstance(p, (ast.Call, ast.keyword, ast.Return)):
                    continue                      # ownership transferred
                if isinstance(p, (ast.Assign, ast.NamedExpr)):
                    targets = (p.targets if isinstance(p, ast.Assign)
                               else [p.target])
                    names: Set[str] = set()
                    owned_by_object = False
                    for t in targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
                        elif isinstance(t, ast.Subscript):
                            c = _chain(t.value)
                            if c:
                                names.add(c[0])
                        elif isinstance(t, ast.Attribute):
                            owned_by_object = True
                    if owned_by_object or names & closed:
                        continue
                    yield self.finding(
                        ctx, call,
                        f"builder bound to {sorted(names) or '<target>'} "
                        "is never closed on failure paths — use a "
                        "with-block or close it in a finally")
                else:
                    yield self.finding(
                        ctx, call,
                        "builder created and dropped — bind it and close "
                        "it, or pass it directly to its owner")


# --- LMR002: no foreign IO / callbacks under the index flock ---------------

_IDX_OS_ALLOWED = {"read", "write", "lseek", "close", "fstat", "pread",
                   "pwrite"}
_IDX_DENY_ROOTS = {"json", "tempfile", "subprocess", "shutil", "socket",
                   "urllib", "requests", "glob"}


class IndexFlockIORule(Rule):
    id = "LMR002"
    severity = "error"
    title = "no foreign IO or user callbacks under the index flock"
    rationale = (
        "The job index flock serializes every claim/commit in the "
        "cluster. Anything but fd-local record IO inside it — opening "
        "other files, JSON (de)serialization of payloads, store reads, "
        "user callbacks — multiplies the critical section by an "
        "unbounded cost and can deadlock against the payload path. "
        "Payload/manifest IO belongs before the lock (insert) or after "
        "release (claim's doc build), which is how filestore.py is "
        "structured.")
    paths = ("coord/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for scope, body in _scopes(ctx.tree):
            params: Set[str] = set()
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = scope.args
                params = {x.arg for x in (a.posonlyargs + a.args
                                          + a.kwonlyargs)} - {"self", "cls"}
            for kind, _node, stmts in _locked_regions(body):
                if kind != "index":
                    continue
                for call in _calls(stmts):
                    c = _chain(call.func)
                    if not c:
                        continue
                    if c[0] in ("open", "print", "input") and len(c) == 1:
                        yield self.finding(
                            ctx, call, f"{c[0]}() under the index flock")
                    elif c[0] in _IDX_DENY_ROOTS:
                        yield self.finding(
                            ctx, call,
                            f"{'.'.join(c)} under the index flock — do "
                            "payload/manifest IO outside the lock")
                    elif (c[0] == "os" and len(c) > 1
                          and c[1] not in _IDX_OS_ALLOWED
                          and c[1] != "path"):
                        yield self.finding(
                            ctx, call,
                            f"os.{c[1]} under the index flock (only "
                            "fd-local record IO is allowed)")
                    elif len(c) == 1 and c[0] in params:
                        yield self.finding(
                            ctx, call,
                            f"call to parameter {c[0]!r} under the index "
                            "flock — user callbacks must never run "
                            "inside the lock")


# --- LMR003: single lock-acquisition order ---------------------------------

_LOCKING_METHODS = {"_bump", "round_counts", "_open_locked"}


class LockOrderRule(Rule):
    id = "LMR003"
    severity = "error"
    title = "no second lock while holding one"
    rationale = (
        "The coordination plane has exactly one safe order: take ONE "
        "lock, operate, release. Acquiring a second lock (another "
        "_FLock, the index flock, the instance lock, or a method that "
        "takes the class-level rounds lock, like _bump) while holding "
        "one creates an AB/BA deadlock the churn tests can only find "
        "by luck.")
    paths = ("coord/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for _scope, body in _scopes(ctx.tree):
            for _kind, _node, stmts in _locked_regions(body):
                for n in _own_walk(stmts):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            if _is_lock_expr(item.context_expr):
                                yield self.finding(
                                    ctx, n, "nested lock acquisition "
                                    "inside a locked region")
                    elif isinstance(n, ast.Call):
                        c = _chain(n.func)
                        if not c:
                            continue
                        if c[-1] == "_FLock" or (
                                c[0] == "fcntl" and len(c) > 1
                                and c[1] == "flock"):
                            yield self.finding(
                                ctx, n, f"{'.'.join(c)} acquired inside "
                                "a locked region")
                        elif c[-1] in _LOCKING_METHODS and len(c) > 1:
                            yield self.finding(
                                ctx, n,
                                f"{'.'.join(c)}() takes another lock — "
                                "call it before or after the critical "
                                "section")
                        elif c[-1] == "acquire":
                            yield self.finding(
                                ctx, n, "explicit .acquire() inside a "
                                "locked region")


# --- LMR004: no wall-clock reads under a coordination lock -----------------

_CLOCK_CALLS = {"time", "monotonic", "time_ns", "perf_counter"}


class WallclockUnderLockRule(Rule):
    id = "LMR004"
    severity = "error"
    title = "no time.time() inside a locked critical section"
    rationale = (
        "Lease math (claim stamps, heartbeats, staleness cutoffs) must "
        "use a timestamp decided BEFORE the lock: a wall-clock read "
        "inside the critical section moves with lock contention, so "
        "two runs of the same protocol order events differently — and "
        "it grows the hold time of the hottest lock in the system. "
        "Hoist ``now = time.time()`` above the acquisition (the index "
        "engines take ``now`` as an argument for exactly this reason).")
    paths = ("coord/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for _scope, body in _scopes(ctx.tree):
            for _kind, _node, stmts in _locked_regions(body):
                for call in _calls(stmts):
                    c = _chain(call.func)
                    if (c and len(c) == 2 and c[0] == "time"
                            and c[1] in _CLOCK_CALLS):
                        yield self.finding(
                            ctx, call,
                            f"{'.'.join(c)}() under a coordination lock "
                            "— hoist the clock read above the lock")


# --- LMR005: swallow-except hygiene ----------------------------------------

_LOG_ATTRS = {"warning", "error", "exception", "critical", "info", "debug",
              "log", "warn", "print_exc", "_exit", "exit"}


class SwallowExceptRule(Rule):
    id = "LMR005"
    severity = "error"
    title = "bare/BaseException handlers must re-raise or log"
    rationale = (
        "A handler that catches everything (bare except / "
        "BaseException) and neither re-raises nor logs erases the real "
        "failure — the async-writer and checkpoint threads have both "
        "shipped bugs where the worker's actual exception context "
        "vanished. Catch narrowly, or record what you swallowed. "
        "(``except Exception`` on a best-effort sweep path is allowed; "
        "this rule is about the catch-alls that also eat SystemExit/"
        "KeyboardInterrupt.)")

    @staticmethod
    def _catches_everything(h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True
        types = (h.type.elts if isinstance(h.type, ast.Tuple) else [h.type])
        for t in types:
            c = _chain(t)
            if c and c[-1] == "BaseException":
                return True
        return False

    @staticmethod
    def _handles(body: Sequence[ast.AST]) -> bool:
        for n in _own_walk(body):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                c = _chain(n.func)
                if not c:
                    continue
                if c[-1] in _LOG_ATTRS or c[0] in ("print", "log"):
                    return True
        return False

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.ExceptHandler) \
                    and self._catches_everything(n) \
                    and not self._handles(n.body):
                yield self.finding(
                    ctx, n, "catch-all handler swallows the exception — "
                    "re-raise, log it, or narrow the except")


# --- LMR006: raw-bytes store contract --------------------------------------

class RawBytesContractRule(Rule):
    id = "LMR006"
    severity = "error"
    title = "read_range/size come in pairs; shims are latin-1"
    rationale = (
        "The v2 segment reader locates the trailer with size() and "
        "pulls frames with read_range(); a Store that overrides one "
        "natively but inherits the other's O(file) text shim silently "
        "mixes byte spaces (native bytes vs latin-1-decoded text) and "
        "either corrupts frames or re-reads whole files per range. "
        "Implement both or neither. Inside write_bytes/read_range/size "
        "the only legal text bridge is latin-1 — utf-8 is not "
        "byte-transparent (DESIGN §17).")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.ClassDef):
                continue
            bases = {c[-1] for c in map(_chain, n.bases) if c}
            methods = {m.name: m for m in n.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if any(b == "Store" or b.endswith("Store") for b in bases):
                have = {"read_range", "size"} & set(methods)
                if len(have) == 1:
                    (name,) = have
                    other = ({"read_range", "size"} - have).pop()
                    yield self.finding(
                        ctx, methods[name],
                        f"{n.name} overrides {name}() but not {other}() "
                        "— the raw-bytes surface is a pair")
            for mname in ("write_bytes", "read_range", "size"):
                m = methods.get(mname)
                if m is None:
                    continue
                for call in _calls(m.body):
                    if isinstance(call.func, ast.Attribute) \
                            and call.func.attr in ("encode", "decode") \
                            and call.args \
                            and isinstance(call.args[0], ast.Constant) \
                            and str(call.args[0].value).lower().replace(
                                "-", "") != "latin1":
                        yield self.finding(
                            ctx, call,
                            f"{mname}() bridges text with "
                            f"{call.args[0].value!r} — only latin-1 maps "
                            "bytes 0-255 losslessly")


# --- LMR009: spill publishes go through the replication helper -------------

# the unreplicated record-writer factories (core/segment.py). A spill
# producer constructing one of these directly publishes exactly ONE
# copy, whatever the negotiated replication factor says.
_PLAIN_SPILL_FACTORIES = {"writer_for", "SegmentWriter", "TextWriter"}

# literal shapes of the coded stripe plane (faults/coded.py, DESIGN
# §27): "^<i>.<t>^" block prefixes and the "^M^" manifest marker.
# Matched against the LITERAL text of a string (for f-strings, the
# concatenated constant parts: f"^{i}.{t}^{name}" reduces to "^.^") —
# the documented analysis limit: names assembled through .join()/
# concatenation of variables are out of reach, literal prefixes are
# the shape every real offender has.
_STRIPE_BLOCK_RE = re.compile(r"\^(?:\d+|\*)?\.?\^|\^(?:\d+|\*)\.")
_STRIPE_MANIFEST_MARKER = "^M^"
_CODED_HOME = "faults/coded.py"


def _docstring_consts(tree: ast.Module) -> Set[int]:
    """id()s of every docstring Constant — prose that legitimately
    spells stripe names when documenting them."""
    out: Set[int] = set()
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))]
    for s in scopes:
        body = getattr(s, "body", None)
        if body and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            out.add(id(body[0].value))
    return out


def _stripe_literals(ctx: FileContext):
    """(node, literal_text) for every non-docstring string literal or
    f-string in the file, literal parts concatenated (an f-string
    counts once as a whole — its part constants are not re-yielded)."""
    skip = _docstring_consts(ctx.tree)
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.JoinedStr):
            skip.update(id(v) for v in n.values)
    for n in ast.walk(ctx.tree):
        if id(n) in skip:
            continue
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n, n.value
        elif isinstance(n, ast.JoinedStr):
            yield n, "".join(v.value for v in n.values
                             if isinstance(v, ast.Constant)
                             and isinstance(v.value, str))


class ReplicatedSpillRule(Rule):
    id = "LMR009"
    severity = "error"
    title = "engine spill publishes must use the replication helper"
    rationale = (
        "Every run/spill publish in engine/ must go through "
        "faults.replicate.spill_writer(store, format, replication): it "
        "is the one place the negotiated replication factor turns into "
        "an r-way fanout at the placement function's addresses "
        "(DESIGN §20). A raw writer_for()/SegmentWriter()/TextWriter() "
        "in a producer publishes a single copy — silently "
        "under-replicated, invisible until the one copy is lost and a "
        "map re-run pays for it. (Result-file publishes use the plain "
        "store builder and are exempt: final results are deliberately "
        "not replicated.) Coded corollary (DESIGN §27): a \"^i.t^\" "
        "stripe-block name spelled as a literal outside faults/coded.py "
        "is a publish (or read) that bypasses the codec — a hand-rolled "
        "block misses the stripe manifest's CRC/placement contract and "
        "the scavenger's repair accounting; only the coded module may "
        "mint block names.")
    paths = ("engine/", "faults/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel.startswith("engine/"):
            for n in ast.walk(ctx.tree):
                if not isinstance(n, ast.Call):
                    continue
                c = _chain(n.func)
                if c and c[-1] in _PLAIN_SPILL_FACTORIES:
                    yield self.finding(
                        ctx, n,
                        f"{c[-1]}(...) in engine/ publishes a single "
                        "unreplicated copy — route the spill through "
                        "faults.replicate.spill_writer so the negotiated "
                        "replication factor applies")
        if ctx.rel != _CODED_HOME:
            for node, text in _stripe_literals(ctx):
                if _STRIPE_BLOCK_RE.search(text):
                    yield self.finding(
                        ctx, node,
                        "stripe-block name constructed directly "
                        f"({text!r}) — \"^i.t^\" blocks exist only "
                        "behind the coded codec's manifest/CRC/"
                        "placement contract; use the faults.coded "
                        "helpers (stripe_patterns for matching, "
                        "CodedStore/publish_stripe for I/O)")


# --- LMR008: classified raisables across the retry boundary ----------------

# the op surfaces the retry layer wraps (DESIGN §19): store data-plane
# ops and coord RPCs. Raises inside these methods cross the retry
# boundary, so the retry layer must be able to classify them.
_RETRY_BOUNDARY_METHODS = {
    # Store / FileBuilder surface
    "lines", "read_range", "size", "list", "exists", "remove", "build",
    "write", "write_bytes", "_put", "_get", "_drain", "_flush_async",
    # JobStore RPC surface
    "claim", "claim_batch", "commit_batch", "release_batch", "heartbeat",
    "heartbeat_batch", "set_job_status", "set_job_times", "counts",
    "scavenge", "requeue_stale", "get_task", "put_task", "update_task",
    "delete_task", "insert_jobs", "insert_error", "drain_errors",
}

# generic exception types the taxonomy cannot place: raising one of
# these across the boundary forces the retry layer to guess. (ValueError/
# KeyError/FileNotFoundError etc. are fine — the central table maps
# them; StoreError subclasses are the preferred currency.)
_UNCLASSIFIED_RAISES = {"Exception", "BaseException", "RuntimeError",
                        "OSError", "IOError", "EnvironmentError",
                        "SystemError"}


class ClassifiedRaiseRule(Rule):
    id = "LMR008"
    severity = "error"
    title = "store/coord op raises must be classified StoreError shapes"
    rationale = (
        "Every store op and coord RPC runs under the transient-fault "
        "retry layer (faults/retry.py). A generic RuntimeError/OSError "
        "raised across that boundary cannot be classified: the retry "
        "layer either retries a deterministic failure (wasted backoff, "
        "masked bug) or gives up on a transient one (spurious job "
        "release). Raise a StoreError subclass (TransientStoreError / "
        "PermanentStoreError / NativeIndexError / NoTaskError ...) or a "
        "builtin the taxonomy maps (FileNotFoundError, TimeoutError, "
        "ValueError for data errors).")
    paths = ("store/", "coord/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for scope, body in _scopes(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            if scope.name not in _RETRY_BOUNDARY_METHODS:
                continue
            for n in _own_walk(body):
                if not isinstance(n, ast.Raise) or n.exc is None:
                    continue
                exc = n.exc
                if isinstance(exc, ast.Call):
                    exc = exc.func
                c = _chain(exc)
                if c and c[-1] in _UNCLASSIFIED_RAISES:
                    yield self.finding(
                        ctx, n,
                        f"raise {c[-1]} inside retry-boundary op "
                        f"{scope.name}() — use a classified StoreError "
                        "subclass so the retry layer can route it")


# --- LMR007: purity of jit/shard_map-traced functions ----------------------

_TRACER_NAMES = {"jit", "shard_map", "pjit", "pallas_call", "vmap", "pmap",
                 "grad", "value_and_grad", "checkpoint", "remat", "scan"}
_IMPURE_ROOTS = {("np", "random"), ("numpy", "random"), ("random",),
                 ("time",)}


class JaxPurityRule(Rule):
    id = "LMR007"
    severity = "error"
    title = "no host side effects inside traced functions"
    rationale = (
        "A function under jit/shard_map runs its Python body ONCE at "
        "trace time: numpy/stdlib RNG draws become compile-time "
        "constants baked into every call, time.time() measures tracing, "
        "and print/open fire on trace, not on execution. Use "
        "jax.random with explicit keys, jax.debug.print, and pass host "
        "data in as arguments.")
    paths = ("ops/", "parallel/")

    @staticmethod
    def _decorator_traces(dec: ast.AST) -> bool:
        c = _chain(dec)
        if c and c[-1] in _TRACER_NAMES:
            return True
        if isinstance(dec, ast.Call):
            c = _chain(dec.func)
            if c and c[-1] in _TRACER_NAMES:
                return True
            if c and c[-1] == "partial":
                for a in dec.args[:1]:
                    ca = _chain(a)
                    if ca and ca[-1] in _TRACER_NAMES:
                        return True
        return False

    def _traced_names(self, tree: ast.Module) -> Set[str]:
        """Function names passed (positionally, first arg) to a tracing
        transform anywhere in the module."""
        out: Set[str] = set()
        for n in ast.walk(tree):
            if isinstance(n, ast.Call):
                c = _chain(n.func)
                if c and c[-1] in _TRACER_NAMES and n.args:
                    ca = _chain(n.args[0])
                    if ca and len(ca) == 1:
                        out.add(ca[0])
        return out

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        traced = self._traced_names(ctx.tree)
        for n in ast.walk(ctx.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if n.name not in traced and not any(
                    self._decorator_traces(d) for d in n.decorator_list):
                continue
            # the whole body, nested defs included — inner closures
            # trace with their parent
            for m in ast.walk(n):
                if not isinstance(m, ast.Call):
                    continue
                c = _chain(m.func)
                if not c:
                    continue
                if len(c) == 1 and c[0] in ("open", "input"):
                    yield self.finding(
                        ctx, m, f"{c[0]}() inside traced "
                        f"function {n.name!r}")
                elif len(c) == 1 and c[0] == "print":
                    yield self.finding(
                        ctx, m, f"print() inside traced function "
                        f"{n.name!r} fires at trace time — use "
                        "jax.debug.print")
                elif any(c[:len(root)] == root for root in _IMPURE_ROOTS):
                    yield self.finding(
                        ctx, m, f"{'.'.join(c)} inside traced function "
                        f"{n.name!r} is evaluated once at trace time — "
                        "use jax.random / pass values as arguments")


# --- LMR010: trace/ span timing must use the injectable clock ---------------

_DIRECT_CLOCK_CALLS = {"time", "monotonic", "perf_counter", "time_ns",
                       "monotonic_ns", "perf_counter_ns"}


class InjectableClockRule(Rule):
    id = "LMR010"
    severity = "error"
    title = "trace code reads time only through the injectable clock"
    rationale = (
        "Every span timestamp in trace/ must flow through the Tracer's "
        "injectable clock (self._clock / tracer.clock()), never a bare "
        "time.time()/perf_counter() call: deterministic-trace tests "
        "replay exact timelines on a virtual clock, and a single direct "
        "wall-clock read silently splits the timeline into two time "
        "bases that no collector can re-align (the LMR004 discipline, "
        "extended from lock scopes to the whole tracing subsystem). "
        "Binding time.time as a DEFAULT (clock=time.time) is the one "
        "legal appearance — it is the injection point itself, a "
        "reference, not a read. Engine job timing (JobTimes) predates "
        "the tracer and stays on its own clock; the rule scopes to "
        "trace/ where determinism is the contract.")
    paths = ("trace/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            c = _chain(n.func)
            if (c and len(c) == 2 and c[0] == "time"
                    and c[1] in _DIRECT_CLOCK_CALLS):
                yield self.finding(
                    ctx, n,
                    f"{'.'.join(c)}() in trace/ — route the read "
                    "through the Tracer's injectable clock "
                    "(self._clock() / tracer.clock())")


# --- LMR011: engine/coord waits go through the injectable Waiter ------------

class IdleWaitRule(Rule):
    id = "LMR011"
    severity = "error"
    title = "no bare time.sleep in coord/engine wait paths"
    rationale = (
        "Every wait in the coordination and engine planes — idle-poll "
        "backoff, barrier polls, retry delays, lock contention — must "
        "go through the sched Waiter (sched/waiter.py): a bare "
        "time.sleep() is a wait that NOTHING can interrupt, so one "
        "call silently re-opens the fixed-interval dispatch-latency "
        "floor the watch/notify layer removed (DESIGN §23), and it "
        "dodges the injectable-clock discipline virtual-time tests "
        "rely on. Call waiter.wait(timeout) (the NullWaiter degrades "
        "to exactly a sleep when notify is off); binding time.sleep as "
        "a DEFAULT (sleep=time.sleep) is the injection point itself — "
        "a reference, not a call — and stays legal.")
    paths = ("coord/", "engine/")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for n in ast.walk(ctx.tree):
            if not isinstance(n, ast.Call):
                continue
            c = _chain(n.func)
            if c and len(c) == 2 and c[0] == "time" and c[1] == "sleep":
                yield self.finding(
                    ctx, n,
                    "time.sleep() in a coord/engine wait path — wait "
                    "through the injectable Waiter "
                    "(sched.waiter.channel_for(store).waiter().wait / "
                    "NullWaiter) so notifications can interrupt it")


# --- LMR012: inbox publishes go through spill_writer ------------------------

# literal markers of push-plane names (engine/push.py): inbox frame /
# tail fragments and the PUSH manifest namespace
_PUSH_NAME_MARKERS = ("INBOX", ".PUSH.")


class PushInboxPublishRule(Rule):
    id = "LMR012"
    severity = "error"
    title = "inbox publishes in engine/ must go through spill_writer"
    rationale = (
        "Every push-shuffle publish — inbox frames, eviction tails, "
        "PUSH manifests — must be built by a writer obtained from "
        "faults.replicate.spill_writer (DESIGN §24): it is the one "
        "place the negotiated replication factor becomes an r-way "
        "fanout at the placement addresses, and the failover/repair/"
        "blackout machinery assumes every inbox copy exists where the "
        "placement function says. A raw store builder (store.builder()"
        ".build(...)) publishing an INBOX-/PUSH-named file lands a "
        "single unreplicated copy that one lost target silently "
        "erases. Heuristic scope (the documented analysis limits): "
        "builds whose name argument carries a literal INBOX/.PUSH. "
        "part, receivers resolved within one function scope. Coded "
        "corollary (DESIGN §27): a \"^M^\" stripe-manifest name "
        "spelled as a literal outside faults/coded.py forges the "
        "visibility gate itself — a hand-written manifest makes a "
        "partial stripe readable (or hides a complete one), so only "
        "the coded module may mint manifest names.")
    paths = ("engine/", "faults/")

    @staticmethod
    def _literal_parts(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            return "".join(v.value for v in node.values
                           if isinstance(v, ast.Constant)
                           and isinstance(v.value, str))
        return ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel.startswith("engine/"):
            for _scope, body in _scopes(ctx.tree):
                ok: Set[Tuple[str, ...]] = set()
                for n in _own_walk(body):
                    if isinstance(n, ast.Assign) \
                            and isinstance(n.value, ast.Call):
                        c = _chain(n.value.func)
                        if c and c[-1] == "spill_writer":
                            for t in n.targets:
                                tc = _chain(t)
                                if tc:
                                    ok.add(tc)
                for call in _calls(body):
                    if not (isinstance(call.func, ast.Attribute)
                            and call.func.attr == "build" and call.args):
                        continue
                    text = self._literal_parts(call.args[0])
                    if not any(m in text for m in _PUSH_NAME_MARKERS):
                        continue
                    recv = _chain(call.func.value)
                    if recv is not None and recv in ok:
                        continue
                    yield self.finding(
                        ctx, call,
                        "inbox/manifest publish built outside "
                        "spill_writer — a raw builder lands ONE "
                        "unreplicated copy; route the publish through "
                        "faults.replicate.spill_writer so the "
                        "negotiated replication factor applies")
        if ctx.rel != _CODED_HOME:
            for node, text in _stripe_literals(ctx):
                if _STRIPE_MANIFEST_MARKER in text:
                    yield self.finding(
                        ctx, node,
                        "stripe-manifest name constructed directly "
                        f"({text!r}) — \"^M^\" manifests ARE the "
                        "stripe visibility gate; minting one outside "
                        "faults.coded can expose a partial stripe. "
                        "Match them with faults.coded.manifest_pattern/"
                        "stripe_patterns, publish through the codec")


# --- LMR018: controller-owned knobs must ride the task-doc negotiation ------

# the attribute names of knobs the autotune controller owns when the
# task doc carries the "autotune" marker (sched/controller.py
# CONTROLLER_KNOBS, minus the ones with no per-worker attribute)
_CONTROLLER_KNOB_ATTRS = ("batch_k", "speculation", "push_budget_mb")


class AutotuneKnobBypassRule(Rule):
    id = "LMR018"
    severity = "error"
    title = "task-scoped engine code must read controller-owned knobs " \
            "through the task doc"
    rationale = (
        "The autotune controller (DESIGN §29) deploys its decisions by "
        "writing knob values onto the task document; the fleet follows "
        "the doc on its next poll. An engine/ hot path that handles a "
        "``task`` doc but reads ``self.batch_k`` / ``self.speculation`` "
        "/ ``self.push_budget_mb`` directly — without consulting "
        "``task.get(\"<knob>\")`` — silently pins the process-local "
        "value: the controller's change lands on the doc, every "
        "compliant worker follows it, and the bypassing path diverges "
        "from the fleet (a batch_k bypass splits lease sizing; a "
        "speculation bypass desynchronizes the straggler threshold). "
        "Heuristic scope (the documented analysis limits): function "
        "scopes that bind a ``task`` name, one scope at a time. Reads "
        "that ARE the negotiation — the same scope also reads "
        "``task.get(\"<same knob>\")`` (the own-override-else-doc "
        "pattern) — and knob values passed to ``put_task`` / "
        "``update_task`` (the deploy writes themselves) are exempt.")
    paths = ("engine/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for scope, body in _scopes(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            has_task = any(a.arg == "task" for a in
                           scope.args.args + scope.args.kwonlyargs)
            if not has_task:
                has_task = any(
                    isinstance(n, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "task"
                            for t in n.targets)
                    for n in _own_walk(body))
            if not has_task:
                continue
            negotiated: Set[str] = set()
            for call in _calls(body):
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "get"
                        and _chain(call.func.value) == ("task",)
                        and call.args
                        and isinstance(call.args[0], ast.Constant)):
                    negotiated.add(call.args[0].value)
            par = _parent_map(body)
            for n in _own_walk(body):
                if not (isinstance(n, ast.Attribute)
                        and isinstance(n.ctx, ast.Load)
                        and n.attr in _CONTROLLER_KNOB_ATTRS
                        and _chain(n.value) == ("self",)):
                    continue
                if n.attr in negotiated:
                    continue
                cur, exempt = n, False
                while cur in par:
                    cur = par[cur]
                    if isinstance(cur, ast.Call):
                        c = _chain(cur.func)
                        if c and c[-1] in ("put_task", "update_task"):
                            exempt = True      # the deploy write itself
                            break
                if exempt:
                    continue
                yield self.finding(
                    ctx, n,
                    f"direct read of controller-owned knob "
                    f"self.{n.attr} in a task-scoped path — the "
                    f"autotune deploy lands on the task doc, so read "
                    f"the negotiated value (own override else "
                    f"task.get(\"{n.attr}\")) or the fleet diverges "
                    "when the controller retunes it")
