"""Interprocedural context propagation + the LMR013+ deep rules.

The per-function rules (analysis/rules.py) each guard one region kind —
the index flock, a retry-boundary op body, a traced function — but stop
at the first call: a helper one frame deep evades every one of them.
This pass closes that hole.  It seeds *execution contexts* at the same
syntactic regions the per-function rules recognize, then propagates
them over the whole-program call graph (analysis/callgraph.py):

====================  =====================================================
context               seeded at
====================  =====================================================
holds-flock           call sites inside an ``_open_locked`` index region
                      (coord/ — the flock discipline, LMR002's region)
inside-retry-boundary bodies of retry-boundary ops (store//coord//faults/,
                      LMR008's method set) and functions handed to a
                      ``RetryPolicy.call`` frame
under-jit-trace       jit/shard_map-traced functions in ops//parallel/
                      (LMR007's detection)
replay-deterministic  every function in trace/ (LMR010's scope) and call
                      sites inside coord/ locked regions (LMR004's scope)
====================  =====================================================

The context lattice is flat — a function either runs under a context or
does not; propagation is a BFS per context with the first (shortest)
call chain kept for the diagnostic.  Which edge kinds propagate is per
context: the storage-plane ``interface`` fan-out follows only the
retry-boundary context (a retried op really may dispatch to any
implementation); the deterministic contexts follow static edges only.

Each deep rule then checks the *reached* functions and reports with the
full chain.  Violations a per-function rule already catches at depth 0
are left to that rule (one finding per defect, stable anchors); the
deep ids fire on what the per-function pass provably misses:

- **LMR013** — foreign IO / blocking store ops / user callbacks
  reachable while the index flock is held (interprocedural LMR002; the
  store data-plane call check also fires at depth 0 — LMR002 has no
  net for it).
- **LMR014** — unclassified raisables reachable across the retry
  boundary (interprocedural LMR008, now also covering helpers outside
  store//coord/).
- **LMR015** — wall-clock / RNG reachable inside a replay-deterministic
  region (interprocedural LMR004 + LMR010).
- **LMR016** — non-replayable RPCs (insert_jobs / pt_cas / claim_batch)
  reachable from inside a RetryPolicy-wrapped frame: a retried frame
  that can re-run one of these double-inserts or strands a lease
  (DESIGN §19's excluded-ops table, now enforced).
- **LMR017** — host side effects reachable under a jit/shard_map trace
  (interprocedural LMR007).

Suppression is the lint engine's: inline ``# lmr: disable=`` on the
offending line, or a justified baseline entry.
"""

from __future__ import annotations

import ast
import dataclasses
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from lua_mapreduce_tpu.analysis import rules as _r
from lua_mapreduce_tpu.analysis.callgraph import (CallGraph, Edge,
                                                  FunctionInfo,
                                                  build_callgraph)
from lua_mapreduce_tpu.analysis.lint import (Finding, _baseline_match,
                                             _line_disables_in,
                                             load_baseline)

# -- contexts ----------------------------------------------------------------

HOLDS_FLOCK = "holds-flock"
RETRY_BOUNDARY = "inside-retry-boundary"
# the retried refinement of the boundary: only frames the retry layer
# actually REPLAYS on a transient fault (the boundary minus the
# deliberately unretried ops) — LMR016's scope. claim/claim_batch ARE
# boundary ops (their raises must classify) but are never replayed, so
# their own claim_batch call is not a replay hazard.
RETRIED_FRAME = "inside-retried-frame"
JIT_TRACE = "under-jit-trace"
REPLAY_DET = "replay-deterministic"

# which call-edge kinds each context follows (the lattice's propagation
# policy — see module docstring)
_FOLLOW = {
    HOLDS_FLOCK: {"direct", "method", "ctor"},
    RETRY_BOUNDARY: {"direct", "method", "ctor", "interface"},
    RETRIED_FRAME: {"direct", "method", "ctor", "interface"},
    JIT_TRACE: {"direct", "method", "ctor"},
    REPLAY_DET: {"direct", "method", "ctor"},
}

_MAX_DEPTH = 12           # cycles are cut by the visited set; this only
                          # bounds pathological chains in the report

# store data-plane methods whose *call* under the flock is itself the
# violation (blocking IO through the storage interface — LMR002 has no
# net for these, so LMR013 fires at any depth including 0)
_DATA_PLANE_CALLS = {"lines", "builder", "read_range", "list", "exists",
                     "remove", "size", "write_bytes", "build"}

# the non-replayable RPC set (DESIGN §19): a retried frame reaching one
# of these can double-insert / double-claim on a landed first attempt
_NON_REPLAYABLE = {"insert_jobs", "pt_cas", "claim_batch"}

# retried frames: the boundary set MINUS the deliberately unretried ops
# (faults/wrappers.py: _RETRIED_RPCS = RPC_OPS - {claim_batch,
# claim_spec}, and insert_jobs/pt_cas/claim forward unretried). The
# errors-stream pair (insert_error/drain_errors) IS retried — its
# at-least-once contract makes replay acceptable for telemetry, but a
# helper chain from it into a non-replayable RPC is still LMR016.
_RETRIED_FRAME_METHODS = _r._RETRY_BOUNDARY_METHODS - {
    "claim", "claim_batch", "insert_jobs"}

_CLOCK_CALLS = {"time", "monotonic", "perf_counter", "time_ns",
                "monotonic_ns", "perf_counter_ns"}
_RNG_ROOTS = {("random",), ("np", "random"), ("numpy", "random")}


# -- deep-rule registry (metadata mirrors lint.Rule for the catalog) ---------

@dataclasses.dataclass(frozen=True)
class DeepRule:
    id: str
    severity: str
    title: str
    rationale: str
    paths: Tuple[str, ...]    # where the CONTEXT seeds live (findings
                              # may anchor anywhere a chain reaches)


DEEP_RULES: Tuple[DeepRule, ...] = (
    DeepRule(
        "LMR013", "error",
        "no IO or user callbacks reachable while the flock is held",
        "The index flock serializes every claim/commit in the cluster; "
        "LMR002 polices the locked region itself, but a helper called "
        "from it runs under the same flock one frame deep. Any call "
        "chain from an _open_locked region into foreign IO (open/json/"
        "tempfile/os.*), a blocking store data-plane op (lines/build/"
        "read_range...), time.sleep, or a user callback multiplies the "
        "hottest critical section by an unbounded cost.",
        ("coord/",)),
    DeepRule(
        "LMR014", "error",
        "no unclassified raisables reachable across the retry boundary",
        "Every store op and coord RPC runs under the transient-fault "
        "retry layer; LMR008 checks the op bodies, but a helper they "
        "call — in core/, utils/, anywhere — that raises a generic "
        "RuntimeError/OSError sends an unclassifiable exception across "
        "the same boundary. The retry layer then guesses: wasted "
        "backoff on a deterministic failure, or a spurious job release "
        "on a transient one.",
        ("store/", "coord/", "faults/")),
    DeepRule(
        "LMR015", "error",
        "no wall-clock/RNG reachable inside replay-deterministic regions",
        "Trace timestamps and lease math must be decided by the "
        "injectable clock (LMR010) or hoisted above the lock (LMR004); "
        "a helper called from those regions that reads time.time() or "
        "draws from an unseeded RNG splits the timeline into two time "
        "bases one frame deep, where the per-function rules cannot see "
        "it — and replay/chaos byte-identity quietly stops meaning "
        "anything.",
        ("trace/", "coord/")),
    DeepRule(
        "LMR016", "error",
        "no non-replayable RPCs reachable from a RetryPolicy-wrapped frame",
        "insert_jobs, pt_cas and claim_batch are excluded from the "
        "retried-op set by design (DESIGN §19): a retry whose first "
        "attempt landed double-inserts a namespace, double-applies a "
        "task-doc CAS, or strands a claimed lease nobody executes. A "
        "call chain from inside any retried frame into one of them "
        "re-opens exactly that hole.",
        ("store/", "coord/", "faults/")),
    DeepRule(
        "LMR017", "error",
        "no host side effects reachable under a jit/shard_map trace",
        "A traced function's Python body runs once at trace time — and "
        "so does every helper it calls. LMR007 checks the traced "
        "function itself; a helper one frame deep with np.random/"
        "time.time()/print bakes trace-time garbage into every "
        "execution just as silently.",
        ("ops/", "parallel/")),
)


# -- seeding -----------------------------------------------------------------

@dataclasses.dataclass
class _Seed:
    context: str
    fid: str
    # restrict propagation to edges at these lines (region seeds); None
    # seeds the whole function body
    lines: Optional[Set[int]]
    # where the context was established, for the chain diagnostic
    origin: str
    # run the depth-0 checks on the seed function itself: set for seeds
    # NO per-function rule anchors (a function handed to
    # RetryPolicy.call is the retried frame, but it is not a boundary
    # method LMR008 would have checked)
    depth0: bool = False


def _region_call_lines(stmts: Sequence[ast.AST]) -> Set[int]:
    return {c.lineno for c in _r._calls(stmts)}


def _collect_seeds(g: CallGraph) -> List[_Seed]:
    seeds: List[_Seed] = []
    for fid, fi in sorted(g.functions.items()):
        rel = fi.rel
        body = fi.node.body
        if rel.startswith("coord/"):
            for kind, _node, stmts in _r._locked_regions(body):
                lines = _region_call_lines(stmts)
                if not lines:
                    continue
                if kind == "index":
                    seeds.append(_Seed(HOLDS_FLOCK, fid, lines,
                                       f"{rel}:{fi.qual}"))
                # every locked coordination region is replay-
                # deterministic: lease math must not move with the clock
                seeds.append(_Seed(REPLAY_DET, fid, lines,
                                   f"{rel}:{fi.qual}"))
        if rel.startswith("trace/") and rel != "trace/__main__.py" \
                and fi.qual != "<module>" and "utest" not in fi.qual:
            # the trace CLI (__main__) is the offline PRESENTATION
            # layer: it wires real stores (whose retry jitter draws a
            # wall-seeded RNG) to READ spans — it never stamps one.
            # utest() drives the subsystem from OUTSIDE the
            # deterministic region (it builds stores, jobs, policies on
            # the real clock) — not a replay-deterministic frame
            seeds.append(_Seed(REPLAY_DET, fid, None, f"{rel}:{fi.qual}"))
        if rel.startswith(("store/", "coord/", "faults/")) \
                and fi.cls is not None:
            if fi.name in _r._RETRY_BOUNDARY_METHODS:
                seeds.append(_Seed(RETRY_BOUNDARY, fid, None,
                                   f"{rel}:{fi.qual}"))
            if fi.name in _RETRIED_FRAME_METHODS:
                seeds.append(_Seed(RETRIED_FRAME, fid, None,
                                   f"{rel}:{fi.qual}"))
        if rel.startswith(("ops/", "parallel/")) and fi.qual != "<module>":
            if _is_traced(g, fi):
                seeds.append(_Seed(JIT_TRACE, fid, None,
                                   f"{rel}:{fi.qual}"))
    seeds.extend(_policy_call_seeds(g))
    return seeds


def _is_traced(g: CallGraph, fi: FunctionInfo) -> bool:
    """LMR007's detection: decorated by a tracer, or passed (first
    positional) to a tracing transform anywhere in its module."""
    node = fi.node
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    rule = _r.JaxPurityRule()
    if any(rule._decorator_traces(d) for d in node.decorator_list):
        return True
    mod = g.modules.get(fi.rel)
    return mod is not None and fi.name in rule._traced_names(mod.tree) \
        and fi.cls is None


def _policy_call_seeds(g: CallGraph) -> Iterable[_Seed]:
    """Functions handed to a RetryPolicy frame: ``<policyish>.call(fn)``
    with fn a local/nested function name, or a lambda (whose calls are
    attributed to the enclosing function — seed those lines)."""
    for fid, fi in sorted(g.functions.items()):
        if fi.qual == "<module>":
            continue          # every def re-walks below; module-level
                              # RetryPolicy frames don't exist
        for n in ast.walk(fi.node):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "call" and n.args):
                continue
            recv = _r._chain(n.func.value)
            if not recv or not any("policy" in part.lower()
                                   for part in recv):
                continue
            arg = n.args[0]
            if isinstance(arg, ast.Lambda):
                lines = {c.lineno for c in ast.walk(arg)
                         if isinstance(c, ast.Call)}
                if lines:
                    for ctx in (RETRY_BOUNDARY, RETRIED_FRAME):
                        yield _Seed(ctx, fid, lines,
                                    f"{fi.rel}:{fi.qual}")
            elif isinstance(arg, ast.Name):
                target = _resolve_local_name(g, fi, arg.id)
                if target is not None:
                    for ctx in (RETRY_BOUNDARY, RETRIED_FRAME):
                        # depth0: the handed function IS the retried
                        # frame, and it is not a boundary method LMR008
                        # would have checked — its own raises count
                        yield _Seed(ctx, target, None,
                                    f"{fi.rel}:{fi.qual}", depth0=True)


def _resolve_local_name(g: CallGraph, fi: FunctionInfo,
                        name: str) -> Optional[str]:
    nested = f"{fi.rel}::{fi.qual}.{name}"
    if nested in g.functions:
        return nested
    mod = g.modules.get(fi.rel)
    if mod and name in mod.functions:
        return mod.functions[name]
    return None


# -- propagation -------------------------------------------------------------

@dataclasses.dataclass
class Reached:
    fid: str
    context: str
    depth: int
    chain: Tuple[Tuple[str, int], ...]   # ((fid, call line), ...) hops
    origin: str
    # region seeds: only these lines of the function run under the
    # context (the locked region / the RetryPolicy.call lambda) — the
    # depth-0 checks scope to them; None = the whole body
    lines: Optional[Set[int]] = None
    # depth-0 checks apply to this function itself (see _Seed.depth0)
    depth0: bool = False


def propagate(g: CallGraph,
              seeds: Optional[List[_Seed]] = None) -> List[Reached]:
    """BFS each context over the graph; first (shortest) chain wins.
    Line-restricted (region) seeds contribute a depth-0 entry scoped to
    the region's own lines plus propagation through its call sites."""
    if seeds is None:
        seeds = _collect_seeds(g)
    reached: Dict[Tuple[str, str], Reached] = {}
    entries: List[Reached] = []          # line-scoped depth-0 regions
    frontier: List[Reached] = []
    for s in seeds:
        key = (s.context, s.fid)
        r = Reached(s.fid, s.context, 0, (), s.origin, s.lines, s.depth0)
        if s.lines is None:
            if key not in reached:
                reached[key] = r
                frontier.append(r)
            elif s.depth0 and not reached[key].depth0 \
                    and reached[key].depth == 0:
                reached[key].depth0 = True
        else:
            entries.append(r)
        follow = _FOLLOW[s.context]
        for e in g.callees(s.fid):
            if s.lines is not None and e.line not in s.lines:
                continue
            if e.kind not in follow:
                continue
            for callee in _expand(g, e):
                ckey = (s.context, callee)
                if ckey in reached:
                    continue
                nr = Reached(callee, s.context, 1,
                             ((s.fid, e.line),), s.origin)
                reached[ckey] = nr
                frontier.append(nr)
    i = 0
    while i < len(frontier):
        cur = frontier[i]
        i += 1
        if cur.depth >= _MAX_DEPTH:
            continue
        follow = _FOLLOW[cur.context]
        for e in g.callees(cur.fid):
            if e.kind not in follow:
                continue
            for callee in _expand(g, e):
                key = (cur.context, callee)
                if key in reached:
                    continue
                nr = Reached(callee, cur.context, cur.depth + 1,
                             cur.chain + ((cur.fid, e.line),), cur.origin)
                reached[key] = nr
                frontier.append(nr)
    return list(reached.values()) + entries


def _expand(g: CallGraph, e: Edge) -> Iterable[str]:
    if e.kind == "interface":
        meth = e.callee[len("<iface:"):-1]
        return g.iface_targets(meth)
    if e.callee.startswith("<"):
        return ()
    return (e.callee,) if e.callee in g.functions else ()


# -- violation checks --------------------------------------------------------

def _fmt_chain(g: CallGraph, r: Reached) -> str:
    hops = []
    for fid, line in r.chain[-4:]:
        fi = g.functions.get(fid)
        hops.append(f"{fi.qual if fi else fid}:{line}")
    via = " -> ".join(hops)
    return f"reached from {r.origin}" + (f" via {via}" if via else "")


def _finding(g: CallGraph, rule: str, fi: FunctionInfo, node: ast.AST,
             r: Reached, what: str) -> Finding:
    return Finding(rule, "error", fi.rel, getattr(node, "lineno", fi.lineno),
                   getattr(node, "col_offset", 0),
                   f"{what} in {fi.qual}() runs under {r.context} — "
                   f"{_fmt_chain(g, r)}")


def _own_call_nodes(fi: FunctionInfo,
                    r: Optional[Reached] = None) -> Iterable[ast.Call]:
    """The function's own calls (lambdas included — the call graph
    attributes them to the enclosing frame — nested defs not), scoped
    to the region lines when ``r`` carries a restriction."""
    stack = list(fi.node.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.Call) and not (
                r is not None and r.lines is not None
                and n.lineno not in r.lines):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _check_flock(g: CallGraph, fi: FunctionInfo,
                 r: Reached) -> Iterable[Finding]:
    for call in _own_call_nodes(fi, r):
        c = _r._chain(call.func)
        if not c:
            continue
        if r.depth >= 1:
            if c[0] in ("open", "print", "input") and len(c) == 1:
                yield _finding(g, "LMR013", fi, call, r,
                               f"{c[0]}()")
                continue
            if c[0] in _r._IDX_DENY_ROOTS:
                yield _finding(g, "LMR013", fi, call, r,
                               f"{'.'.join(c)}")
                continue
            if (c[0] == "os" and len(c) > 1
                    and c[1] not in _r._IDX_OS_ALLOWED and c[1] != "path"):
                yield _finding(g, "LMR013", fi, call, r, f"os.{c[1]}")
                continue
            if len(c) == 1 and c[0] in fi.params:
                yield _finding(g, "LMR013", fi, call, r,
                               f"call to parameter {c[0]!r} (user "
                               "callback)")
                continue
        if c == ("time", "sleep") and r.depth >= 1:
            # depth 0 is LMR011's anchor (bare sleep in coord/)
            yield _finding(g, "LMR013", fi, call, r, "time.sleep()")
        elif (len(c) >= 2 and c[-1] in _DATA_PLANE_CALLS
                and c[0] != "os"
                and not (len(c) == 2 and c[0] == "self")):
            # store.lines(...) / self.store.lines(...): blocking
            # data-plane IO through the storage interface. Bare
            # self.lines() is the object's own method — the method
            # edge already propagates the context into it; os.* is
            # the fd-local/metadata surface LMR002 arbitrates.
            yield _finding(g, "LMR013", fi, call, r,
                           f"store data-plane call {'.'.join(c)}()")


def _check_retry_raises(g: CallGraph, fi: FunctionInfo,
                        r: Reached) -> Iterable[Finding]:
    if r.depth < 1 and not r.depth0:
        return        # boundary-method bodies are LMR008's anchor
    for n in _r._own_walk(list(fi.node.body)):
        if not isinstance(n, ast.Raise) or n.exc is None:
            continue
        exc = n.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        c = _r._chain(exc)
        if c and c[-1] in _r._UNCLASSIFIED_RAISES:
            yield _finding(g, "LMR014", fi, n, r,
                           f"raise {c[-1]}")


def _check_nonreplayable(g: CallGraph, fi: FunctionInfo,
                         r: Reached) -> Iterable[Finding]:
    for call in _own_call_nodes(fi, r):
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name in _NON_REPLAYABLE:
            yield _finding(g, "LMR016", fi, call, r,
                           f"non-replayable RPC {name}()")


def _check_replay(g: CallGraph, fi: FunctionInfo,
                  r: Reached) -> Iterable[Finding]:
    if r.depth < 1 or fi.rel.startswith("trace/"):
        return        # depth 0 / trace-resident reads are LMR004/LMR010
    for call in _own_call_nodes(fi):
        c = _r._chain(call.func)
        if not c:
            continue
        if len(c) == 2 and c[0] == "time" and c[1] in _CLOCK_CALLS:
            yield _finding(g, "LMR015", fi, call, r,
                           f"time.{c[1]}()")
        elif any(c[:len(root)] == root for root in _RNG_ROOTS) \
                and len(c) > 1:
            yield _finding(g, "LMR015", fi, call, r,
                           f"{'.'.join(c)}")


def _check_jit(g: CallGraph, fi: FunctionInfo,
               r: Reached) -> Iterable[Finding]:
    if r.depth < 1:
        return                          # depth 0 is LMR007's anchor
    for call in _own_call_nodes(fi):
        c = _r._chain(call.func)
        if not c:
            continue
        if len(c) == 1 and c[0] in ("open", "input", "print"):
            yield _finding(g, "LMR017", fi, call, r, f"{c[0]}()")
        elif any(c[:len(root)] == root for root in _r._IMPURE_ROOTS):
            yield _finding(g, "LMR017", fi, call, r, f"{'.'.join(c)}")


_CHECKS = {
    HOLDS_FLOCK: (_check_flock,),
    RETRY_BOUNDARY: (_check_retry_raises,),
    RETRIED_FRAME: (_check_nonreplayable,),
    REPLAY_DET: (_check_replay,),
    JIT_TRACE: (_check_jit,),
}


# -- driver ------------------------------------------------------------------

@dataclasses.dataclass
class DeepResult:
    findings: List[Finding]          # post-suppression
    raw: List[Finding]               # pre-suppression (audit input)
    graph: CallGraph
    reached: int
    wall_s: float


def analyze(paths: Optional[Sequence[str]] = None,
            baseline: Optional[str] = None,
            graph: Optional[CallGraph] = None) -> DeepResult:
    """The full deep pass: graph, contexts, rules, suppression."""
    t0 = time.perf_counter()
    if graph is None:
        graph = build_callgraph(paths)
    reached = propagate(graph)
    raw: List[Finding] = []
    for r in reached:
        fi = graph.functions.get(r.fid)
        if fi is None or fi.qual == "<module>":
            continue
        for check in _CHECKS[r.context]:
            raw.extend(check(graph, fi, r))
    # one finding per (path, line, rule): overlapping chains into the
    # same defect collapse to the shortest-chain report
    best: Dict[tuple, Finding] = {}
    for f in raw:
        best.setdefault(f.key(), f)
    raw = sorted(best.values(), key=Finding.key)
    base = load_baseline(baseline)
    out = []
    for f in raw:
        if f.rule in _line_disables(graph, f.path, f.line):
            continue
        if any(_baseline_match(e, f) for e in base):
            continue
        out.append(f)
    return DeepResult(out, raw, graph, len(reached),
                      time.perf_counter() - t0)


def _line_disables(g: CallGraph, rel: str, lineno: int) -> Set[str]:
    m = g.modules.get(rel)
    if m is None:
        return set()
    return _line_disables_in(m.lines, lineno)


def run_deep(paths: Optional[Sequence[str]] = None,
             baseline: Optional[str] = None) -> List[Finding]:
    """Deep findings surviving suppression — the CLI/gate entry point."""
    return analyze(paths, baseline).findings


def deep_rule_catalog() -> List[Dict[str, str]]:
    return [{"id": d.id, "severity": d.severity, "title": d.title,
             "rationale": d.rationale, "paths": list(d.paths)}
            for d in DEEP_RULES]


def utest() -> None:
    """Self-test: each deep rule re-finds a seeded helper-indirection
    violation its per-function sibling provably misses, clean twins
    pass, and the real package analyzes clean."""
    from lua_mapreduce_tpu.analysis.lint import run_lint

    flock_fix = ("coord/fx.py", (
        "import json, os, time\n"
        "class Idx:\n"
        "    def claim(self):\n"
        "        fd = self._open_locked()\n"
        "        try:\n"
        "            return self._load_doc(fd)\n"
        "        finally:\n"
        "            os.close(fd)\n"
        "    def _load_doc(self, fd):\n"
        "        doc = json.load(open('sidecar'))\n"
        "        time.sleep(0.1)\n"
        "        return doc\n"
    ))
    g = CallGraph.from_sources([flock_fix])
    res = analyze(graph=g, baseline="/nonexistent")
    rules_hit = sorted({f.rule for f in res.findings})
    assert "LMR013" in rules_hit, res.findings
    assert all(f.line in (10, 11) for f in res.findings
               if f.rule == "LMR013")

    retry_fix = ("store/fx.py", (
        "class MyStore:\n"
        "    def read_range(self, name, offset, length):\n"
        "        return self._fetch(name)\n"
        "    def _fetch(self, name):\n"
        "        raise RuntimeError('backend hiccup')\n"
        "    def build(self, name):\n"
        "        self._publish(name)\n"
        "    def _publish(self, name):\n"
        "        self.js.insert_jobs('ns', [])\n"
    ))
    g = CallGraph.from_sources([retry_fix])
    got = {f.rule for f in analyze(graph=g,
                                   baseline="/nonexistent").findings}
    assert {"LMR014", "LMR016"} <= got, got

    replay_fix = ("coord/cx.py", (
        "import time\n"
        "class S:\n"
        "    def stamp(self):\n"
        "        with self._lock:\n"
        "            self.t = self._now()\n"
        "    def _now(self):\n"
        "        return time.time()\n"
    ))
    g = CallGraph.from_sources([replay_fix])
    got = [f for f in analyze(graph=g, baseline="/nonexistent").findings]
    assert [f.rule for f in got] == ["LMR015"] and got[0].line == 7, got

    jit_fix = ("ops/ox.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return x + _noise(3)\n"
        "def _noise(n):\n"
        "    return np.random.randn(n)\n"
    ))
    g = CallGraph.from_sources([jit_fix])
    got = [f for f in analyze(graph=g, baseline="/nonexistent").findings]
    assert [f.rule for f in got] == ["LMR017"] and got[0].line == 7, got

    # the acceptance pair: the per-function pass misses ALL of these
    for rel, src in (flock_fix, retry_fix, replay_fix, jit_fix):
        import tempfile, os as _os
        with tempfile.TemporaryDirectory() as d:
            sub = _os.path.join(d, _os.path.dirname(rel))
            _os.makedirs(sub, exist_ok=True)
            p = _os.path.join(d, rel)
            with open(p, "w") as fh:
                fh.write(src)
            per_fn = run_lint([d], baseline="/nonexistent")
            assert [f for f in per_fn
                    if f.rule in ("LMR002", "LMR004", "LMR007",
                                  "LMR008")] == [], (rel, per_fn)

    # clean twins: hoisted clock, classified raise, pure helper
    g = CallGraph.from_sources([
        ("coord/clean.py", (
            "import time\n"
            "class S:\n"
            "    def stamp(self):\n"
            "        now = self._now()\n"
            "        with self._lock:\n"
            "            self.t = now\n"
            "    def _now(self):\n"
            "        return time.time()\n"
        )),
        ("store/clean.py", (
            "class S:\n"
            "    def read_range(self, name, offset, length):\n"
            "        return self._fetch(name)\n"
            "    def _fetch(self, name):\n"
            "        raise TransientStoreError('blip')\n"
        )),
    ])
    assert analyze(graph=g, baseline="/nonexistent").findings == []

    # inline suppression holds for deep findings too
    g = CallGraph.from_sources([(
        "coord/sup.py",
        replay_fix[1].replace("cx", "sup").replace(
            "return time.time()",
            "return time.time()  # lmr: disable=LMR015"),
    )])
    assert analyze(graph=g, baseline="/nonexistent").findings == []
