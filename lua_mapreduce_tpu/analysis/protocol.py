"""Exhaustive small-scope model checker for the lease protocol.

The JobStore claim/commit/release/heartbeat/requeue/scavenge machine is
the one part of the system whose correctness cannot be established by
running it: the dangerous behaviors are interleavings, and the SIGKILL
churn suites only sample them.  This module extracts that protocol into
an explicit transition system and enumerates EVERY interleaving of a
small configuration (2-3 workers × 2-4 jobs), checking safety
invariants in each reached state:

- **legal transitions** — every per-job status edge is one the protocol
  defines (WAITING→RUNNING, RUNNING→{FINISHED,BROKEN,WAITING},
  FINISHED→{WRITTEN,BROKEN}, BROKEN→{RUNNING,FAILED,BROKEN}; WRITTEN
  and FAILED are terminal);
- **repetitions monotone** — a retry counter never decreases;
- **no double commit** — at most one successful commit per job, ever;
- **commit ownership** — a commit lands only for the worker that holds
  the job's CURRENT claim (the CAS the protocol relies on);
- **no lost or stuck job** — in every quiescent state with a live
  worker, every job is WRITTEN or FAILED (in particular: never parked
  FINISHED+unclaimed, the kill-between-FINISHED-and-WRITTEN gap).

Time is a deterministic VIRTUAL CLOCK: every lease carries an age that
a global ``tick`` transition advances; at ``stale_age`` the lease is
eligible for the scavenger's requeue, and a heartbeat resets it.  This
makes "the worker went silent" an explicit, enumerable event instead of
a sleep in a stress test.

The model mirrors the shipped protocol operation-for-operation:
``claim_batch`` (one atomic pass, lowest ids first, exactly like both
index engines), the default two-step per-job commit
(RUNNING→FINISHED→WRITTEN CASed on ownership, engine/jobstore.py
``commit_batch``), the failure path (commit done prefix, release the
unstarted tail without a repetition bump, mark the failing job BROKEN),
batched heartbeats (live only while job bodies run — the worker's beat
thread stops before the success-path commit but covers the
failure-path one, mirroring Worker._execute_batch), stale requeue
(RUNNING|FINISHED), scavenge (BROKEN with reps ≥ max_retries → FAILED),
and worker death at ANY step.

On a violation the checker returns the shortest trace (BFS), and
:func:`replay_trace` replays it against a real ``MemJobStore`` /
``FileJobStore``: a trace from the correct model reproduces
step-for-step and lands in the same final state; a trace from a seeded
bug model DIVERGES at the exact store operation whose CAS closes the
race — which is the confirmation that the real protocol is guarded
where the model says it must be.

**Replica-aware recovery (DESIGN §20).** With
``ModelConfig(data_loss_budget=N)`` each job record carries the state
of its published output's replica set (intact / under-replicated /
every-copy-lost; environment loss events are budget-bounded so the
space stays finite), and the scavenger gains the reconstruct-vs-requeue
edge: ``repair`` heals an under-replicated output WITHOUT touching job
state, and ``rerun_requeue`` CASes a WRITTEN producer whose output is
wholly lost back to WAITING — the one legal WRITTEN→WAITING edge, it
must charge NO repetition (the loss is not the job's fault) and it
opens a new commit generation (the re-run's commit is not a double
commit). Two new invariants ride the existing set: the no-stranded-data
rule (quiescent with a live worker ⇒ no WRITTEN job whose output is
wholly lost — the reduce phase would wedge on it) and the
zero-charge rule on the requeue edge itself.

**Speculative execution (DESIGN §21).** With
``ModelConfig(allow_spec=True)`` each job record carries its
duplicate-lease state (none / OPEN / taken-by-worker-w) and the system
gains the speculation edges, op-for-op with the shipped protocol: the
detector's ``speculate`` (RUNNING ∧ no-speculation → OPEN — a pure
marker, no status or repetition change), an idle worker's
``claim_spec`` (OPEN → taken, never the job's own claimant, lowest id
first — the same scan order as both index engines), the clone's body +
two-step commit racing the original's (ownership satisfied by EITHER
the claimant or the shadow holder; the status CAS arbitrates
first-commit-wins, so the loser's commit fails and degrades to
``spec_cancel`` — a pure shadow-lease dissolution), the clone's
revocation/failure edge (``spec_cancel`` from any clone stage), and
shadow-lease dissolution on every unlease transition (release,
requeue, mark-broken — a re-claimed job must never be committable by a
stale clone). The full invariant set rides along unchanged; the ones
speculation exists to threaten — no-double-commit and
reps-monotone — are checked on every interleaving of original vs
clone commit, death at any step included.

**Elastic join/leave (DESIGN §29).** With ``ModelConfig(elastic=True)``
the pool itself becomes part of the state: the last worker starts
ABSENT (not yet spawned — the controller's scale-up capacity) and may
``join`` at any step, and any IDLE worker may ``retire`` (the
controller's scale-down) into a terminal GONE mode. Both edges must be
state-transparent on every job, and retire carries the
no-lease-abandoned invariant: a worker may leave only while it owns no
RUNNING/FINISHED lease — exactly the graceful-retire contract
``FleetSupervisor`` implements by bounding a member's lifetime so it
exits AFTER its current lease commits. The seeded bug
(``elastic_retire_holds_lease``) lets a mid-lease worker retire — the
scale-down that strands its leased jobs until the scavenger requeues
them with an undeserved repetition charge — and the checker re-finds
it as a direct invariant hit on the retire step.

Seedable bugs (``ModelConfig(bug=...)``):

- ``"commit_skips_owner_cas"`` — commit checks status but not
  ownership: the historical commit-racing-scavenger-requeue race (a
  stale worker retires a job the scavenger already handed to someone
  else);
- ``"requeue_ignores_finished"`` — the scavenger skips FINISHED
  leases: a worker killed between its FINISHED and WRITTEN transitions
  wedges the barrier forever;
- ``"scavenge_skips_lost_data"`` — the scavenger repairs
  under-replicated outputs but never requeues wholly-lost ones: the
  reduce phase waits forever on data nobody will regenerate
  (requires ``data_loss_budget > 0``);
- ``"lost_requeue_skips_written_cas"`` — the lost-data requeue fires
  without the expect=(WRITTEN,) status CAS: it can yank a job another
  worker is mid-commit on (the real ``Server._requeue_maps`` carries
  exactly that CAS; requires ``data_loss_budget > 0``);
- ``"spec_commit_skips_winner_cas"`` — the loser's commit skips the
  winner's status CAS: a clone (or original) that lost the
  first-commit-wins race lands its commit anyway — the double-commit /
  illegal-WRITTEN-edge shape the one-transition arbitration exists to
  prevent (requires ``allow_spec=True``);
- ``"lost_wakeup_no_fallback"`` — a sleeping worker wakes ONLY on
  notification, with no timeout fallback: one lost notification
  (the budget-bounded ``lose_notify`` environment event) parks the
  worker forever and claimable jobs strand — the hang the Waiter's
  degradation ladder exists to prevent (requires
  ``allow_notify=True``);
- ``"coded_decode_lost_stripe"`` — the scavenger's repair rung decodes
  stripes with fewer than k surviving blocks: data is conjured from
  nothing, masking a loss the producer must regenerate (requires
  ``coded=True`` and ``data_loss_budget ≥ 1``);
- ``"coded_requeue_skips_decode"`` — the scavenger treats ANY block
  loss as total loss (never tries the decode rung) AND trusts its own
  stale classification, firing the producer requeue without the
  expect=(WRITTEN,) status CAS: it yanks jobs mid-commit exactly like
  the replica-plane CAS bug, but now on stripes that were perfectly
  decodable (requires ``coded=True`` and ``data_loss_budget ≥ 1``);
- ``"double_leader"`` — a standby's acquire overwrites a LIVE lease
  without the version CAS, reusing the stored epoch: two coordinators
  hold overlapping validity windows and both pass the fence — the
  split-brain shape the CAS + epoch bump exist to prevent (requires
  ``ha=True``);
- ``"zombie_leader_write"`` — a deposed leader's mutation skips the
  fencing guard: the stale write lands after a takeover bumped the
  epoch — the corruption ``FencedJobStore`` turns into a permanent
  ``StaleLeaderError`` (requires ``ha=True``).

**Watch/notify wakeups (DESIGN §23).** With
``ModelConfig(allow_notify=True)`` each worker may go to SLEEP when its
poll finds nothing claimable (arming the Waiter), and the state carries
one pending-wakeup bit per worker: every claimable-work producer —
release, stale requeue, mark-broken, the detector's speculate, the
lost-data requeue — broadcasts the bits (the real channels are a bus),
a sleeping worker consumes its bit via ``notify_wake``, ``timeout_wake``
is always enabled (the poll fallback), and the budget-bounded
``lose_notify`` adversary clears a pending bit — the lost-notification
event. Three properties ride the existing invariant set: sleep/wake
edges are state-transparent on every job (a stale or duplicate wakeup
is a no-op by construction), the full lifecycle invariants survive
every sleep/wake interleaving, and in the correct model no quiescent
state strands a claimable job on a sleeping worker — delete the
timeout fallback (the seeded bug) and exactly that hang is re-found.

**Erasure-coded recovery (DESIGN §27).** With ``ModelConfig(coded=True)``
the data plane models a k+m stripe instead of r whole copies: the
budget-bounded ``lose_parity`` event degrades a published output ONE
BLOCK at a time (intact → decodable-but-under-width → below-k lost —
it takes m+1 events to kill a stripe where ``lose_replica`` killed a
copy outright), while ``lose_all`` keeps the blackout/dead-backend
shape. The scavenger's ladder is unchanged in form — ``repair`` now
means decode-from-survivors + re-encode back to full stripe width
(job state untouched), ``rerun_requeue`` stays the last rung — but
gains the DECODE-CONSERVATION invariant: no repair step may take a
job's output from below-k-survivors to readable. Decode is linear
algebra, not necromancy; only a producer re-run regenerates a stripe
that lost more than m blocks, and a scavenger that claims otherwise is
silently serving garbage. Two seeded bugs live on exactly these edges
(``coded_decode_lost_stripe``, ``coded_requeue_skips_decode``); the
second one's shortest trace replays against BOTH real stores and
diverges at the WRITTEN expectation of the requeue CAS.

**Leader lease / fencing (DESIGN §31).** With ``ModelConfig(ha=True)``
the coordinator itself joins the state: two contending coordinators
over one CAS lease document ``(epoch, holder, age)`` plus each
coordinator's BELIEVED epoch (0 = standby). The lease has its own
virtual clock (``lease_tick`` — a leader may die while every job still
WAITS, so lease expiry cannot ride the job tick), and the edges are
op-for-op the shipped ``sched/lease.py``: a standby's ``acquire``
(version CAS, legal only on a free/released or EXPIRED lease; the
epoch bumps on every transfer so validity windows never overlap), the
leader's ``renew`` (CAS resets the clock; failure = fenced back to
standby, permanently — ``StaleLeaderError`` is never retried),
``lead_release`` (clean handback), and ``lead_write`` — a guarded
server-side mutation that lands iff the believed epoch IS the lease
epoch (past the local deadline the landing rides the inline renewal
CAS, exactly ``FencedJobStore._check`` → ``validate`` → ``renew``).
All HA edges are environment (coordinator churn is never job
progress) and state-transparent on every job — who leads is invisible
to the claim protocol, because workers are leader-agnostic. Two
invariants pin the design down: at most one coordinator may ever
believe it holds the CURRENT epoch (no double leader), and no write
may land from a coordinator whose believed epoch is stale (no zombie
write). The seeded bugs (``double_leader``, ``zombie_leader_write``)
break exactly those, and their shortest traces replay against the
real ``LeaderLease`` + ``FencedJobStore`` over a real store, diverging
at the acquire CAS / the fencing guard respectively.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

from lua_mapreduce_tpu.core.constants import Status

_WAIT = int(Status.WAITING)
_RUN = int(Status.RUNNING)
_BRK = int(Status.BROKEN)
_FIN = int(Status.FINISHED)
_WRI = int(Status.WRITTEN)
_FAI = int(Status.FAILED)

_ALLOWED_EDGES = {
    _WAIT: {_RUN},
    _RUN: {_FIN, _BRK, _WAIT},
    _FIN: {_WRI, _BRK},
    _BRK: {_RUN, _FAI, _BRK},
    _WRI: set(),
    _FAI: set(),
}

KNOWN_BUGS = ("commit_skips_owner_cas", "requeue_ignores_finished",
              "scavenge_skips_lost_data", "lost_requeue_skips_written_cas",
              "spec_commit_skips_winner_cas", "lost_wakeup_no_fallback",
              "coded_decode_lost_stripe", "coded_requeue_skips_decode",
              "elastic_retire_holds_lease", "zombie_leader_write",
              "double_leader")

# bugs living on the replica-recovery edge need loss events to surface
LOSS_BUGS = ("scavenge_skips_lost_data", "lost_requeue_skips_written_cas")

# bugs living on the erasure-coded decode ladder need the coded data
# plane (block-at-a-time loss) plus a loss budget to be reachable
CODED_BUGS = ("coded_decode_lost_stripe", "coded_requeue_skips_decode")

# bugs living on the duplicate-lease edge need speculation enabled
SPEC_BUGS = ("spec_commit_skips_winner_cas",)

# bugs living on the watch/notify edge need the wakeup layer enabled
# (and a loss budget — a never-lost notification always wakes)
NOTIFY_BUGS = ("lost_wakeup_no_fallback",)

# bugs living on the elastic join/leave edge need the elastic pool
ELASTIC_BUGS = ("elastic_retire_holds_lease",)

# bugs living on the leader-lease/fencing edge need the HA layer
HA_BUGS = ("zombie_leader_write", "double_leader")

# elastic join/leave must be state-transparent on every job: scaling
# the pool may never change a status, an owner, or a retry budget —
# the semantics-neutrality rule of DESIGN §29
_ELASTIC_PURE_OPS = frozenset({"join", "retire"})

# notify/wait edges must be state-transparent on every job: going to
# sleep, waking (by notification or timeout), and losing a wakeup may
# never change a status or a retry budget — the stale-wakeup-is-a-no-op
# rule of DESIGN §23
_WAIT_PURE_OPS = frozenset({"sleep", "notify_wake", "timeout_wake",
                            "lose_notify"})

# job spec-lease state: none / OPEN / taken-by-worker-w (w = value - 10)
_SP_NONE = 0
_SP_OPEN = 1
_SP_TAKEN0 = 10     # taken by worker w encodes as _SP_TAKEN0 + w

# labels that must be state-transparent on the job (no status or
# repetition change) — the zero-charge rule of the speculation edges
_SPEC_PURE_OPS = frozenset({"speculate", "claim_spec", "spec_cancel"})

# replica-set state of a job's published output.  Under coded=True the
# same ladder reads as stripe survivorship: INTACT = full k+m width,
# UNDER = ≥k survivors (readable via decode, repairable by re-encode),
# LOST = below k survivors (only a producer re-run regenerates it)
_D_LOST = 0      # every copy gone — only a producer re-run regenerates
_D_UNDER = 1     # readable, but below full r-way redundancy
_D_INTACT = 2    # full redundancy

# environment events: enumerable, but never count as protocol progress
# (join/retire are the controller's capacity choices — WHEN capacity
# arrives or leaves is the environment's pick, like death). The HA
# coordinator-plane edges are environment too: who leads (and when a
# zombie probes a write) never constitutes JOB progress, so a state
# whose only options are coordinator churn is still quiescent for the
# lost-job invariants.
_ENV_OPS = frozenset({"die", "lose_replica", "lose_all", "lose_parity",
                      "lose_notify", "join", "retire", "lease_tick",
                      "acquire", "renew", "lead_release", "lead_write"})

# HA small-scope bounds: two contending coordinators, epochs saturate
# (three acquisitions are enough to exhibit election, succession, AND
# expiry takeover; an unbounded epoch would make the space infinite)
_N_COORDS = 2
_EPOCH_CAP = 3


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    n_workers: int = 2
    n_jobs: int = 3
    batch_k: int = 2
    max_retries: int = 2
    stale_age: int = 1
    allow_death: bool = True
    allow_fail: bool = False
    data_loss_budget: int = 0
    coded: bool = False
    allow_spec: bool = False
    allow_notify: bool = False
    notify_loss_budget: int = 1
    elastic: bool = False
    ha: bool = False
    bug: Optional[str] = None

    def __post_init__(self):
        if not (1 <= self.n_workers <= 3 and 1 <= self.n_jobs <= 4):
            raise ValueError("small-scope checker: ≤3 workers, ≤4 jobs")
        if not (1 <= self.batch_k <= self.n_jobs):
            raise ValueError(
                f"batch_k={self.batch_k} must be in [1, n_jobs]: a k<1 "
                "worker never claims, which quiesces with every job "
                "WAITING and would read as a fake lost-job violation")
        if self.max_retries < 1 or self.stale_age < 1:
            raise ValueError("max_retries and stale_age must be ≥ 1")
        if not (0 <= self.data_loss_budget <= 3):
            raise ValueError("data_loss_budget must be in [0, 3] "
                             "(small-scope: each loss event multiplies "
                             "the space)")
        if self.bug is not None and self.bug not in KNOWN_BUGS:
            raise ValueError(f"unknown bug {self.bug!r}; known: "
                             f"{KNOWN_BUGS}")
        if self.bug in LOSS_BUGS and self.data_loss_budget < 1:
            raise ValueError(f"bug {self.bug!r} lives on the "
                             "replica-recovery edge: it needs "
                             "data_loss_budget ≥ 1 to be reachable")
        if self.coded and self.data_loss_budget < 1:
            raise ValueError("coded=True without a data_loss_budget is "
                             "inert: lose_parity is the only edge the "
                             "coded plane adds, and it is budget-gated")
        if self.bug in CODED_BUGS and (
                not self.coded or self.data_loss_budget < 1):
            raise ValueError(f"bug {self.bug!r} lives on the "
                             "erasure-coded decode ladder: it needs "
                             "coded=True and data_loss_budget ≥ 1 to "
                             "be reachable")
        if self.bug in SPEC_BUGS and not self.allow_spec:
            raise ValueError(f"bug {self.bug!r} lives on the "
                             "duplicate-lease edge: it needs "
                             "allow_spec=True to be reachable")
        if not (0 <= self.notify_loss_budget <= 3):
            raise ValueError("notify_loss_budget must be in [0, 3]")
        if self.bug in NOTIFY_BUGS and (
                not self.allow_notify or self.notify_loss_budget < 1):
            raise ValueError(f"bug {self.bug!r} lives on the watch/notify "
                             "edge: it needs allow_notify=True and "
                             "notify_loss_budget ≥ 1 to be reachable")
        if self.elastic and self.n_workers < 2:
            raise ValueError("elastic=True needs ≥ 2 workers: the last "
                             "worker starts ABSENT (scale-up capacity), "
                             "so a 1-worker pool would have nobody to "
                             "run jobs before the join")
        if self.bug in ELASTIC_BUGS and not self.elastic:
            raise ValueError(f"bug {self.bug!r} lives on the elastic "
                             "join/leave edge: it needs elastic=True "
                             "to be reachable")
        if self.bug in HA_BUGS and not self.ha:
            raise ValueError(f"bug {self.bug!r} lives on the leader-"
                             "lease/fencing edge: it needs ha=True to "
                             "be reachable")
        if self.allow_spec and self.n_workers < 2:
            raise ValueError("allow_spec needs ≥ 2 workers: a shadow "
                             "lease is never taken by the job's own "
                             "claimant, so a 1-worker box has no "
                             "reachable speculation edge")


# Job record: (status, reps, owner, age, data, spec).  owner is 0
# (none) or worker-index+1; age counts virtual ticks since the last
# liveness signal and saturates at stale_age; data is the replica-set
# state of the job's published output (_D_INTACT until a budgeted loss
# event, restored by repair or by the re-run's commit); spec is the
# duplicate-lease state (_SP_NONE | _SP_OPEN | _SP_TAKEN0 + w).  State:
# (jobs, workers, commits, loss_budget, wakes, notify_budget) — wakes
# is one pending-wakeup bit per worker (set broadcast-style by every
# claimable-work producer: release, requeue, mark-broken, speculate,
# lost-data requeue; consumed by notify_wake; cleared by the
# budget-bounded lose_notify environment event), notify_budget bounds
# the lost-notification events.  Worker modes:
#   ("I",)                                       idle (polling)
#   ("S",)                                       asleep awaiting wakeup
#   ("D",)                                       dead
#   ("A",)                                       absent (elastic: not yet
#                                                joined — scale-up slot)
#   ("G",)                                       gone (elastic: retired)
#   ("R", leased, pos, done)                     executing job bodies
#   ("C", leased, entries, i, phase, tail, brk)  committing entry i
#   ("L", leased, tail, brk)                     releasing unstarted tail
#   ("K", leased, brk)                           marking the failed job
#   ("SR", j)                                    executing a clone body
#   ("SC", j, phase)                             clone committing (2-step)
# brk is the failing job id (failure path) or -1 (clean commit).

_IDLE = ("I",)
_DEAD = ("D",)
_ABSENT = ("A",)
_GONE = ("G",)


@dataclasses.dataclass
class Violation:
    message: str
    trace: List[tuple]
    state: tuple


@dataclasses.dataclass
class CheckResult:
    config: ModelConfig
    states: int
    transitions: int
    quiescent: int
    wall_s: float
    violation: Optional[Violation]

    @property
    def ok(self) -> bool:
        return self.violation is None


class LeaseModel:
    """The transition system: enumerate and apply protocol steps."""

    def __init__(self, config: ModelConfig):
        self.cfg = config
        self._rep_cap = config.max_retries + 1   # saturate: finite space

    def initial(self) -> tuple:
        jobs = tuple((_WAIT, 0, 0, 0, _D_INTACT, _SP_NONE)
                     for _ in range(self.cfg.n_jobs))
        workers = tuple(_IDLE for _ in range(self.cfg.n_workers))
        if self.cfg.elastic:
            # the last worker is the controller's scale-up capacity:
            # absent until a budget-free "join" brings it into the pool
            workers = workers[:-1] + (_ABSENT,)
        commits = (0,) * self.cfg.n_jobs
        # the leader-lease plane (DESIGN §31): (epoch, holder, age,
        # believed_0, believed_1) — holder is 0 (free/released) or
        # coordinator-index+1; age counts lease_ticks since the last
        # renewal; believed_c is the epoch coordinator c thinks it
        # holds (0 = standby). A constant zero tuple when ha is off.
        lease = (0, 0, 0) + (0,) * _N_COORDS
        return (jobs, workers, commits, self.cfg.data_loss_budget,
                (0,) * self.cfg.n_workers,
                self.cfg.notify_loss_budget if self.cfg.allow_notify else 0,
                lease)

    # -- per-transition effects (each is ONE atomic store op or one
    # worker-local step, which is exactly the interleaving granularity
    # the locks give the real system) -----------------------------------

    def _sat(self, reps: int) -> int:
        return min(reps, self._rep_cap)

    def transitions(self, state: tuple) -> List[Tuple[tuple, tuple]]:
        """[(label, next_state), ...] — every enabled step."""
        jobs, workers, commits, budget, wakes, nbudget, ha_st = state
        out: List[Tuple[tuple, tuple]] = []
        cfg = self.cfg

        def repl_job(j, rec):
            return tuple(rec if i == j else r for i, r in enumerate(jobs))

        def repl_w(w, mode, njobs=None, ncommits=None, nwakes=None):
            nw = tuple(mode if i == w else m for i, m in enumerate(workers))
            return ((jobs if njobs is None else njobs), nw,
                    (commits if ncommits is None else ncommits), budget,
                    (wakes if nwakes is None else nwakes), nbudget)

        def woken(produced) -> tuple:
            """Wake bits after a claimable-work producer: the notify
            bus is a broadcast, so every worker's pending bit sets —
            exactly what release/requeue/broken/speculate do through
            the real channels (DESIGN §23)."""
            if cfg.allow_notify and produced:
                return (1,) * len(workers)
            return wakes

        for w, mode in enumerate(workers):
            kind = mode[0]
            if kind in ("D", "G"):
                continue
            if kind == "A":
                # elastic scale-up: the absent worker joins the pool —
                # a pure capacity event, no job is touched
                out.append((("join", w), repl_w(w, _IDLE)))
                continue
            if cfg.allow_death:
                out.append((("die", w), repl_w(w, _DEAD)))
            if cfg.elastic and kind == "I":
                # elastic scale-down: an IDLE worker retires — the
                # graceful-exit contract (it owns no lease here by
                # construction; the step invariant verifies exactly
                # that, and the seeded bug below violates it)
                out.append((("retire", w), repl_w(w, _GONE)))
            if (cfg.bug == "elastic_retire_holds_lease"
                    and kind in ("R", "C")):
                # the seeded bug: the supervisor retires a member
                # MID-LEASE (kills the thread instead of bounding its
                # lifetime) — its leased jobs strand until the stale
                # requeue charges them a repetition they never earned
                out.append((("retire", w), repl_w(w, _GONE)))
            if kind == "S":
                # asleep in Waiter.wait. A pending notification wakes
                # it (consuming this worker's bit — the cursor);
                # TIMEOUT always wakes it too, pending bit or not —
                # the degradation-ladder fallback that turns a lost
                # notification into a plain poll instead of a hang.
                # The seeded bug deletes exactly that edge.
                if wakes[w]:
                    nw = tuple(0 if i == w else b
                               for i, b in enumerate(wakes))
                    out.append((("notify_wake", w),
                                repl_w(w, _IDLE, nwakes=nw)))
                if cfg.bug != "lost_wakeup_no_fallback":
                    out.append((("timeout_wake", w), repl_w(w, _IDLE)))
                continue
            if kind == "I":
                claimable = [j for j, rec in enumerate(jobs)
                             if rec[0] in (_WAIT, _BRK)]
                take = tuple(claimable[:cfg.batch_k])
                if take:
                    nj = list(jobs)
                    for j in take:
                        s, r, _, _, d, _ = nj[j]
                        # fresh claim resets any carried shadow lease,
                        # mirroring both index engines
                        nj[j] = (_RUN, r, w + 1, 0, d, _SP_NONE)
                    out.append((("claim", w, take),
                                repl_w(w, ("R", take, 0, ()),
                                       tuple(nj))))
                elif cfg.allow_spec:
                    # only a worker with NOTHING claimable probes for a
                    # shadow lease (Worker.poll_once's gating); lowest
                    # open id first — the engines' scan order inside a
                    # preference class (the model has no placement
                    # tags, so traces replay exactly on the 2-worker
                    # boxes the gate pins). Never the worker's own job.
                    open_ids = [j for j, rec in enumerate(jobs)
                                if rec[0] == _RUN and rec[5] == _SP_OPEN
                                and rec[2] != w + 1]
                    for j in open_ids[:1]:
                        s, r, o, a, d, _ = jobs[j]
                        nj = repl_job(j, (s, r, o, a, d, _SP_TAKEN0 + w))
                        out.append((("claim_spec", w, j),
                                    repl_w(w, ("SR", j), nj)))
                if cfg.allow_notify and not take:
                    # polled, found nothing claimable: arm the Waiter.
                    # The pending bit is NOT cleared — a notification
                    # that raced the poll-then-arm window is consumed
                    # by the next wait immediately (the per-waiter
                    # cursor rule, sched/waiter.py)
                    out.append((("sleep", w), repl_w(w, ("S",))))
            elif kind == "R":
                _, leased, pos, done = mode
                j = leased[pos]
                out.append((("exec", w, j),
                            repl_w(w, self._norm(
                                ("R", leased, pos + 1, done + (j,))))))
                if cfg.allow_fail:
                    out.append((("exec_fail", w, j),
                                repl_w(w, self._norm(
                                    ("C", leased, done, 0, 0,
                                     leased[pos + 1:], j)))))
            elif kind == "C":
                _, leased, entries, i, phase, tail, brk = mode
                j = entries[i]
                s, r, o, a, d, sp = jobs[j]
                owner_ok = (o == w + 1) or \
                    (cfg.bug == "commit_skips_owner_cas")
                if phase == 0:
                    ok = (s == _RUN) and owner_ok
                    nj = repl_job(j, (_FIN, r, o, a, d, sp)) if ok else jobs
                    nmode = ("C", leased, entries, i, 1, tail, brk) if ok \
                        else ("C", leased, entries, i + 1, 0, tail, brk)
                    out.append((("commit_a", w, j, ok),
                                repl_w(w, self._norm(nmode), nj)))
                else:
                    ok = (s == _FIN) and owner_ok
                    # a landed commit means the (re-)run's output was
                    # published whole at full redundancy
                    nj = repl_job(j, (_WRI, r, o, a, _D_INTACT, sp)) \
                        if ok else jobs
                    nc = tuple(min(c + 1, 2) if ok and i2 == j else c
                               for i2, c in enumerate(commits))
                    nmode = ("C", leased, entries, i + 1, 0, tail, brk)
                    out.append((("commit_b", w, j, ok),
                                repl_w(w, self._norm(nmode), nj, nc)))
            elif kind == "L":
                _, leased, tail, brk = mode
                nj = list(jobs)
                released = []
                for t in tail:
                    s, r, o, a, d, _ = nj[t]
                    if s == _RUN and o == w + 1:
                        # no repetition bump; release dissolves any
                        # shadow lease (the index engines' unlease rule)
                        nj[t] = (_WAIT, r, o, 0, d, _SP_NONE)
                        released.append(t)
                out.append((("release", w, tail, tuple(released)),
                            repl_w(w, self._norm(("K", leased, brk)),
                                   tuple(nj), nwakes=woken(released))))
            elif kind == "K":
                _, leased, brk = mode
                s, r, o, a, d, sp = jobs[brk]
                # ownership AND still-RUNNING: a job the scavenger
                # already requeued (BROKEN) or failed (FAILED) must not
                # be touched — Worker._mark_broken carries the matching
                # expect=(RUNNING,) CAS
                ok = (o == w + 1) and s == _RUN
                nj = repl_job(brk, (_BRK, self._sat(r + 1), o, 0, d,
                                    _SP_NONE)) if ok else jobs
                out.append((("mark_broken", w, brk, ok),
                            repl_w(w, _IDLE, nj, nwakes=woken(ok))))
            elif kind == "SR":
                j = mode[1]
                out.append((("spec_exec", w, j),
                            repl_w(w, ("SC", j, 0))))
                # the clone's revocation probe / body failure: dissolve
                # the shadow lease (iff still held), touch nothing else
                # — Worker.run_one's cancel path
                sp = jobs[j][5]
                held = sp == _SP_TAKEN0 + w
                nj = repl_job(j, jobs[j][:5] + (_SP_NONE,)) if held \
                    else jobs
                out.append((("spec_cancel", w, j, held),
                            repl_w(w, _IDLE, nj)))
            elif kind == "SC":
                _, j, phase = mode
                s, r, o, a, d, sp = jobs[j]
                # clone ownership = holding the shadow lease; the bug
                # variant ALSO skips the winner's status CAS — the race
                # the one-transition arbitration exists to prevent
                spec_ok = sp == _SP_TAKEN0 + w
                skip_status = cfg.bug == "spec_commit_skips_winner_cas"
                if phase == 0:
                    ok = spec_ok and (s == _RUN or skip_status)
                    if ok:
                        nj = repl_job(j, (_FIN, r, o, a, d, sp))
                        out.append((("commit_a", w, j, True),
                                    repl_w(w, ("SC", j, 1), nj)))
                    else:
                        # lost the race (or the lease): the cancel is
                        # the NEXT step (SP_X), mirroring run_one's
                        # failed-commit-then-cancel_spec order
                        out.append((("commit_a", w, j, False),
                                    repl_w(w, ("SP_X", j))))
                else:
                    ok = spec_ok and (s == _FIN or skip_status)
                    if ok:
                        nj = repl_job(j, (_WRI, r, o, a, _D_INTACT, sp))
                        nc = tuple(min(c + 1, 2) if i2 == j else c
                                   for i2, c in enumerate(commits))
                        out.append((("commit_b", w, j, True),
                                    repl_w(w, _IDLE, nj, nc)))
                    else:
                        out.append((("commit_b", w, j, False),
                                    repl_w(w, ("SP_X", j))))
            elif kind == "SP_X":
                # a clone whose commit failed dissolves its shadow lease
                # (iff still held) and goes idle — Worker._spec_lost
                j = mode[1]
                held = jobs[j][5] == _SP_TAKEN0 + w
                nj = repl_job(j, jobs[j][:5] + (_SP_NONE,)) if held \
                    else jobs
                out.append((("spec_cancel", w, j, held),
                            repl_w(w, _IDLE, nj)))
            # heartbeats: alive while job bodies run (R / SR) and on the
            # failure path (the except runs inside the _beating scope);
            # the clean commit happens after the beat thread stopped
            beating = (kind == "R") or (
                kind == "C" and (brk_of(mode) >= 0 or tail_of(mode))) \
                or kind in ("L", "K")
            if beating:
                leased = mode[1]
                beaten = tuple(t for t in leased
                               if jobs[t][0] in (_RUN, _FIN)
                               and jobs[t][2] == w + 1)
                if any(jobs[t][3] > 0 for t in beaten):
                    nj = list(jobs)
                    for t in beaten:
                        s, r, o, _, d, sp = nj[t]
                        nj[t] = (s, r, o, 0, d, sp)
                    out.append((("beat", w, beaten),
                                (tuple(nj), workers, commits, budget,
                                 wakes, nbudget)))
            elif kind == "SR":
                # the clone's beat thread: ownership through the shadow
                # lease — this is what keeps a job whose ORIGINAL died
                # from being stale-requeued (and repetition-charged)
                # while a live clone still races it
                j = mode[1]
                if (jobs[j][0] in (_RUN, _FIN)
                        and jobs[j][5] == _SP_TAKEN0 + w
                        and jobs[j][3] > 0):
                    nj = repl_job(j, jobs[j][:3] + (0,) + jobs[j][4:])
                    out.append((("beat", w, (j,)),
                                (nj, workers, commits, budget,
                                 wakes, nbudget)))

        # -- global (server/scavenger/clock) steps -----------------------
        if cfg.allow_spec:
            # the straggler detector's edge: any RUNNING job with no
            # speculation may be marked OPEN (the model abstracts the
            # EWMA-age threshold away — WHICH job straggles is the
            # environment's choice, so every choice is enumerated; the
            # CAS shape is what the checker verifies). A pure marker:
            # status, reps, owner, age all untouched.
            for j, rec in enumerate(jobs):
                if rec[0] == _RUN and rec[5] == _SP_NONE:
                    # opening a shadow lease wakes the idle fleet (the
                    # detector's notify in Server._speculate_stragglers)
                    out.append((("speculate", j),
                                (repl_job(j, rec[:5] + (_SP_OPEN,)),
                                 workers, commits, budget,
                                 woken(True), nbudget)))
        aged = [j for j, rec in enumerate(jobs)
                if rec[0] in (_RUN, _FIN) and rec[3] < self.cfg.stale_age]
        if aged:
            nj = list(jobs)
            for j in aged:
                s, r, o, a, d, sp = nj[j]
                nj[j] = (s, r, o, a + 1, d, sp)
            out.append((("tick",), (tuple(nj), workers, commits, budget,
                                    wakes, nbudget)))

        requeue_from = (_RUN,) if self.cfg.bug == "requeue_ignores_finished" \
            else (_RUN, _FIN)
        stale = tuple(j for j, rec in enumerate(jobs)
                      if rec[0] in requeue_from
                      and rec[3] >= self.cfg.stale_age)
        if stale:
            nj = list(jobs)
            for j in stale:
                s, r, o, a, d, sp = nj[j]
                # requeue dissolves any shadow lease (unlease rule)
                nj[j] = (_BRK, self._sat(r + 1), o, 0, d, _SP_NONE)
            out.append((("requeue", stale),
                        (tuple(nj), workers, commits, budget,
                         woken(True), nbudget)))

        failed = tuple(j for j, rec in enumerate(jobs)
                       if rec[0] == _BRK and rec[1] >= self.cfg.max_retries)
        if failed:
            nj = list(jobs)
            for j in failed:
                s, r, o, a, d, sp = nj[j]
                nj[j] = (_FAI, r, o, a, d, sp)
            out.append((("scavenge", failed),
                        (tuple(nj), workers, commits, budget,
                         wakes, nbudget)))

        # -- replica-aware data plane (DESIGN §20) -----------------------
        # environment loss events, budget-bounded: a published output
        # loses one replica, or every copy at once (the blackout /
        # dead-backend shape). Only WRITTEN jobs hold published output.
        if budget > 0:
            for j, (s, r, o, a, d, sp) in enumerate(jobs):
                if s != _WRI:
                    continue
                if cfg.coded:
                    # k+m stripe: blocks die ONE at a time. A first
                    # loss leaves the stripe decodable (UNDER — still
                    # ≥ k survivors); another drops it below k (LOST).
                    # Killing a stripe costs two budget charges where
                    # lose_replica's whole-copy semantics cost one —
                    # the durability the coding buys, made enumerable.
                    if d != _D_LOST:
                        out.append((
                            ("lose_parity", j),
                            (repl_job(j, (s, r, o, a, d - 1, sp)),
                             workers, commits, budget - 1, wakes,
                             nbudget)))
                elif d == _D_INTACT:
                    out.append((
                        ("lose_replica", j),
                        (repl_job(j, (s, r, o, a, _D_UNDER, sp)), workers,
                         commits, budget - 1, wakes, nbudget)))
                if d != _D_LOST:
                    out.append((
                        ("lose_all", j),
                        (repl_job(j, (s, r, o, a, _D_LOST, sp)), workers,
                         commits, budget - 1, wakes, nbudget)))
        # scavenger pass, reconstruct rung: every under-replicated
        # output is healed from a survivor — job state UNTOUCHED (the
        # whole point of the trade). Under coded=True the same rung is
        # decode-from-survivors + re-encode to full width; the seeded
        # bug also "repairs" below-k stripes — data from nothing, which
        # the decode-conservation step invariant catches
        repair_from = (_D_UNDER, _D_LOST) \
            if cfg.bug == "coded_decode_lost_stripe" else (_D_UNDER,)
        under = tuple(j for j, rec in enumerate(jobs)
                      if rec[0] == _WRI and rec[4] in repair_from)
        if under:
            nj = list(jobs)
            for j in under:
                s, r, o, a, _, sp = nj[j]
                nj[j] = (s, r, o, a, _D_INTACT, sp)
            out.append((("repair", under),
                        (tuple(nj), workers, commits, budget,
                         wakes, nbudget)))
        # scavenger pass, requeue rung (last resort): producers of
        # wholly-lost output go back to WAITING via a status CAS on
        # WRITTEN, with NO repetition charge, opening a fresh commit
        # generation. The seeded bugs delete the rung entirely or drop
        # the WRITTEN expectation from the CAS.
        if self.cfg.bug != "scavenge_skips_lost_data":
            if self.cfg.bug == "lost_requeue_skips_written_cas":
                lost = tuple(j for j, rec in enumerate(jobs)
                             if rec[4] == _D_LOST
                             and rec[0] in (_WRI, _FIN, _RUN))
            elif self.cfg.bug == "coded_requeue_skips_decode":
                # the decode-blind scavenger: ANY block loss reads as
                # total loss (the decode rung is never tried), and its
                # stale classification is trusted — no WRITTEN CAS
                lost = tuple(j for j, rec in enumerate(jobs)
                             if rec[4] in (_D_UNDER, _D_LOST)
                             and rec[0] in (_WRI, _FIN, _RUN))
            else:
                lost = tuple(j for j, rec in enumerate(jobs)
                             if rec[0] == _WRI and rec[4] == _D_LOST)
            if lost:
                nj = list(jobs)
                nc = list(commits)
                for j in lost:
                    _, r, _, _, d, _ = nj[j]
                    # the WAITING transition dissolves any (historical)
                    # shadow lease, like every unlease edge
                    nj[j] = (_WAIT, r, 0, 0, d, _SP_NONE)
                    nc[j] = 0
                out.append((("rerun_requeue", lost),
                            (tuple(nj), workers, tuple(nc), budget,
                             woken(True), nbudget)))

        # -- watch/notify adversary (DESIGN §23) -------------------------
        # a pending wakeup evaporates (dropped wake write, crashed
        # producer, cleared generation): budget-bounded so the space
        # stays finite. The timeout fallback is what must absorb it.
        if nbudget > 0:
            for w, bit in enumerate(wakes):
                if bit:
                    nw = tuple(0 if i == w else b
                               for i, b in enumerate(wakes))
                    out.append((("lose_notify", w),
                                (jobs, workers, commits, budget,
                                 nw, nbudget - 1)))

        # every job/worker/data-plane edge above leaves the lease plane
        # untouched: thread it through verbatim
        out = [(lbl, st + (ha_st,) if len(st) == 6 else st)
               for lbl, st in out]

        # -- leader-lease plane (DESIGN §31) ------------------------------
        # Two contending coordinators over one CAS lease document. All
        # edges are PURE on jobs/workers by construction — who leads is
        # invisible to the claim protocol (workers are leader-agnostic).
        if cfg.ha:
            ep, hold, age = ha_st[0], ha_st[1], ha_st[2]
            coords = ha_st[3:]

            def ha_next(nep=ep, nhold=hold, nage=age, coord=None):
                nc = list(coords)
                if coord is not None:
                    nc[coord[0]] = coord[1]
                return (jobs, workers, commits, budget, wakes, nbudget,
                        (nep, nhold, nage) + tuple(nc))

            # the lease's own virtual clock, separate from the job
            # clock: a leader may die while every job still WAITS, and
            # its lease must still be able to expire
            if hold != 0 and age < cfg.stale_age:
                out.append((("lease_tick",), ha_next(nage=age + 1)))
            for c in range(_N_COORDS):
                bel = coords[c]
                if bel == 0:
                    # standby election probe: the CAS acquire — legal on
                    # a free/released lease or an EXPIRED one (takeover,
                    # epoch bump past the dead leader). The seeded
                    # double_leader bug overwrites a LIVE lease without
                    # the version CAS, reusing the stored epoch — the
                    # two-live-holders shape the invariant catches.
                    expired = hold != 0 and age >= cfg.stale_age
                    can = hold == 0 or expired
                    buggy_live = (cfg.bug == "double_leader"
                                  and hold != 0 and not expired)
                    if (can and ep < _EPOCH_CAP) or buggy_live:
                        nep = ep if buggy_live else ep + 1
                        out.append((("acquire", c, expired),
                                    ha_next(nep=nep, nhold=c + 1, nage=0,
                                            coord=(c, nep))))
                else:
                    if bel == ep and hold == c + 1:
                        # the live leader: renewal resets the clock;
                        # release hands the lease back cleanly
                        if age > 0:
                            out.append((("renew", c, True),
                                        ha_next(nage=0)))
                        out.append((("lead_release", c),
                                    ha_next(nhold=0, nage=0,
                                            coord=(c, 0))))
                    else:
                        # the lease moved under this coordinator: its
                        # renewal CAS fails and it is fenced back to
                        # standby (never retried — StaleLeaderError is
                        # permanent by classification)
                        out.append((("renew", c, False),
                                    ha_next(coord=(c, 0))))
                    # a server-side mutation through the fencing guard.
                    # Correct model: lands iff the believed epoch IS the
                    # lease epoch; past the local deadline the landing
                    # rides the inline renewal CAS, which resets the
                    # clock (FencedJobStore._check → validate → renew).
                    # The seeded zombie_leader_write bug skips the guard
                    # — the stale write lands, which is the step
                    # violation.
                    landed = (bel == ep
                              or cfg.bug == "zombie_leader_write")
                    if landed:
                        nage = 0 if (bel == ep and age >= cfg.stale_age) \
                            else age
                        out.append((("lead_write", c, True),
                                    ha_next(nage=nage)))
                    else:
                        out.append((("lead_write", c, False),
                                    ha_next(coord=(c, 0))))
        return out

    @staticmethod
    def _norm(mode: tuple) -> tuple:
        """Collapse empty stages so every mode has a pending action."""
        while True:
            kind = mode[0]
            if kind == "R" and mode[2] >= len(mode[1]):
                mode = ("C", mode[1], mode[3], 0, 0, (), -1)
            elif kind == "C" and mode[3] >= len(mode[2]):
                _, leased, _, _, _, tail, brk = mode
                mode = ("L", leased, tail, brk) if tail else \
                    (("K", leased, brk) if brk >= 0 else _IDLE)
            elif kind == "L" and not mode[2]:
                mode = ("K", mode[1], mode[3])
            elif kind == "K" and mode[2] < 0:
                mode = _IDLE
            else:
                return mode

    # -- invariants -----------------------------------------------------

    def step_violation(self, old: tuple, new: tuple,
                       label: tuple) -> Optional[str]:
        ojobs, ocommits = old[0], old[2]
        njobs, ncommits = new[0], new[2]
        if label[0] == "acquire":
            # the fencing invariant (DESIGN §31): validity windows of
            # successive epochs never overlap, so at most ONE
            # coordinator may ever believe it holds the lease's CURRENT
            # epoch — the version CAS + epoch bump guarantee it
            nha = new[6]
            live = [c for c, b in enumerate(nha[3:])
                    if b > 0 and b == nha[0]]
            if len(live) >= 2:
                return (f"double leader: coordinators {live} both hold "
                        f"live epoch {nha[0]} after {label} — the "
                        "acquire skipped the version CAS / expiry "
                        "check, so two validity windows overlap and "
                        "both leaders' writes pass the fence "
                        "(DESIGN §31)")
        if label[0] == "lead_write" and label[2]:
            oha = old[6]
            c = label[1]
            if oha[3 + c] != oha[0]:
                return (f"stale-epoch write landed: coordinator {c} "
                        f"wrote with epoch {oha[3 + c]} while the lease "
                        f"is at epoch {oha[0]} — a zombie leader "
                        "mutated job state after losing a takeover "
                        "(the fencing guard must reject it with "
                        "StaleLeaderError; DESIGN §31)")
        if label[0] == "retire":
            # the no-lease-abandoned rule (DESIGN §29): a retiring
            # worker must own no live lease — FleetSupervisor's
            # graceful exit bounds the member's lifetime so it leaves
            # only AFTER its current lease settles
            w = label[1]
            held = [j for j, rec in enumerate(ojobs)
                    if rec[0] in (_RUN, _FIN)
                    and (rec[2] == w + 1 or rec[5] == _SP_TAKEN0 + w)]
            if held:
                return (f"retired worker {w} abandoned leases on jobs "
                        f"{held} — an elastic scale-down must wait for "
                        "the in-flight lease to settle (the stale "
                        "requeue would charge those jobs a repetition "
                        "they never earned; DESIGN §29)")
        for j, ((os_, or_, oo, _, od, osp), (ns_, nr, no, _, nd, nsp)) in \
                enumerate(zip(ojobs, njobs)):
            if nr < or_:
                return (f"repetitions of job {j} decreased {or_}→{nr} "
                        f"on {label}")
            if label[0] == "repair" and od == _D_LOST and nd != _D_LOST:
                # decode-conservation (DESIGN §27): repair reconstructs
                # from ≥ k survivors; a stripe below k has no decode —
                # a scavenger that "heals" it is fabricating bytes and
                # masking a loss only a producer re-run can cover
                return (f"repair resurrected job {j}'s output from "
                        f"below-k survivors on {label} — decode cannot "
                        "reconstruct a stripe with fewer than k blocks; "
                        "only a producer re-run regenerates it")
            if label[0] in _WAIT_PURE_OPS and (os_, or_, oo, osp) != \
                    (ns_, nr, no, nsp):
                # sleep/wake/lost-notify must be invisible to every job:
                # a wakeup carries no payload, so a stale or duplicate
                # one is a no-op by construction (DESIGN §23)
                return (f"notify edge {label} touched job {j} state — "
                        "sleep/wake transitions must be pure")
            if label[0] in _ELASTIC_PURE_OPS and (os_, or_, oo, osp) != \
                    (ns_, nr, no, nsp):
                # join/retire are pure capacity events: scaling the
                # pool may never touch a job (DESIGN §29)
                return (f"elastic edge {label} touched job {j} state — "
                        "join/retire must be pure pool-membership "
                        "transitions")
            if label[0] in _SPEC_PURE_OPS and (ns_ != os_ or nr != or_):
                # the zero-charge rule of the speculation edges: marking,
                # taking, or dissolving a shadow lease must be invisible
                # to the job's status and retry budget (DESIGN §21)
                return (f"speculation edge {label} touched job {j} state "
                        f"({Status(os_).name},{or_})→"
                        f"({Status(ns_).name},{nr}) — speculate/claim/"
                        "cancel must be pure lease-markers")
            if ns_ != os_ and ns_ not in _ALLOWED_EDGES[os_]:
                # the ONE legal WRITTEN→WAITING edge: the scavenger's
                # lost-data requeue — and it must charge no repetition
                # (the loss is not the job's fault; DESIGN §20)
                if (label[0] == "rerun_requeue" and os_ == _WRI
                        and ns_ == _WAIT):
                    if nr != or_:
                        return (f"lost-data requeue charged a repetition "
                                f"to job {j} ({or_}→{nr}) — storage loss "
                                "must never march a job toward FAILED")
                    continue
                return (f"illegal status edge job {j}: "
                        f"{Status(os_).name}→{Status(ns_).name} on {label}")
        if label[0] == "commit_b" and label[3]:
            w, j = label[1], label[2]
            if ncommits[j] > 1:
                return (f"double commit: job {j} committed twice "
                        f"(worker {w} landed a second commit — the "
                        "first-commit-wins CAS failed to arbitrate)")
            if ojobs[j][2] != w + 1 and ojobs[j][5] != _SP_TAKEN0 + w:
                return (f"commit without ownership: worker {w} committed "
                        f"job {j} currently claimed by worker "
                        f"{ojobs[j][2] - 1} with no shadow lease — the "
                        "scavenger requeued and re-claimed it mid-commit")
        return None

    def quiescent_violation(self, state: tuple) -> Optional[str]:
        jobs, workers = state[0], state[1]
        if all(m[0] in ("D", "G", "A") for m in workers):
            # a fully dead pool may strand work; so may a pool whose
            # every member retired or never joined (the elastic analog
            # — the real supervisor's baseline floor prevents it, but
            # the model enumerates the environment's worst case)
            return None
        bad = {j: Status(s).name
               for j, (s, _, _, _, _, _) in enumerate(jobs)
               if s not in (_WRI, _FAI)}
        if bad:
            msg = (f"lost/stuck jobs at quiescence with a live worker: "
                   f"{bad} (every job must end WRITTEN or FAILED; a "
                   "FINISHED entry here is the stuck-FINISHED+unclaimed "
                   "gap)")
            asleep = [w for w, m in enumerate(workers) if m[0] == "S"]
            if asleep:
                msg += (f"; workers {asleep} are asleep awaiting a "
                        "wakeup that will never arrive — the lost-wakeup"
                        " hang the Waiter's timeout fallback exists to "
                        "prevent (DESIGN §23)")
            return msg
        stranded = [j for j, (s, _, _, _, d, _) in enumerate(jobs)
                    if s == _WRI and d == _D_LOST]
        if stranded:
            return (f"stranded lost shuffle data at quiescence with a "
                    f"live worker: jobs {stranded} are WRITTEN but every "
                    "replica of their output is gone and nobody will "
                    "regenerate it — the reduce phase wedges (the "
                    "scavenger must requeue the producer, DESIGN §20)")
        return None


def brk_of(mode: tuple) -> int:
    return mode[6] if mode[0] == "C" else -1


def tail_of(mode: tuple) -> tuple:
    return mode[5] if mode[0] == "C" else ()


def check_protocol(config: ModelConfig = ModelConfig(),
                   max_states: int = 5_000_000) -> CheckResult:
    """Exhaustively enumerate every reachable interleaving (BFS, so a
    violation trace is shortest-possible) and check all invariants."""
    model = LeaseModel(config)
    t0 = _time.perf_counter()
    init = model.initial()
    visited = {init}
    parents: Dict[tuple, Tuple[Optional[tuple], Optional[tuple]]] = {
        init: (None, None)}
    frontier = [init]
    n_trans = 0
    n_quiescent = 0

    def trace_to(state, extra=None):
        labels = []
        cur = state
        while True:
            prev, label = parents[cur]
            if prev is None:
                break
            labels.append(label)
            cur = prev
        labels.reverse()
        if extra is not None:
            labels.append(extra)
        return labels

    while frontier:
        next_frontier = []
        for state in frontier:
            trans = model.transitions(state)
            # quiescence means no PROGRESS is possible; worker death
            # and data-loss events are environment events, not progress
            # — a state whose only enabled steps are "somebody could
            # still die / more data could be lost" is already stuck,
            # and must pass the lost-job + stranded-data invariants
            if all(label[0] in _ENV_OPS for label, _ in trans):
                n_quiescent += 1
                msg = model.quiescent_violation(state)
                if msg:
                    return CheckResult(config, len(visited), n_trans,
                                       n_quiescent,
                                       _time.perf_counter() - t0,
                                       Violation(msg, trace_to(state),
                                                 state))
                if all(label[0] == "die" for label, _ in trans):
                    continue     # only deaths left: nothing new to learn
                # loss events still pending: a lost output must wake the
                # scavenger back up — keep exploring those branches
            for label, new in trans:
                n_trans += 1
                msg = model.step_violation(state, new, label)
                if msg:
                    return CheckResult(config, len(visited), n_trans,
                                       n_quiescent,
                                       _time.perf_counter() - t0,
                                       Violation(msg,
                                                 trace_to(state, label),
                                                 new))
                if new not in visited:
                    if len(visited) >= max_states:
                        raise RuntimeError(
                            f"state space exceeds {max_states} states — "
                            "shrink the configuration")
                    visited.add(new)
                    parents[new] = (state, label)
                    next_frontier.append(new)
        frontier = next_frontier
    return CheckResult(config, len(visited), n_trans, n_quiescent,
                       _time.perf_counter() - t0, None)


# -- trace replay against the real stores -----------------------------------

_DUMMY_TIMES = {"started": 1.0, "finished": 2.0, "written": 3.0,
                "cpu": 0.5, "real": 2.0}


def replay_trace(store, trace: Sequence[tuple], config: ModelConfig,
                 final_state: Optional[tuple] = None,
                 ns: str = "model_jobs") -> dict:
    """Run a model trace's store operations against a REAL JobStore.

    Virtual-clock steps (``tick``) have no store analog; the staleness
    they produce is applied surgically at the ``requeue`` step via the
    same status CAS ``requeue_stale`` performs (RUNNING|FINISHED →
    BROKEN, +1 repetition), on exactly the jobs the model requeued.

    Returns ``{"ok": True, ...}`` when every operation's outcome matches
    the model (and, when ``final_state`` is given, the store's final
    per-job status/reps match it), else ``{"ok": False, "step": k,
    "label": ..., "reason": ...}`` naming the first divergent step —
    for a seeded-bug trace that divergence IS the real store's CAS
    refusing the racy operation.
    """
    from lua_mapreduce_tpu.coord.jobstore import make_job

    store.insert_jobs(ns, [make_job(f"k{j}", j)
                           for j in range(config.n_jobs)])
    wname = [f"mw{w}" for w in range(config.n_workers)]

    def diverged(i, label, reason):
        return {"ok": False, "step": i, "label": label, "reason": reason}

    # the leader-lease plane replays against the REAL pt_cas lease +
    # FencedJobStore (DESIGN §31). The virtual lease clock advances
    # 1.25 per lease_tick against ttl = stale_age, so "age ≥ stale_age"
    # in the model is strictly past the real deadline (the real expiry
    # compare is strict) while "age < stale_age" stays strictly inside.
    ha_now = [0.0]
    ha_leases: Dict[int, object] = {}

    def ha_lease(c: int):
        if c not in ha_leases:
            from lua_mapreduce_tpu.sched.lease import LeaderLease
            ha_leases[c] = LeaderLease(store, holder=f"mc{c}",
                                       ttl_s=float(config.stale_age),
                                       clock=lambda: ha_now[0])
        return ha_leases[c]

    for i, label in enumerate(trace):
        op = label[0]
        if op == "lease_tick":
            ha_now[0] += 1.25
            continue
        if op == "acquire":
            _, c, took = label
            if not ha_lease(c).try_acquire():
                return diverged(
                    i, label,
                    f"acquire CAS refused coordinator {c} — the real "
                    "lease's version CAS + expiry check block the "
                    "takeover the buggy model allowed")
            if ha_lease(c).took_over != took:
                return diverged(i, label,
                                f"took_over={ha_lease(c).took_over}, "
                                f"model said {took}")
            continue
        if op == "renew":
            _, c, ok = label
            got = ha_lease(c).renew()
            if got != ok:
                return diverged(i, label,
                                f"renew CAS returned {got}, model "
                                f"said {ok}")
            continue
        if op == "lead_release":
            ha_lease(label[1]).release()
            continue
        if op == "lead_write":
            _, c, landed = label
            from lua_mapreduce_tpu.faults.errors import StaleLeaderError
            from lua_mapreduce_tpu.sched.lease import FencedJobStore
            fenced = FencedJobStore(store, ha_lease(c))
            try:
                # a harmless guarded mutation: the fencing check is
                # what's under test, not the op's payload
                fenced.requeue_stale(ns, 1e9)
                got = True
            except StaleLeaderError:
                got = False
            if got != landed:
                return diverged(
                    i, label,
                    f"fenced write: real guard "
                    + ("rejected the write the buggy model landed — "
                       "StaleLeaderError fences the zombie" if landed
                       else f"landed a write the model fenced"))
            continue
        if op in ("exec", "exec_fail", "spec_exec", "die", "tick",
                  "lose_replica", "lose_all", "lose_parity", "repair",
                  "sleep", "notify_wake", "timeout_wake", "lose_notify",
                  "join", "retire"):
            # loss events / replica repair live on the data plane, and
            # sleep/wake edges live in the Waiter layer (sched/waiter.py)
            # — neither has a jobstore op to replay; the store-visible
            # consequences (what was claimed, requeued, committed)
            # replay through the surrounding protocol ops
            continue
        if op == "speculate":
            (_, j) = label
            if not store.speculate(ns, j):
                return diverged(i, label,
                                f"speculate CAS refused job {j}")
        elif op == "claim_spec":
            _, w, j = label
            doc = store.claim_spec(ns, wname[w])
            got = doc["_id"] if doc else None
            if got != j:
                return diverged(i, label,
                                f"claim_spec took {got}, model took {j}")
        elif op == "spec_cancel":
            _, w, j, held = label
            got = store.cancel_spec(ns, j, wname[w])
            if got != held:
                return diverged(i, label,
                                f"cancel_spec returned {got}, model "
                                f"said {held}")
        elif op == "claim":
            _, w, take = label
            docs = store.claim_batch(ns, wname[w], k=config.batch_k)
            got = tuple(d["_id"] for d in docs)
            if got != tuple(take):
                return diverged(i, label,
                                f"claimed {got}, model claimed {take}")
        elif op == "commit_a":
            _, w, j, ok = label
            got = store.set_job_status(ns, j, Status.FINISHED,
                                       expect=(Status.RUNNING,),
                                       expect_worker=wname[w])
            if got != ok:
                return diverged(
                    i, label,
                    f"FINISHED CAS returned {got}, model said {ok}"
                    + ("" if ok else " — the store is weaker than the "
                       "protocol allows")
                    + (" — the real store's status+ownership CAS refuses "
                       "the commit the buggy model allowed" if not got
                       else ""))
            if got:
                store.set_job_times(ns, j, _DUMMY_TIMES)
        elif op == "commit_b":
            _, w, j, ok = label
            got = store.set_job_status(ns, j, Status.WRITTEN,
                                       expect=(Status.FINISHED,),
                                       expect_worker=wname[w])
            if got != ok:
                return diverged(
                    i, label,
                    f"WRITTEN CAS returned {got}, model said {ok}"
                    + (" — the real store's ownership CAS refuses the "
                       "commit the buggy model allowed" if not got else ""))
        elif op == "release":
            _, w, tail, released = label
            n = store.release_batch(ns, wname[w], list(tail))
            if n != len(released):
                return diverged(i, label,
                                f"released {n}, model released "
                                f"{len(released)}")
        elif op == "mark_broken":
            _, w, j, ok = label
            got = store.set_job_status(ns, j, Status.BROKEN,
                                       expect=(Status.RUNNING,),
                                       expect_worker=wname[w])
            if got != ok:
                return diverged(i, label,
                                f"BROKEN CAS returned {got}, model "
                                f"said {ok}")
        elif op == "beat":
            _, w, beaten = label
            n = store.heartbeat_batch(ns, list(beaten), wname[w])
            if n != len(beaten):
                return diverged(i, label,
                                f"{n} beats landed, model landed "
                                f"{len(beaten)}")
        elif op == "requeue":
            (_, stale) = label
            for j in stale:
                if not store.set_job_status(
                        ns, j, Status.BROKEN,
                        expect=(Status.RUNNING, Status.FINISHED)):
                    return diverged(i, label,
                                    f"requeue CAS refused job {j}")
        elif op == "scavenge":
            (_, failed) = label
            n = store.scavenge(ns, config.max_retries)
            if n != len(failed):
                return diverged(i, label,
                                f"scavenged {n}, model scavenged "
                                f"{len(failed)}")
        elif op == "rerun_requeue":
            # the reconstruct-vs-requeue edge's last rung: exactly the
            # WRITTEN→WAITING CAS Server._requeue_maps performs per
            # producer of a wholly-lost file — the real store refuses it
            # for any job not currently WRITTEN, which is where a
            # skips-the-CAS bug trace diverges
            (_, lost) = label
            for j in lost:
                if not store.set_job_status(ns, j, Status.WAITING,
                                            expect=(Status.WRITTEN,)):
                    return diverged(
                        i, label,
                        f"lost-data requeue CAS refused job {j} — the "
                        "real store's WRITTEN expectation blocks the "
                        "requeue the buggy model allowed")
        else:
            return diverged(i, label, f"unknown trace op {op!r}")

    result = {"ok": True, "steps": len(trace)}
    if final_state is not None:
        jobs = final_state[0]
        cap = config.max_retries + 1
        for j, (s, r, _, _, _, _) in enumerate(jobs):
            doc = store.get_job(ns, j)
            if int(doc["status"]) != s or min(int(doc["repetitions"]),
                                              cap) != r:
                return {"ok": False, "step": len(trace),
                        "label": ("final",),
                        "reason": f"job {j} ended "
                                  f"({Status(int(doc['status'])).name}, "
                                  f"{doc['repetitions']}), model ended "
                                  f"({Status(s).name}, {r})"}
    return result


def utest() -> None:
    """Self-test: a 1×2 exhaustive pass holds every invariant (with and
    without the replica-recovery edge); every seeded bug is re-found; a
    violation trace replayed against the real MemJobStore diverges
    exactly at the guarding CAS."""
    from lua_mapreduce_tpu.coord.jobstore import MemJobStore

    small = ModelConfig(n_workers=1, n_jobs=2, batch_k=2)
    res = check_protocol(small)
    assert res.ok and res.states > 10 and res.quiescent > 0

    bug = check_protocol(dataclasses.replace(
        small, n_workers=2, bug="commit_skips_owner_cas"))
    assert not bug.ok and "ownership" in bug.violation.message
    rep = replay_trace(MemJobStore(), bug.violation.trace,
                       bug.config)
    assert not rep["ok"] and rep["label"][0].startswith("commit")

    stuck = check_protocol(dataclasses.replace(
        small, n_workers=2, bug="requeue_ignores_finished"))
    assert not stuck.ok and "FINISHED" in stuck.violation.message

    # replica-recovery edge (DESIGN §20): loss events + repair +
    # lost-data requeue keep the full invariant set, including the
    # zero-repetition-charge and no-stranded-data rules
    lossy = dataclasses.replace(small, data_loss_budget=2)
    res2 = check_protocol(lossy)
    assert res2.ok and res2.states > res.states

    strand = check_protocol(dataclasses.replace(
        lossy, bug="scavenge_skips_lost_data"))
    assert not strand.ok and "stranded" in strand.violation.message

    yank = check_protocol(dataclasses.replace(
        lossy, n_workers=2, bug="lost_requeue_skips_written_cas"))
    assert not yank.ok and "illegal status edge" in yank.violation.message
    rep2 = replay_trace(MemJobStore(), yank.violation.trace, yank.config)
    assert not rep2["ok"]
    assert rep2["label"][0] in ("rerun_requeue", "commit_a", "commit_b",
                                "claim")

    # speculation edges (DESIGN §21): the duplicate-lease lifecycle
    # holds every invariant exhaustively, and the loser-commit-skips-
    # winner-CAS race is re-found and diverges on the real store's CAS
    spec = dataclasses.replace(small, n_workers=2, allow_spec=True)
    res3 = check_protocol(spec)
    assert res3.ok and res3.states > res.states

    race = check_protocol(dataclasses.replace(
        spec, bug="spec_commit_skips_winner_cas"))
    assert not race.ok, "seeded spec race not found"
    assert ("double commit" in race.violation.message
            or "illegal status edge" in race.violation.message)
    rep3 = replay_trace(MemJobStore(), race.violation.trace, race.config)
    assert not rep3["ok"]
    assert rep3["label"][0].startswith(("commit", "claim_spec",
                                        "spec_cancel"))

    # watch/notify edges (DESIGN §23): sleep/wake/lost-notification
    # interleavings keep the whole invariant set, and deleting the
    # timeout fallback re-finds the lost-wakeup hang, replayable: the
    # store ops of the hang trace reproduce and land jobs exactly where
    # the model stranded them
    waked = dataclasses.replace(small, n_workers=2, allow_notify=True)
    res4 = check_protocol(waked)
    assert res4.ok and res4.states > res.states

    hang = check_protocol(dataclasses.replace(
        waked, bug="lost_wakeup_no_fallback"))
    assert not hang.ok, "seeded lost-wakeup hang not found"
    assert "asleep" in hang.violation.message
    rep4 = replay_trace(MemJobStore(), hang.violation.trace, hang.config,
                        final_state=hang.violation.state)
    assert rep4["ok"], rep4    # the wedge reproduces on the real store

    # erasure-coded recovery (DESIGN §27): block-at-a-time loss +
    # decode-repair keep the full invariant set exhaustively; the
    # conjured-decode and decode-blind-requeue bugs are re-found, and
    # the requeue bug's trace diverges at the WRITTEN CAS on BOTH real
    # stores (the ISSUE's survivor-set-decode-vs-requeue edge)
    import tempfile
    from lua_mapreduce_tpu.coord.filestore import FileJobStore

    coded = dataclasses.replace(small, data_loss_budget=2, coded=True)
    res5 = check_protocol(coded)
    assert res5.ok and res5.states > res.states

    conj = check_protocol(dataclasses.replace(
        coded, bug="coded_decode_lost_stripe"))
    assert not conj.ok, "seeded conjured-decode bug not found"
    assert "below-k" in conj.violation.message

    blind = check_protocol(dataclasses.replace(
        coded, n_workers=2, bug="coded_requeue_skips_decode"))
    assert not blind.ok, "seeded decode-blind requeue not found"
    assert "illegal status edge" in blind.violation.message
    with tempfile.TemporaryDirectory() as td:
        for st in (MemJobStore(), FileJobStore(td)):
            rep5 = replay_trace(st, blind.violation.trace, blind.config)
            assert not rep5["ok"], (type(st).__name__, rep5)
            assert rep5["label"][0] in ("rerun_requeue", "commit_a",
                                        "commit_b", "claim"), rep5

    # elastic join/leave (DESIGN §29): the pool-membership edges hold
    # every invariant exhaustively (join/retire purity, graceful exit),
    # and retiring a mid-lease member is re-found as the abandoned-
    # lease violation; the correct-model trace replays on the real
    # store (join/retire have no store op — exactly the point: scaling
    # is invisible to the lease protocol)
    elastic = dataclasses.replace(small, n_workers=2, elastic=True)
    res6 = check_protocol(elastic)
    assert res6.ok and res6.states > res.states

    abandon = check_protocol(dataclasses.replace(
        elastic, bug="elastic_retire_holds_lease"))
    assert not abandon.ok, "seeded mid-lease retire not found"
    assert "abandoned leases" in abandon.violation.message
    rep6 = replay_trace(MemJobStore(), abandon.violation.trace[:-1],
                        abandon.config)
    assert rep6["ok"], rep6   # every store op up to the bad retire lands

    # leader lease / fencing (DESIGN §31): coordinator churn holds the
    # whole invariant set exhaustively (HA edges are job-transparent —
    # who leads is invisible to the claim protocol); the split-brain
    # and zombie-write bugs are re-found as direct invariant hits, and
    # their traces replayed against the REAL LeaderLease/FencedJobStore
    # over a real store diverge at exactly the guarding CAS / fence
    ha_cfg = dataclasses.replace(small, ha=True)
    res7 = check_protocol(ha_cfg)
    assert res7.ok and res7.states > res.states

    dbl = check_protocol(dataclasses.replace(ha_cfg, bug="double_leader"))
    assert not dbl.ok, "seeded double-leader bug not found"
    assert "double leader" in dbl.violation.message
    rep7 = replay_trace(MemJobStore(), dbl.violation.trace, dbl.config)
    assert not rep7["ok"] and rep7["label"][0] == "acquire", rep7

    zomb = check_protocol(dataclasses.replace(
        ha_cfg, bug="zombie_leader_write"))
    assert not zomb.ok, "seeded zombie-write bug not found"
    assert "stale-epoch write landed" in zomb.violation.message
    rep8 = replay_trace(MemJobStore(), zomb.violation.trace, zomb.config)
    assert not rep8["ok"] and rep8["label"][0] == "lead_write", rep8
