"""``lmr-analyze``: the analysis CLI.

    python -m lua_mapreduce_tpu.analysis [lint|protocol|all] [options]

``lint`` runs the framework-aware rule registry over the package (or
explicit paths); ``protocol`` exhaustively model-checks the lease
lifecycle; ``all`` (the default) runs both.  Exit code 0 = clean; with
``--fail-on-findings`` any surviving lint finding exits 1 (the CI
gate); a protocol violation of the shipped model always exits 1.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from lua_mapreduce_tpu.analysis import lint as lint_mod
from lua_mapreduce_tpu.analysis import protocol as proto_mod


def _cmd_lint(args) -> tuple:
    findings = lint_mod.run_lint(args.paths or None,
                                 baseline=args.baseline)
    fail = bool(findings) and args.fail_on_findings
    return findings, fail


def _protocol_suite(args):
    """The default exhaustive sweep: the full lifecycle with worker
    death, then the failure path (release/mark-broken) on a smaller
    box, then the seeded-race regressions (each MUST be re-found)."""
    runs = []
    base = proto_mod.ModelConfig(n_workers=args.workers, n_jobs=args.jobs,
                                 batch_k=args.batch_k)
    runs.append(("lifecycle+death", base))
    runs.append(("failure-path", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2), allow_fail=True,
        allow_death=False)))
    # the reconstruct-vs-requeue scavenge edge (DESIGN §20): budgeted
    # data-loss events + repair + lost-data requeue, exhaustively — on
    # a 2-job box so loss×death interleavings stay tractable
    runs.append(("replica-recovery", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2),
        data_loss_budget=2)))
    # the duplicate-lease edge (DESIGN §21): speculate / claim_spec /
    # racing commits / revoke, exhaustively with worker death — PINNED
    # to a 2-worker 2-job box (~377k states; the spec dimension
    # multiplies the space, so the lifecycle box above stays spec-free,
    # and at 2 workers the model's tag-free claim_spec scan order
    # matches both engines exactly, keeping violation traces replayable)
    runs.append(("speculation", dataclasses.replace(
        base, n_workers=2, n_jobs=2,
        batch_k=min(args.batch_k, 2), allow_spec=True)))
    # the watch/notify edge (DESIGN §23): sleep / notify-wake /
    # timeout-fallback / lost-notification interleavings, exhaustively
    # with worker death — on a 2-job box (the wakeup-bit dimension
    # multiplies the space like the spec dimension does)
    runs.append(("notify-wakeup", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2),
        allow_notify=True)))
    if args.seed_bug:
        bugs = [args.seed_bug]
    else:
        bugs = list(proto_mod.KNOWN_BUGS)
    out = []
    failed = False
    for name, cfg in runs:
        res = proto_mod.check_protocol(cfg)
        entry = {"run": name, "config": dataclasses.asdict(cfg),
                 "states": res.states, "transitions": res.transitions,
                 "quiescent_states": res.quiescent,
                 "wall_s": round(res.wall_s, 3), "ok": res.ok}
        if not res.ok:
            entry["violation"] = res.violation.message
            entry["trace"] = [list(t) for t in res.violation.trace]
            failed = True
        out.append(entry)
    for bug in bugs:
        extra = {}
        if bug in proto_mod.LOSS_BUGS:
            # loss-edge bugs are unreachable without loss events; the
            # smaller box keeps the seeded sweep fast
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         data_loss_budget=2)
        elif bug in proto_mod.SPEC_BUGS:
            # spec-edge bugs need the duplicate-lease dimension and a
            # second worker to hold the shadow lease (pinned to 2 for
            # trace replayability, like the exhaustive run)
            extra = dict(n_workers=2, n_jobs=2,
                         batch_k=min(args.batch_k, 2), allow_spec=True)
        elif bug in proto_mod.NOTIFY_BUGS:
            # notify-edge bugs need the wakeup dimension plus at least
            # one lost-notification event to be reachable
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         allow_notify=True)
        cfg = dataclasses.replace(base, bug=bug, **extra)
        res = proto_mod.check_protocol(cfg)
        entry = {"run": f"seeded:{bug}", "states": res.states,
                 "wall_s": round(res.wall_s, 3),
                 "found": not res.ok}
        if res.ok:
            entry["error"] = ("seeded bug NOT detected — the checker "
                              "lost its teeth")
            failed = True
        else:
            entry["violation"] = res.violation.message
            entry["trace_len"] = len(res.violation.trace)
        out.append(entry)
    return {"protocol": out}, failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lua_mapreduce_tpu.analysis",
        description="framework-aware lint + lease-protocol model checker")
    ap.add_argument("command", nargs="?", default="all",
                    choices=("all", "lint", "protocol", "rules"))
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 when lint findings survive suppression")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: analysis/baseline.json)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--batch-k", type=int, default=2)
    ap.add_argument("--seed-bug", default=None,
                    choices=proto_mod.KNOWN_BUGS,
                    help="restrict the seeded-race regression to one bug")
    args = ap.parse_args(argv)

    if args.command == "rules":
        catalog = lint_mod.rule_catalog()
        if args.format == "json":
            print(json.dumps(catalog, indent=2))
        else:
            for r in catalog:
                print(f"{r['id']} [{r['severity']}] "
                      f"({', '.join(r['paths'])}): {r['title']}")
                print(f"    {r['rationale']}")
        return 0

    payload = {}
    findings = None
    rc = 0
    if args.command in ("all", "lint"):
        findings, fail = _cmd_lint(args)
        payload.update(lint_mod.report_dict(findings))
        rc = max(rc, 1 if fail else 0)
    if args.command in ("all", "protocol"):
        try:
            proto_payload, fail = _protocol_suite(args)
        except ValueError as e:
            # out-of-range --workers/--jobs/--batch-k is a usage error,
            # not a protocol violation
            ap.error(str(e))
        except RuntimeError as e:
            # an allowed-but-too-big box (3 workers × 4 jobs) exceeding
            # the state cap is equally a usage problem — report it
            # cleanly, don't traceback
            ap.error(f"{e}; try fewer workers/jobs")
        payload.update(proto_payload)
        rc = max(rc, 1 if fail else 0)

    if args.format == "json":
        print(json.dumps(payload, indent=2))
        return rc
    if findings is not None:
        if findings:
            print(lint_mod.format_text(findings))
        print(f"lint: {len(findings)} finding(s)")
    for entry in payload.get("protocol", ()):
        if entry["run"].startswith("seeded:"):
            status = ("re-found: " + entry["violation"]
                      if entry["found"] else "MISSED")
            print(f"protocol {entry['run']}: {status} "
                  f"[{entry['states']} states, {entry['wall_s']}s]")
        else:
            status = "ok" if entry["ok"] else \
                f"VIOLATION: {entry['violation']}"
            print(f"protocol {entry['run']}: {status} "
                  f"[{entry['states']} states, {entry['transitions']} "
                  f"transitions, {entry['quiescent_states']} quiescent, "
                  f"{entry['wall_s']}s]")
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `... | head` closed the pipe: not an error. Point stdout at
        # devnull so interpreter shutdown does not retry the flush.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
