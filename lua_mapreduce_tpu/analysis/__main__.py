"""``lmr-analyze``: the analysis CLI.

    python -m lua_mapreduce_tpu.analysis \\
        [lint|deep|conc|protocol|task|rules|callgraph|all] [options]

``lint`` runs the per-function rule registry over the package (or
explicit paths); ``deep`` runs the interprocedural pass (call graph +
context propagation, LMR013+); ``conc`` runs the concurrency pass
(thread-spawn graph + interprocedural locksets + lock-order cycles,
LMR026-030) and re-finds the seeded races; ``task <module>...``
statically validates user task modules (contract + determinism +
lowerability verdict); ``protocol`` exhaustively model-checks the
lease lifecycle; ``callgraph`` prints the graph's size; ``all`` (the
default) runs lint + deep + conc + the stale-suppression audit +
protocol.

Exit code 0 = clean; with ``--fail-on-findings`` any surviving finding
exits 1 (the CI gate); ``--fail-on-stale`` exits 1 when a suppression
(inline pragma or baseline entry) no longer fires; a protocol violation
of the shipped model, an unresolvable/invalid task module, or a task
verdict differing from ``--expect`` always exits 1.

``--format json`` emits one machine-readable payload; ``--format
sarif`` (lint/deep/conc/task) emits SARIF 2.1.0 for CI/editor
annotation.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from lua_mapreduce_tpu.analysis import contracts as contracts_mod
from lua_mapreduce_tpu.analysis import dataflow as dataflow_mod
from lua_mapreduce_tpu.analysis import lint as lint_mod
from lua_mapreduce_tpu.analysis import protocol as proto_mod
from lua_mapreduce_tpu.analysis import sarif as sarif_mod


def _cmd_lint(args) -> tuple:
    findings = lint_mod.run_lint(args.paths or None,
                                 baseline=args.baseline)
    fail = bool(findings) and args.fail_on_findings
    return findings, fail


def _protocol_suite(args):
    """The default exhaustive sweep: the full lifecycle with worker
    death, then the failure path (release/mark-broken) on a smaller
    box, then the seeded-race regressions (each MUST be re-found)."""
    runs = []
    base = proto_mod.ModelConfig(n_workers=args.workers, n_jobs=args.jobs,
                                 batch_k=args.batch_k)
    runs.append(("lifecycle+death", base))
    runs.append(("failure-path", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2), allow_fail=True,
        allow_death=False)))
    # the reconstruct-vs-requeue scavenge edge (DESIGN §20): budgeted
    # data-loss events + repair + lost-data requeue, exhaustively — on
    # a 2-job box so loss×death interleavings stay tractable
    runs.append(("replica-recovery", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2),
        data_loss_budget=2)))
    # the duplicate-lease edge (DESIGN §21): speculate / claim_spec /
    # racing commits / revoke, exhaustively with worker death — PINNED
    # to a 2-worker 2-job box (~377k states; the spec dimension
    # multiplies the space, so the lifecycle box above stays spec-free,
    # and at 2 workers the model's tag-free claim_spec scan order
    # matches both engines exactly, keeping violation traces replayable)
    runs.append(("speculation", dataclasses.replace(
        base, n_workers=2, n_jobs=2,
        batch_k=min(args.batch_k, 2), allow_spec=True)))
    # the watch/notify edge (DESIGN §23): sleep / notify-wake /
    # timeout-fallback / lost-notification interleavings, exhaustively
    # with worker death — on a 2-job box (the wakeup-bit dimension
    # multiplies the space like the spec dimension does)
    runs.append(("notify-wakeup", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2),
        allow_notify=True)))
    # the erasure-coded decode ladder (DESIGN §27): block-at-a-time
    # loss (lose_parity) + decode-repair + the rerun rung, exhaustively
    # — same 2-job box as replica-recovery, with the budget spent one
    # block at a time instead of one copy at a time
    runs.append(("coded-recovery", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2),
        data_loss_budget=2, coded=True)))
    # the elastic join/leave edge (DESIGN §29): pool membership as
    # state — absent-worker join, idle-worker graceful retire — with
    # death, exhaustively on a 2-worker 2-job box (the membership
    # modes add little space; retire purity and the no-lease-abandoned
    # rule are the invariants that matter)
    runs.append(("elastic-pool", dataclasses.replace(
        base, n_workers=2, n_jobs=2, batch_k=min(args.batch_k, 2),
        elastic=True)))
    # the leader-lease/fencing edge (DESIGN §31): two contending
    # coordinators over one CAS lease — election, renewal, expiry
    # takeover, fenced zombie writes — exhaustively on a 2-job box
    # (the coordinator plane is job-transparent, so its invariants are
    # the overlap/zombie ones, not the job lifecycle)
    runs.append(("leader-lease", dataclasses.replace(
        base, n_jobs=2, batch_k=min(args.batch_k, 2), ha=True)))
    if args.seed_bug:
        bugs = [args.seed_bug]
    else:
        bugs = list(proto_mod.KNOWN_BUGS)
    out = []
    failed = False
    for name, cfg in runs:
        res = proto_mod.check_protocol(cfg)
        entry = {"run": name, "config": dataclasses.asdict(cfg),
                 "states": res.states, "transitions": res.transitions,
                 "quiescent_states": res.quiescent,
                 "wall_s": round(res.wall_s, 3), "ok": res.ok}
        if not res.ok:
            entry["violation"] = res.violation.message
            entry["trace"] = [list(t) for t in res.violation.trace]
            failed = True
        out.append(entry)
    for bug in bugs:
        extra = {}
        if bug in proto_mod.LOSS_BUGS:
            # loss-edge bugs are unreachable without loss events; the
            # smaller box keeps the seeded sweep fast
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         data_loss_budget=2)
        elif bug in proto_mod.SPEC_BUGS:
            # spec-edge bugs need the duplicate-lease dimension and a
            # second worker to hold the shadow lease (pinned to 2 for
            # trace replayability, like the exhaustive run)
            extra = dict(n_workers=2, n_jobs=2,
                         batch_k=min(args.batch_k, 2), allow_spec=True)
        elif bug in proto_mod.NOTIFY_BUGS:
            # notify-edge bugs need the wakeup dimension plus at least
            # one lost-notification event to be reachable
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         allow_notify=True)
        elif bug in proto_mod.ELASTIC_BUGS:
            # elastic-edge bugs need the pool-membership dimension and
            # a second worker (the last one starts absent)
            extra = dict(n_workers=2, n_jobs=2,
                         batch_k=min(args.batch_k, 2), elastic=True)
        elif bug in proto_mod.HA_BUGS:
            # HA-edge bugs need the coordinator plane: the lease clock,
            # two contenders, and the fencing guard on lead_write
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         ha=True)
        elif bug in proto_mod.CODED_BUGS:
            # coded-edge bugs need the stripe data plane and enough
            # budget to degrade a stripe (and, for the decode-blind
            # requeue, to re-run a producer into the mid-commit window)
            extra = dict(n_jobs=2, batch_k=min(args.batch_k, 2),
                         data_loss_budget=2, coded=True)
        cfg = dataclasses.replace(base, bug=bug, **extra)
        res = proto_mod.check_protocol(cfg)
        entry = {"run": f"seeded:{bug}", "states": res.states,
                 "wall_s": round(res.wall_s, 3),
                 "found": not res.ok}
        if res.ok:
            entry["error"] = ("seeded bug NOT detected — the checker "
                              "lost its teeth")
            failed = True
        else:
            entry["violation"] = res.violation.message
            entry["trace_len"] = len(res.violation.trace)
        out.append(entry)
    return {"protocol": out}, failed


def _cmd_conc(args) -> tuple:
    """The concurrency pass plus the seeded-race pins: every race in
    KNOWN_RACES must be re-found on its fixture (the protocol checker's
    discipline — a pass that stops seeing a planted race has quietly
    lost its teeth, and the gate must say so, not stay green)."""
    from lua_mapreduce_tpu.analysis import lockset as lockset_mod
    res = lockset_mod.analyze_conc(args.paths or None,
                                   baseline=args.baseline)
    fail = bool(res.findings) and args.fail_on_findings
    seeded = []
    for name, (_rel, rule, _src) in sorted(lockset_mod.KNOWN_RACES.items()):
        hits = lockset_mod.find_seeded(name)
        entry = {"run": f"seeded:{name}", "rule": rule,
                 "found": bool(hits)}
        if not hits:
            entry["error"] = ("seeded race NOT re-found — the conc "
                              "pass lost its teeth")
            fail = True
        seeded.append(entry)
    return res, seeded, fail


def _cmd_task(args) -> tuple:
    """Check every task-module spec; the payload carries one report per
    spec. Fails on findings (always — an invalid task module is never a
    soft result) and on an ``--expect`` verdict mismatch."""
    reports = [contracts_mod.check_task(spec) for spec in args.paths]
    expected = []
    for pin in args.expect_stage or ():
        name, _, want = pin.partition("=")
        if not want:
            raise SystemExit(f"--expect-stage needs NAME=VERDICT, "
                             f"got {pin!r}")
        expected.append((name, want))
    fail = False
    for rep in reports:
        if any(f.severity == "error" for f in rep.findings):
            fail = True
        if args.expect and rep.verdict != args.expect:
            fail = True
        if args.expect_ingraph_fn and not any(
                fr.verdict == contracts_mod.VERDICT_INGRAPH
                for fr in rep.functions.values()):
            fail = True
        stages = contracts_mod.stage_report(rep)
        for name, want in expected:
            if name in stages:
                got = "compiled" if stages[name]["compiled"] \
                    else "interpreted"
            else:
                fr = rep.functions.get(name)
                got = fr.verdict if fr is not None else "<missing>"
            if got != want:
                print(f"{rep.spec}: --expect-stage {name}={want} "
                      f"but oracle says {got}", file=sys.stderr)
                fail = True
    return reports, fail


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lua_mapreduce_tpu.analysis",
        description="framework-aware lint, interprocedural deep pass, "
                    "task-contract checker + lease-protocol model checker")
    ap.add_argument("command", nargs="?", default="all",
                    choices=("all", "lint", "deep", "conc", "protocol",
                             "rules", "task", "callgraph"))
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint, or task-module specs for "
                         "the task command (default: the package)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--fail-on-findings", action="store_true",
                    help="exit 1 when lint/deep findings survive "
                         "suppression")
    ap.add_argument("--fail-on-stale", action="store_true",
                    help="exit 1 when an inline pragma or baseline entry "
                         "no longer suppresses anything")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: analysis/baseline.json)")
    ap.add_argument("--expect", default=None,
                    choices=(contracts_mod.VERDICT_INGRAPH,
                             contracts_mod.VERDICT_STORE,
                             contracts_mod.VERDICT_INVALID),
                    help="task: required task-level verdict")
    ap.add_argument("--expect-ingraph-fn", action="store_true",
                    help="task: require at least one in-graph-eligible "
                         "function")
    ap.add_argument("--expect-stage", action="append", default=None,
                    metavar="NAME=VERDICT",
                    help="task: pin a per-stage lowering verdict "
                         "(repeatable; DESIGN §28). NAME is a hybrid "
                         "stage ('map'/'reduce', VERDICT "
                         "'compiled'/'interpreted') or a function name "
                         "('mapfn'..., VERDICT 'in-graph'/'store-plane'/"
                         "'invalid'); a mismatch fails the gate")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--jobs", type=int, default=3)
    ap.add_argument("--batch-k", type=int, default=2)
    ap.add_argument("--seed-bug", default=None,
                    choices=proto_mod.KNOWN_BUGS,
                    help="restrict the seeded-race regression to one bug")
    args = ap.parse_args(argv)

    if args.format == "sarif" and args.command not in ("lint", "deep",
                                                       "conc", "task"):
        ap.error("--format sarif applies to lint/deep/conc/task only")
    if args.fail_on_stale and args.command != "all":
        # only `all` runs the suppression audit — a scoped lint/deep
        # pass cannot tell live pragmas from stale ones, so honoring
        # the flag there would mint a permanently green gate
        ap.error("--fail-on-stale applies to the all command (it runs "
                 "the stale-suppression audit)")
    if args.fail_on_stale and args.paths:
        # a subset of the PACKAGE drops context seeds that live outside
        # it (an LMR014 helper's seed sits in store/), so live pragmas
        # would read as stale; self-contained external trees are fine
        from lua_mapreduce_tpu.analysis.lint import _PKG_ROOT
        for p in args.paths:
            ap_ = os.path.abspath(p)
            if ap_ != _PKG_ROOT and ap_.startswith(_PKG_ROOT + os.sep):
                ap.error("--fail-on-stale needs the whole package in "
                         "view: a package-scoped subset cannot tell "
                         "live pragmas (whose context seeds may live "
                         "outside it) from stale ones")

    if args.command == "rules":
        catalog = lint_mod.rule_catalog()
        if args.format == "json":
            print(json.dumps(catalog, indent=2))
        else:
            for r in catalog:
                print(f"{r['id']} [{r['severity']}] "
                      f"({', '.join(r['paths'])}): {r['title']}")
                print(f"    {r['rationale']}")
        return 0

    if args.command == "callgraph":
        from lua_mapreduce_tpu.analysis.callgraph import build_callgraph
        g = build_callgraph(args.paths or None)
        payload = {"callgraph": {
            "nodes": g.node_count(), "edges": g.edge_count(),
            "interface_methods": len(g.interface_methods()),
            "unresolved_calls": g.unresolved}}
        if args.format == "json":
            print(json.dumps(payload, indent=2))
        else:
            cg = payload["callgraph"]
            print(f"callgraph: {cg['nodes']} nodes, {cg['edges']} edges, "
                  f"{cg['interface_methods']} interface methods, "
                  f"{cg['unresolved_calls']} unresolved call sites")
        return 0

    if args.command == "task":
        if not args.paths:
            ap.error("task requires at least one module spec")
        reports, fail = _cmd_task(args)
        if args.format == "json":
            print(json.dumps(
                {"tasks": [contracts_mod.report_dict(r)
                           for r in reports]}, indent=2))
        elif args.format == "sarif":
            fs = [f for r in reports for f in r.findings]
            print(sarif_mod.format_sarif(fs))
        else:
            for r in reports:
                print(contracts_mod.format_text(r))
        return 1 if fail else 0

    payload = {}
    findings = None
    rc = 0
    if args.command == "lint":
        findings, fail = _cmd_lint(args)
        payload.update(lint_mod.report_dict(findings))
        rc = max(rc, 1 if fail else 0)
    if args.command == "deep":
        res = dataflow_mod.analyze(args.paths or None,
                                   baseline=args.baseline)
        findings = res.findings
        payload.update(lint_mod.report_dict(findings))
        payload["callgraph"] = {"nodes": res.graph.node_count(),
                                "edges": res.graph.edge_count(),
                                "reached": res.reached,
                                "wall_s": round(res.wall_s, 3)}
        rc = max(rc, 1 if findings and args.fail_on_findings else 0)
    if args.command == "conc":
        res, seeded, fail = _cmd_conc(args)
        findings = res.findings
        payload.update(lint_mod.report_dict(findings))
        payload["conc"] = {
            "locks": len(res.locks),
            "spawn_sites": len(res.tgraph.spawns),
            "thread_entries": len(res.tgraph.entries),
            "order_edges": len(res.order_edges),
            "cycles": [sorted(c) for c in res.cycles],
            "wall_s": round(res.wall_s, 3),
            "seeded": seeded}
        rc = max(rc, 1 if fail else 0)
    if args.command == "all":
        # one combined pass: per-function + deep findings with shared
        # suppression, plus the stale audit over both
        audit = lint_mod.run_audit(args.paths or None,
                                   baseline=args.baseline)
        findings = audit.findings
        payload.update(lint_mod.report_dict(findings))
        payload["stale_pragmas"] = audit.stale_pragmas
        payload["stale_baseline"] = audit.stale_baseline
        rc = max(rc, 1 if findings and args.fail_on_findings else 0)
        rc = max(rc, 1 if audit.stale and args.fail_on_stale else 0)
    if args.command in ("all", "protocol"):
        try:
            proto_payload, fail = _protocol_suite(args)
        except ValueError as e:
            # out-of-range --workers/--jobs/--batch-k is a usage error,
            # not a protocol violation
            ap.error(str(e))
        except RuntimeError as e:
            # an allowed-but-too-big box (3 workers × 4 jobs) exceeding
            # the state cap is equally a usage problem — report it
            # cleanly, don't traceback
            ap.error(f"{e}; try fewer workers/jobs")
        payload.update(proto_payload)
        rc = max(rc, 1 if fail else 0)

    if args.format == "sarif":
        print(sarif_mod.format_sarif(findings or []))
        return rc
    if args.format == "json":
        print(json.dumps(payload, indent=2))
        return rc
    if findings is not None:
        if findings:
            print(lint_mod.format_text(findings))
        label = {"lint": "lint", "deep": "deep",
                 "conc": "conc"}.get(args.command, "lint+deep+conc")
        print(f"{label}: {len(findings)} finding(s)")
    if "conc" in payload:
        c = payload["conc"]
        print(f"conc: {c['locks']} locks, {c['spawn_sites']} spawn "
              f"sites, {c['thread_entries']} thread entries, "
              f"{c['order_edges']} order edges, {len(c['cycles'])} "
              f"cycles, {c['wall_s']}s")
        for e in c["seeded"]:
            status = f"re-found {e['rule']}" if e["found"] else "MISSED"
            print(f"conc {e['run']}: {status}")
    if "callgraph" in payload:
        cg = payload["callgraph"]
        print(f"callgraph: {cg['nodes']} nodes, {cg['edges']} edges, "
              f"{cg['reached']} context-reached functions, "
              f"{cg['wall_s']}s")
    for p in payload.get("stale_pragmas", ()):
        print(f"{p['path']}:{p['line']}: stale suppression — "
              f"# lmr: disable={p['rule']} no longer fires")
    for e in payload.get("stale_baseline", ()):
        print(f"baseline: stale entry {e.get('rule')} @ "
              f"{e.get('path')}:{e.get('line', '*')} "
              f"({e.get('reason', '')}) no longer fires")
    if "stale_pragmas" in payload:
        n = len(payload["stale_pragmas"]) + len(payload["stale_baseline"])
        print(f"suppression audit: {n} stale entr"
              f"{'y' if n == 1 else 'ies'}")
    for entry in payload.get("protocol", ()):
        if entry["run"].startswith("seeded:"):
            status = ("re-found: " + entry["violation"]
                      if entry["found"] else "MISSED")
            print(f"protocol {entry['run']}: {status} "
                  f"[{entry['states']} states, {entry['wall_s']}s]")
        else:
            status = "ok" if entry["ok"] else \
                f"VIOLATION: {entry['violation']}"
            print(f"protocol {entry['run']}: {status} "
                  f"[{entry['states']} states, {entry['transitions']} "
                  f"transitions, {entry['quiescent_states']} quiescent, "
                  f"{entry['wall_s']}s]")
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `... | head` closed the pipe: not an error. Point stdout at
        # devnull so interpreter shutdown does not retry the flush.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
