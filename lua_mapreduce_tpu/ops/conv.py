"""conv2d — im2col + MXU matmul.

The TPU-native replacement for APRIL-ANN's CUDA conv kernels (SURVEY.md
§2.4, BASELINE.json LeNet-5/ResNet-18 configs). Design: a convolution is
a matmul in disguise — extract the (KH·KW·Cin) patch matrix with static
strided slices (pure data movement, fused by XLA) and push all FLOPs
through the tiled Pallas MXU matmul (ops/matmul.py), where
(N·Ho·Wo) × (KH·KW·Cin) × Cout is large, dense, and bf16-friendly. This
is how TPUs want convs: one big systolic-array contraction, not a
hand-scheduled sliding window.

Layouts: activations NHWC, weights HWIO — the TPU-native layouts (C last
= lane dimension).
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from lua_mapreduce_tpu.ops import resolve_backend
from lua_mapreduce_tpu.ops.matmul import matmul

Padding = Union[str, int, Tuple[int, int]]


def _norm_stride(s) -> Tuple[int, int]:
    return (s, s) if isinstance(s, int) else tuple(s)


def _same_pads(size: int, k: int, s: int) -> Tuple[int, int]:
    """TF-style SAME: output = ceil(size/s), low/high pads may differ
    (symmetric (k-1)//2 shrinks the output for even kernels)."""
    total = max((-(-size // s) - 1) * s + k - size, 0)
    return (total // 2, total - total // 2)


def _norm_padding(padding: Padding, kh: int, kw: int, h: int, w: int,
                  sh: int, sw: int):
    """→ ((ph_lo, ph_hi), (pw_lo, pw_hi))."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return ((0, 0), (0, 0))
        if p == "SAME":
            return (_same_pads(h, kh, sh), _same_pads(w, kw, sw))
        raise ValueError(f"unknown padding {padding!r}")
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    ph, pw = padding
    return ((ph, ph), (pw, pw))


def _im2col(x, kh: int, kw: int, sh: int, sw: int):
    """(N,H,W,C) → (N,Ho,Wo,KH·KW·C) patch tensor via KH·KW static
    strided slices; patch order (kh, kw, c) matches HWIO weight reshape."""
    n, h, w, c = x.shape
    ho = (h - kh) // sh + 1
    wo = (w - kw) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            cols.append(jax.lax.slice(
                x, (0, i, j, 0),
                (n, i + (ho - 1) * sh + 1, j + (wo - 1) * sw + 1, c),
                (1, sh, sw, 1)))
    return jnp.concatenate(cols, axis=-1), ho, wo


def conv2d(x, w, b=None, *, stride=1, padding: Padding = "VALID",
           backend: str = "auto"):
    """2-D convolution, NHWC × HWIO → NHWC.

    ``backend="pallas"``/``"pallas_interpret"`` routes the contraction
    through the Pallas MXU matmul; ``"xla"`` uses
    ``lax.conv_general_dilated`` (the reference implementation for
    correctness tests and non-TPU platforms).
    """
    backend = resolve_backend(backend, "conv2d")
    kh, kw, cin, cout = w.shape
    sh, sw = _norm_stride(stride)
    ph, pw = _norm_padding(padding, kh, kw, x.shape[1], x.shape[2], sh, sw)

    if backend == "xla":
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(sh, sw), padding=(ph, pw),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    else:
        if any(ph) or any(pw):
            x = jnp.pad(x, ((0, 0), ph, pw, (0, 0)))
        patches, ho, wo = _im2col(x, kh, kw, sh, sw)
        n = x.shape[0]
        out = matmul(patches.reshape(n * ho * wo, kh * kw * cin),
                     w.reshape(kh * kw * cin, cout),
                     backend=backend, out_dtype=x.dtype)
        out = out.reshape(n, ho, wo, cout)
    if b is not None:
        out = out + b
    return out
