"""Weight-only int8 matmul — the serving-side quantization kernel.

Decode is weight-bandwidth-bound: every generated token streams the
full parameter set from HBM while the MXU idles (kernels.json's decode
rows measure exactly this). Weight-only int8 halves that traffic — the
kernel reads int8 weight tiles from HBM, converts to bf16 in VMEM for
the MXU dot, and applies the per-output-channel scale ONCE on the f32
accumulator (out[:, j] = (x @ q)[:, j] · s[j], exact because the scale
is constant along the contraction), so nothing wider than int8 ever
crosses HBM for the weights. Activations stay bf16/f32: TPU MXUs take
same-typed operands, and weight-only (not activation) quantization is
the serving standard because activations are small and dynamic.

Quantization is symmetric per-output-channel: q = round(w / s),
s = max|w_col| / 127 — zero-point-free so the dot needs no correction
term. The XLA path (`backend="xla"`, non-TPU platforms, and the
correctness oracle) dequantizes then matmuls; under jit the dequantized
copy may be hoisted/materialized, which is exactly why the kernel
exists.

Reference role: the APRIL-ANN toolkit's kernel library (SURVEY.md §2.4)
— this extends the library the same way the reference would grow a new
CUDA kernel.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend


def quantize_q8(w, axis: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-channel int8: returns (q int8, s f32) with
    w ≈ q · s broadcast along ``axis`` (the contraction axis — scales
    live per OUTPUT channel). For a (K, N) weight use axis=0."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    s = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s.astype(jnp.float32)


def _dequant_matmul_xla(x, q, s):
    """Oracle / non-TPU path: dequantize then dot (f32 accumulate)."""
    w = q.astype(jnp.float32) * s
    return jax.lax.dot_general(
        x.astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)


def _q8_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, n_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # int8 tile → bf16 in VMEM; HBM only ever moved the int8 bytes
    wt = w_ref[...].astype(jnp.bfloat16)
    acc_scr[:] += jax.lax.dot_general(
        x_ref[...], wt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _():
        # per-output-channel scale, applied once on the accumulator
        o_ref[...] = (acc_scr[:] * s_ref[...]).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_m", "block_n", "block_k",
                                    "interpret"))
def _q8_matmul_pallas(x, q, s, block_m=256, block_n=512, block_k=512,
                      interpret=False):
    from lua_mapreduce_tpu.ops.matmul import _pad_to

    m, k = x.shape
    _, n = q.shape
    # clamp blocks to the (padded-to-tile) problem — same discipline as
    # ops/matmul.py: no streaming 512-wide weight tiles for an n=128
    # head projection, no whole-M VMEM block for a prefill-sized call
    block_m = min(block_m, max(8, -(-m // 8) * 8))
    block_n = min(block_n, max(128, -(-n // 128) * 128))
    block_k = min(block_k, max(128, -(-k // 128) * 128))
    xb = _pad_to(x.astype(jnp.bfloat16), block_m, block_k)
    qb = _pad_to(q, block_k, block_n)
    sb = _pad_to(s.reshape(1, n), 1, block_n)
    gm, gk = xb.shape[0] // block_m, xb.shape[1] // block_k
    gn = qb.shape[1] // block_n

    out = pl.pallas_call(
        functools.partial(_q8_kernel, n_k=gk),
        grid=(gm, gn, gk),
        in_specs=[
            pl.BlockSpec((block_m, block_k),
                         lambda mi, ni, ki: (mi, ki),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_k, block_n),
                         lambda mi, ni, ki: (ki, ni),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_n), lambda mi, ni, ki: (0, ni),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni, ki: (mi, ni),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((xb.shape[0], qb.shape[1]), x.dtype,
                             xb, qb, sb),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xb, qb, sb)
    return out[:m, :n]


def q8_matmul(x, q, s, *, backend: str = "auto", block_n: int = 512,
              block_k: int = 512):
    """x (M, K) @ dequant(q (K, N), s (N,)) → (M, K)·(K, N) = (M, N).

    ``backend="pallas"`` streams int8 weight tiles (the decode path);
    ``"xla"`` dequantizes then dots (oracle, non-TPU)."""
    if x.ndim != 2 or q.ndim != 2:
        raise ValueError(f"x and q must be rank-2; got {x.shape}, "
                         f"{q.shape}")
    if x.shape[1] != q.shape[0]:
        raise ValueError(f"contraction mismatch: x {x.shape} vs q "
                         f"{q.shape}")
    if q.dtype != jnp.int8:
        raise ValueError(f"q must be int8, got {q.dtype}")
    s = jnp.asarray(s)
    if s.size != q.shape[1]:
        raise ValueError(f"scale has {s.size} entries for {q.shape[1]} "
                         f"output channels")
    backend = resolve_backend(backend, "q8_matmul")
    if backend == "xla":
        return _dequant_matmul_xla(x, q, s.reshape(1, -1))
    return _q8_matmul_pallas(x, q, s.reshape(-1), block_n=block_n,
                             block_k=block_k,
                             interpret=backend == "pallas_interpret")


def utest() -> None:
    """Quantization round-trip + matmul parity at f32 tolerances."""
    import numpy as np

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(64, 96).astype(np.float32))
    q, s = quantize_q8(w)
    err = float(jnp.max(jnp.abs(q.astype(jnp.float32) * s - w)))
    assert err <= float(jnp.max(jnp.abs(w))) / 127.0 + 1e-6
    x = jnp.asarray(rng.randn(4, 64).astype(np.float32))
    got = q8_matmul(x, q, s.reshape(-1), backend="xla")
    want = x @ (q.astype(jnp.float32) * s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
