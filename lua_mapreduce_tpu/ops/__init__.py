"""TPU kernel library (Pallas) — the APRIL-ANN-toolkit equivalent.

The reference keeps its tensor kernels in the external APRIL-ANN C++/CUDA
toolkit (examples/APRIL-ANN/common.lua:3-4; SURVEY.md §2.4): matrix ops
(``axpy``, common.lua:133), conv/pool/softmax for its NN examples. This
package is the TPU-native replacement: Pallas kernels tiled for the MXU
(128×128 systolic array) and VPU, with XLA reference implementations used
for (a) correctness tests and (b) non-TPU backends.

Backend policy (``default_backend``): "pallas" on TPU, "xla" elsewhere.
Every op takes ``backend=`` with values "auto" | "pallas" | "xla" |
"pallas_interpret" (interpreter mode, for CPU tests of the kernel path).
"""

from __future__ import annotations

import jax


def default_backend() -> str:
    """'pallas' on TPU, 'xla' on CPU/GPU (Pallas-TPU kernels only lower
    on TPU; the interpreter is for tests, not production)."""
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def resolve_backend(backend: str) -> str:
    if backend == "auto":
        return default_backend()
    if backend not in ("pallas", "xla", "pallas_interpret"):
        raise ValueError(f"unknown backend {backend!r}")
    return backend


from lua_mapreduce_tpu.ops.matmul import matmul  # noqa: E402
from lua_mapreduce_tpu.ops.softmax import log_softmax, softmax  # noqa: E402
from lua_mapreduce_tpu.ops.conv import conv2d  # noqa: E402
from lua_mapreduce_tpu.ops.pool import avgpool2d, maxpool2d  # noqa: E402
from lua_mapreduce_tpu.ops.attention import flash_attention  # noqa: E402

__all__ = [
    "default_backend", "resolve_backend",
    "matmul", "log_softmax", "softmax", "conv2d",
    "maxpool2d", "avgpool2d", "flash_attention",
]
