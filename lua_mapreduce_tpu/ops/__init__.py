"""TPU kernel library (Pallas) — the APRIL-ANN-toolkit equivalent.

The reference keeps its tensor kernels in the external APRIL-ANN C++/CUDA
toolkit (examples/APRIL-ANN/common.lua:3-4; SURVEY.md §2.4): matrix ops
(``axpy``, common.lua:133), conv/pool/softmax for its NN examples. This
package is the TPU-native replacement: Pallas kernels tiled for the MXU
(128×128 systolic array) and VPU, with XLA reference implementations used
for (a) correctness tests and (b) non-TPU backends.

Backend policy (``default_backend``): per-op, measured, not dogmatic.
On TPU each op's ``auto`` resolves to whichever implementation the
committed kernel bench (benchmarks/results/kernels.json) shows faster on
real hardware — a hand-written kernel is a means, not an end, and for
some ops XLA's lowering is the better TPU program. Off-TPU everything
resolves to "xla" (Pallas-TPU kernels only lower on TPU). Every op takes
``backend=`` with values "auto" | "pallas" | "xla" | "pallas_interpret"
(interpreter mode, for CPU tests of the kernel path).
"""

from __future__ import annotations

import jax

# Measured on a TPU v5e (benchmarks/results/kernels.json, round-4
# windows 2026-07-31): XLA's conv lowering beats the im2col+Pallas path
# (46.1 vs 8.1 TF/s on the ResNet 56×56 block) STRUCTURALLY — the
# im2col patch round trip alone costs 1.75× XLA's whole runtime
# (DESIGN.md §8b), so conv2d is "xla" permanently for this shape class.
# Matmul: the sweep-tuned wide tiles (matmul_tune.json baked into
# _auto_blocks: (512-1024, 1024, 512)) measured 151.6 TF/s at 8192³ —
# 2.8× the round-2 256² schedule, 0.90× XLA's 169.2 — still fractionally
# under the ≥0.9× flip rule (0.896), so the policy holds at XLA: the
# kernel exists for fusion sites XLA can't express, not to re-win dense
# GEMM. The Pallas pooling kernel beats XLA's reduce_window ~2.7×.
# Flash is Pallas on BOTH grounds, measured on-chip with the
# sweep-tuned (512, 512) blocks (flash_tune.json, two sweep rounds):
#   speed — fwd 3.14× XLA at L=2048 and 9.69× at L=4096, fused
#   backward 3.99× (flash_*/flash_grad_* entries);
#   memory — the XLA composition's compiled buffer assignment holds
#   L²-sized temps across fwd+bwd (attn_memory.json, tpu section): 2.00
#   GiB of grad temps at (b=2, h=8, L=4096, d=128) vs the fused pair's
#   0.178 GiB of O(L) residents (11.3×; 4.06 GiB / 22.9× by L=8192),
#   the gap doubling per context doubling (the CPU buffer-assignment
#   analysis, DESIGN §9, shows the same growth at ~2× the absolute
#   temps) — while the Pallas pair (forward + FlashAttention-2
#   backward re-materializing p from the saved logsumexp) never
#   materializes O(L²).
# Softmax is a wash; XLA wins on fusion-with-neighbors grounds.
_TPU_AUTO_POLICY = {
    "matmul": "xla",
    "conv2d": "xla",
    "softmax": "xla",
    "maxpool2d": "pallas",
    "avgpool2d": "pallas",
    "flash_attention": "pallas",
    # weight-only int8: the kernel is the POINT (int8 tiles streamed
    # from HBM, dequant in VMEM) — the XLA composition materializes a
    # dequantized bf16 copy that jit hoists out of decode loops,
    # forfeiting the halved weight traffic the op exists for
    "q8_matmul": "pallas",
    # flash-decode (ops/decode.py): one query position vs the KV
    # cache, chunk-streamed with dynamic dead-chunk DMA elision —
    # built for the DESIGN §13 decode gap; first on-chip number
    # pending the next window (decode_* bench entries route through
    # greedy_decode and therefore through this policy)
    "decode_attention": "pallas",
}


def default_backend(op: str | None = None) -> str:
    """Resolved backend for ``op`` on the current platform: the measured
    per-op winner on TPU (see ``_TPU_AUTO_POLICY``), 'xla' elsewhere."""
    if jax.default_backend() != "tpu":
        return "xla"
    return _TPU_AUTO_POLICY.get(op, "pallas")


def out_struct(shape, dtype, *like) -> jax.ShapeDtypeStruct:
    """``pallas_call`` out_shape that survives shard_map's vma typing.

    JAX ≥0.9 checks varying-mesh-axes (vma) types inside ``shard_map``
    and rejects a plain ``ShapeDtypeStruct`` out_shape; the output of a
    kernel varies over exactly the union of axes its operands vary over,
    so that union is propagated from ``like``. Outside shard_map every
    operand's vma is empty and this degrades to the plain struct.
    """
    try:
        vma = (frozenset().union(*(jax.typeof(a).vma for a in like))
               if like else frozenset())
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        # older JAX: no jax.typeof/.vma/vma kwarg — and no vma checking
        return jax.ShapeDtypeStruct(shape, dtype)


def resolve_backend(backend: str, op: str | None = None) -> str:
    if backend == "auto":
        return default_backend(op)
    if backend not in ("pallas", "xla", "pallas_interpret"):
        raise ValueError(f"unknown backend {backend!r}")
    return backend


from lua_mapreduce_tpu.ops.matmul import matmul  # noqa: E402
from lua_mapreduce_tpu.ops.softmax import log_softmax, softmax  # noqa: E402
from lua_mapreduce_tpu.ops.conv import conv2d  # noqa: E402
from lua_mapreduce_tpu.ops.pool import avgpool2d, maxpool2d  # noqa: E402
from lua_mapreduce_tpu.ops.attention import flash_attention  # noqa: E402
from lua_mapreduce_tpu.ops.decode import decode_attention  # noqa: E402
from lua_mapreduce_tpu.ops.q8 import q8_matmul, quantize_q8  # noqa: E402

__all__ = [
    "default_backend", "resolve_backend",
    "matmul", "log_softmax", "softmax", "conv2d",
    "maxpool2d", "avgpool2d", "flash_attention", "decode_attention",
    "q8_matmul", "quantize_q8",
]
