"""maxpool2d / avgpool2d — VPU window reductions.

APRIL-ANN's pooling kernels (SURVEY.md §2.4, BASELINE.json LeNet config)
re-expressed for TPU: a pooling window is KH·KW static strided slices
combined elementwise on the VPU — no sliding-window loop, no dynamic
shapes, and the batch dimension is the pipeline grid (one image's
activation block in VMEM at a time).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend
from lua_mapreduce_tpu.ops.conv import _norm_stride


def _pool_kernel(x_ref, o_ref, *, kh, kw, sh, sw, ho, wo, mode):
    # Mosaic can't lower strided vector slices, so downsampling-by-stride
    # is expressed as unstrided slice → reshape → take lane 0: the
    # elements at i + m·sh are exactly reshape(ho, sh, …)[:, 0]. The
    # input block is pre-padded so every slice is full-size; padding
    # never lands in a kept lane.
    x = x_ref[0]
    c = x.shape[-1]
    acc = None
    for i in range(kh):
        for j in range(kw):
            sl = jax.lax.slice(x, (i, j, 0),
                               (i + ho * sh, j + wo * sw, c))
            sl = sl.reshape(ho, sh, wo, sw, c)[:, 0, :, 0, :]
            if acc is None:
                acc = sl if mode == "max" else sl.astype(jnp.float32)
            elif mode == "max":
                acc = jnp.maximum(acc, sl)
            else:
                acc = acc + sl
    if mode == "avg":
        acc = (acc / (kh * kw)).astype(o_ref.dtype)
    o_ref[0] = acc


@functools.partial(jax.jit,
                   static_argnames=("window", "stride", "mode", "interpret"))
def _pool_pallas(x, window, stride, mode, interpret=False):
    kh, kw = window
    sh, sw = stride
    n, h, w, c = x.shape
    ho = (h - kh) // sh + 1
    wo = (w - kw) // sw + 1
    hp, wp = (kh - 1) + ho * sh, (kw - 1) + wo * sw   # slice headroom
    if hp > h or wp > w:
        x = jnp.pad(x, ((0, 0), (0, hp - h), (0, wp - w), (0, 0)))
    return pl.pallas_call(
        functools.partial(_pool_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                          ho=ho, wo=wo, mode=mode),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, hp, wp, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, ho, wo, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((n, ho, wo, c), x.dtype, x),
        # each image is independent — let Mosaic parallelize the batch
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x)


def _pool_xla(x, window, stride, mode):
    # KH·KW static strided slices combined elementwise — NOT
    # lax.reduce_window, which has no linearization rule and kills
    # reverse-mode AD under shard_map/scan (the DP-trainer hot path).
    kh, kw = window
    sh, sw = stride
    n, h, w, c = x.shape
    ho = (h - kh) // sh + 1
    wo = (w - kw) // sw + 1
    out = None
    for i in range(kh):
        for j in range(kw):
            s = jax.lax.slice(
                x, (0, i, j, 0),
                (n, i + (ho - 1) * sh + 1, j + (wo - 1) * sw + 1, c),
                (1, sh, sw, 1))
            if out is None:
                out = s
            else:
                out = jnp.maximum(out, s) if mode == "max" else out + s
    if mode == "avg":
        out = out / (kh * kw)
    return out


# Pallas calls have no JVP rule; the backward pass reuses XLA's
# reduce-window gradient (select-and-scatter for max, uniform spread for
# avg) by differentiating the XLA forward — pooling is cheap, the extra
# forward in bwd is noise next to the convs around it.
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pool_p(x, cfg):
    window, stride, mode, interpret = cfg
    return _pool_pallas(x, window, stride, mode, interpret=interpret)


def _pool_p_fwd(x, cfg):
    return _pool_p(x, cfg), x


def _pool_p_bwd(cfg, x, g):
    window, stride, mode, _ = cfg
    _, vjp = jax.vjp(lambda x: _pool_xla(x, window, stride, mode), x)
    return vjp(g)


_pool_p.defvjp(_pool_p_fwd, _pool_p_bwd)


def _pool(x, window, stride, mode, backend):
    backend = resolve_backend(backend, f"{mode}pool2d")
    window = _norm_stride(window)
    stride = _norm_stride(stride if stride is not None else window)
    if backend == "xla":
        return _pool_xla(x, window, stride, mode)
    return _pool_p(x, (window, stride, mode,
                       backend == "pallas_interpret"))


def maxpool2d(x, window=2, stride=None, *, backend: str = "auto"):
    """VALID max pooling over NHWC; stride defaults to the window."""
    return _pool(x, window, stride, "max", backend)


def avgpool2d(x, window=2, stride=None, *, backend: str = "auto"):
    """VALID average pooling over NHWC; stride defaults to the window."""
    return _pool(x, window, stride, "avg", backend)
