"""Fused decode attention — one query position against the KV cache.

The serving-side gap DESIGN §13 quantifies: the decode scan's per-step
attention reads the ENTIRE padded cache (0.5 GB at the bench shape)
through an XLA einsum+mask+softmax+einsum chain shaped badly for the
TPU — a (B, 1) query has no q axis to tile onto the MXU, the mask and
f32 score row materialize per step, and slots beyond the current
position are streamed only to be masked. This kernel is the
flash-decode form of §9's playbook: stream the cache ONCE through VMEM
in (block_s, D) tiles, fold scores into an online-softmax accumulator,
and — because the grid's chunk axis is driven by a SCALAR-PREFETCHED
position ``t`` — clamp dead chunks onto the live range so their DMAs
are elided entirely (the §9 dead-tile trick, dynamic this time).
Cache traffic per step drops from O(S) to O(t), and the masked-score
materialization disappears.

Layout contract: callers hold decode caches as (B, H_kv, S, D) — the
per-(batch, head) cache rows are contiguous, so the kernel (and XLA)
stream them without a per-step transpose. ``models/transformer.py``'s
``greedy_decode`` owns that layout; its public ``prefill`` contract
stays (B, S, H_kv, D) and is transposed ONCE at the boundary.

The XLA path reproduces the previous in-scan composition
operation-for-operation (same dot dtypes, same f32 softmax, same
where-mask), so ``backend="xla"`` — the off-TPU resolution — is
bit-identical to the code it replaced and every token-exactness pin
keeps meaning what it meant.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend
from lua_mapreduce_tpu.ops.attention import _LANES, _tile_mask

_NEG_INF = -1e30


def _rows(scr):
    """(G, _LANES) lane-replicated scratch → (G, 1) row values (lanes
    all equal; max is exact — §9's row-state convention)."""
    return jnp.max(scr[...], axis=-1, keepdims=True)


def quantize_kv(x, axis: int = -1):
    """Per-row symmetric int8 quantization of cache rows: ``x``
    (..., D) → (int8 rows, f32 scales (...,)). Row scale = amax/127 —
    the KV-cache twin of ops/q8.py's per-channel weight scheme (the
    cache is written once per position, so the scale granularity is
    the position row)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _decode_xla(q, k, v, t, roll: bool, k_scale=None, v_scale=None):
    """Reference composition — exactly the ops the decode scan ran
    in-line before this module existed (models/transformer.py), with
    the q-length-1 axis dropped and the (B, H_kv, S, D) cache layout.
    With int8 caches (``k_scale``/``v_scale`` per (B, H_kv, S) row)
    the scales factor OUT of both contractions — s columns scale by
    k_scale, p rows by v_scale — and the p·v operands round at bf16,
    the same algebra and rounding points as the kernel. Returns f32
    (B, H_kv, G, D)."""
    b, hkv, g, d = q.shape
    s_len = k.shape[2]
    if k_scale is None:
        s = jnp.einsum("bkgd,bkmd->bkgm", q, k,
                       preferred_element_type=jnp.float32)
    else:
        # int8 rows are exact in bf16 (integers ≤ 256), so this dot is
        # the f32 product-accumulation of (q, k_q8) — the scale then
        # restores magnitudes per column
        s = jnp.einsum("bkgd,bkmd->bkgm", q.astype(jnp.bfloat16),
                       k.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        s = s * k_scale[:, :, None, :]
    s = s / jnp.sqrt(jnp.float32(d))
    seen = jnp.arange(s_len)[None, None, None, :]
    if roll:
        # rolling containment IS the window (models/transformer.py):
        # mask only slots not yet filled; a full cache is all-visible
        vis = (seen <= t) | (t >= s_len)
    else:
        # the SHARED mask definition (attention.py _tile_mask) at
        # row = t: decode's windowed case is roll (window < total),
        # so window here is structurally 0
        vis = _tile_mask(t, seen, True, 0, s_len)
    s = jnp.where(vis, s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    if v_scale is None:
        return jnp.einsum("bkgm,bkmd->bkgd", w.astype(v.dtype), v,
                          preferred_element_type=jnp.float32)
    w = (w * v_scale[:, :, None, :]).astype(jnp.bfloat16)
    return jnp.einsum("bkgm,bkmd->bkgd", w, v.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)


def _decode_kernel(t_ref, q_ref, k_ref, v_ref, *rest,
                   block_s, s_len, scale, roll, n_chunks, q8):
    """One (batch·kv-head) row: fold cache chunk ``ki`` into the
    online-softmax state. Row state is lane-replicated (G, _LANES)
    per §9's Mosaic legality rule. With ``q8``, k/v arrive int8 and
    two extra (block_s, 1) scale refs follow — the k scale multiplies
    score COLUMNS after the dot, the v scale folds into p before the
    value dot, so no dequantized tile ever materializes."""
    if q8:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    ki = pl.program_id(1)
    t = t_ref[0]

    @pl.when(ki == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    @pl.when(ki * block_s <= t)
    def _():
        q = q_ref[0]                                   # (G, D)
        k = k_ref[0]                                   # (block_s, D)
        v = v_ref[0]                                   # (block_s, D)
        if q8:
            # int8 rows are exact in bf16 (integers ≤ 256): the dot is
            # the exact product-accumulation, scales restore magnitude
            q = q.astype(jnp.bfloat16)
            k = k.astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (G, block_s)
        if q8:
            s = s * ks_ref[0][:, 0][None, :]
        s = s * scale
        col = ki * block_s + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        vis = col < s_len
        live = (col <= t) | (t >= s_len) if roll else (col <= t)
        s = jnp.where(vis & live, s, _NEG_INF)
        # ragged final block: out-of-bounds v rows hold unspecified
        # values (NaN in interpret mode); their p weight is exp(-inf)=0
        # but 0·NaN = NaN, so the rows must be zeroed before the dot
        row = ki * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (block_s, 1), 0)
        v = jnp.where(row < s_len, v, 0).astype(v.dtype)

        m_prev = _rows(m_scr)                          # (G, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # (G, block_s)
        l_prev = _rows(l_scr)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if q8:
            # OOB scale lanes are unspecified like OOB v rows — zero
            # them for the same 0·NaN reason
            vs = jnp.where(row[:, 0] < s_len, vs_ref[0][:, 0], 0.0)
            p = p * vs[None, :]
            pv = jax.lax.dot_general(
                p.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        acc[...] = acc[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_chunks - 1)
    def _():
        o_ref[0] = acc[...] / jnp.maximum(_rows(l_scr), 1e-30)


@functools.partial(
    jax.jit,
    static_argnames=("roll", "block_s", "interpret"))
def _decode_pallas(q, k, v, t, roll: bool = False, block_s: int = 512,
                   interpret: bool = False, k_scale=None, v_scale=None):
    b, hkv, g, d = q.shape
    s_len = k.shape[2]
    q8 = k_scale is not None
    block_s = min(block_s, max(128, -(-s_len // 128) * 128))
    # ceil-divided grid, NO padding: k/v ride the decode scan's carry,
    # so a jnp.pad here would copy the whole cache every generated
    # token — the O(S) per-step traffic this kernel exists to kill.
    # Pallas masks the ragged final block itself; its out-of-bounds
    # lanes surface as undefined values in `s`, which the explicit
    # `col < s_len` mask sends to -inf before they touch the softmax.
    n_chunks = -(-s_len // block_s)
    qb = q.reshape(b * hkv, g, d)
    kb = k.reshape(b * hkv, s_len, d)
    vb = v.reshape(b * hkv, s_len, d)
    scale = 1.0 / float(d) ** 0.5
    tarr = jnp.asarray(t, jnp.int32).reshape(1)

    def chunk(ki, t_ref):
        # dead-chunk DMA elision, dynamic form: chunks past the live
        # position clamp onto the last live chunk — consecutive equal
        # indices skip the copy; compute is pl.when-guarded anyway
        return jnp.minimum(ki, jnp.maximum(t_ref[0], 0) // block_s)

    qspec = pl.BlockSpec((1, g, d), lambda r, ki, t_ref: (r, 0, 0),
                         memory_space=pltpu.VMEM)
    cspec = pl.BlockSpec((1, block_s, d),
                         lambda r, ki, t_ref: (r, chunk(ki, t_ref), 0),
                         memory_space=pltpu.VMEM)
    in_specs = [qspec, cspec, cspec]
    operands = [tarr, qb, kb, vb]
    if q8:
        # scales ride as (rows, S, 1) so the (block_s, 1) block keeps
        # Mosaic's trailing-dims rule (1 == array's own trailing dim)
        sspec = pl.BlockSpec(
            (1, block_s, 1),
            lambda r, ki, t_ref: (r, chunk(ki, t_ref), 0),
            memory_space=pltpu.VMEM)
        in_specs += [sspec, sspec]
        operands += [k_scale.reshape(b * hkv, s_len, 1),
                     v_scale.reshape(b * hkv, s_len, 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, n_chunks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, g, d), lambda r, ki, t_ref: (r, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((g, d), jnp.float32),
                        pltpu.VMEM((g, _LANES), jnp.float32),
                        pltpu.VMEM((g, _LANES), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s, s_len=s_len,
                          scale=scale, roll=roll, n_chunks=n_chunks,
                          q8=q8),
        grid_spec=grid_spec,
        out_shape=out_struct((b * hkv, g, d), jnp.float32, qb, kb, vb),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, hkv, g, d)


def decode_attention(q, k, v, t, *, roll: bool = False,
                     backend: str = "auto", block_s: int = 512,
                     k_scale=None, v_scale=None):
    """One decode position's attention against the KV cache.

    q: (B, H_kv, G, D) — the G query heads grouped under each kv head
    (G = 1 is plain MHA); k, v: (B, H_kv, S, D) caches; ``t``: scalar
    int32 current position. Slots with index > t are invisible unless
    ``roll`` and the rolling cache is full (every slot then holds a
    live position — models/transformer.py's rolling-containment rule).

    int8 KV cache: pass k/v as int8 with ``k_scale``/``v_scale`` f32
    per-row scales, shape (B, H_kv, S) — :func:`quantize_kv` produces
    them. Cache HBM traffic halves (the dominant decode byte stream);
    the scales factor out of both contractions so neither path
    materializes a dequantized cache. Returns f32 (B, H_kv, G, D).
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    backend = resolve_backend(backend, "decode_attention")
    if backend == "xla":
        return _decode_xla(q, k, v, t, roll, k_scale, v_scale)
    return _decode_pallas(q, k, v, t, roll=roll, block_s=block_s,
                          interpret=backend == "pallas_interpret",
                          k_scale=k_scale, v_scale=v_scale)
