"""Tiled MXU matmul — the workhorse kernel.

All conv FLOPs route through this kernel (im2col → matmul, ops/conv.py),
the same role APRIL-ANN's BLAS/CUDA gemm plays for the reference's models
(SURVEY.md §2.4). Classic Pallas schedule: 3-D grid (M, N, K tiles), A and
B tiles streamed HBM→VMEM by the pipeline, partial products accumulated in
a float32 VMEM scratch across the K dimension, output written once on the
last K step. K is the innermost ("arbitrary") grid dimension so the
accumulator is live for exactly one (i, j) tile at a time.

Block sizes default to a size-adaptive schedule (see ``_auto_blocks``):
the kernel's HBM traffic is ``2·m·n·k·itemsize·(1/bm + 1/bn)`` bytes, so
fixed 256-tiles cap large bf16 matmuls at a ~64 TF/s bandwidth roofline
on a v5e (measured: 20.5 ms at 8192³ ≡ the roofline's 21 ms prediction,
benchmarks/results/kernels.json) while 512-tiles double the arithmetic
intensity into compute-bound territory. Full analysis and the measured
evidence trail: docs/DESIGN.md §matmul; the on-chip sweep that validates
or overrides these defaults is benchmarks/matmul_tune.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend


def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pad_to(x, m_mult, n_mult):
    m, n = x.shape
    pm, pn = -m % m_mult, -n % n_mult
    if pm or pn:
        x = jnp.pad(x, ((0, pm), (0, pn)))
    return x


def _auto_blocks(m: int, n: int, k: int) -> tuple:
    """Size-adaptive (bm, bn, bk), set by the on-chip sweep.

    HBM traffic is ``2·m·n·k·itemsize·(1/bm + 1/bn)`` (A re-read once
    per N-tile, B once per M-tile; bk cancels), so the M/N tiles set
    the arithmetic intensity: 256² tiles bound bf16 at ~64 TF/s on a
    v5e's ~820 GB/s — under half the 197 TF/s MXU peak — while the
    wide tiles here lift the roofline past peak (compute-bound). The
    round-4 sweep (benchmarks/matmul_tune.py →
    results/matmul_tune.json, v5e 2026-07-31) measured the winners:
    (1024, 1024, 512) at 4096³ (152.7 TF/s) and (512, 1024, 512) at
    8192³ (171.4 TF/s in the sweep; 151.6 = 0.896× XLA through the
    standard bench that governs the auto policy, kernels.json — the
    shallower bm wins there on VMEM/pipeline pressure: the f32 acc at
    bm=1024 is 4 MB). VMEM at
    (512, 1024, 512) bf16: double-buffered A+B 3 MB + f32 acc 2 MB +
    out 1 MB ≈ 6 MB of the ~16 MB budget. Small problems keep 256²
    (less padding waste, the pipeline still overlaps); tiny dims clamp
    in _matmul_pallas as before."""
    if min(m, n) >= 1024 and k >= 512:
        # bk stays a multiple of the 128-lane native tiling (a raw
        # k//4 could be e.g. 625 and break Mosaic lowering); it cancels
        # out of the traffic formula — the sweep found deeper bk only
        # pays at bm=bn=512 (old schedule), not at the wide winners
        return (512 if max(m, n) >= 8192 else 1024), 1024, 512
    return 256, 256, 256


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "block_n", "block_k", "out_dtype",
                     "interpret"))
def _matmul_pallas(a, b, block_m: int | None = None,
                   block_n: int | None = None, block_k: int | None = None,
                   out_dtype=None, interpret=False):
    m, k = a.shape
    k2, n = b.shape
    if k != k2:    # not assert: must survive python -O, else _pad_to
        raise ValueError(f"contracting dims differ: {k} vs {k2}")
    for nm, v in (("block_m", block_m), ("block_n", block_n),
                  ("block_k", block_k)):
        if v is not None and v <= 0:
            raise ValueError(f"{nm} must be positive, got {v}")
    auto_m, auto_n, auto_k = _auto_blocks(m, n, k)
    block_m = auto_m if block_m is None else block_m
    block_n = auto_n if block_n is None else block_n
    block_k = auto_k if block_k is None else block_k
    out_dtype = out_dtype or jnp.promote_types(a.dtype, b.dtype)

    # clamp blocks to the (padded-to-tile) problem, keep MXU/VPU alignment
    block_m = min(block_m, max(8, -(-m // 8) * 8))
    block_n = min(block_n, max(128, -(-n // 128) * 128))
    block_k = min(block_k, max(128, -(-k // 128) * 128))

    ap = _pad_to(a, block_m, block_k)
    bp = _pad_to(b, block_k, block_n)
    gm, gk = ap.shape[0] // block_m, ap.shape[1] // block_k
    gn = bp.shape[1] // block_n

    out = pl.pallas_call(
        functools.partial(_matmul_kernel, n_k=gk),
        grid=(gm, gn, gk),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((ap.shape[0], bp.shape[1]), out_dtype,
                             ap, bp),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(ap.size + bp.size) * ap.dtype.itemsize
            + m * n * jnp.dtype(out_dtype).itemsize,
            transcendentals=0),
        interpret=interpret,
    )(ap, bp)
    return out[:m, :n]


# Pallas calls have no JVP rule — training needs an explicit VJP. The
# backward pass is two more MXU matmuls: dA = g·Bᵀ, dB = Aᵀ·g.
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _mm(a, b, cfg):
    block_m, block_n, block_k, out_dtype, interpret = cfg
    return _matmul_pallas(a, b, block_m=block_m, block_n=block_n,
                          block_k=block_k, out_dtype=out_dtype,
                          interpret=interpret)


def _mm_fwd(a, b, cfg):
    return _mm(a, b, cfg), (a, b)


def _mm_bwd(cfg, res, g):
    a, b = res
    block_m, block_n, block_k, _, interpret = cfg
    da = _matmul_pallas(g, b.T, block_m=block_m, block_n=block_n,
                        block_k=block_k, out_dtype=a.dtype,
                        interpret=interpret)
    db = _matmul_pallas(a.T, g, block_m=block_m, block_n=block_n,
                        block_k=block_k, out_dtype=b.dtype,
                        interpret=interpret)
    return da, db


_mm.defvjp(_mm_fwd, _mm_bwd)


def matmul(a, b, *, backend: str = "auto", block_m: int = None,
           block_n: int = None, block_k: int = None, out_dtype=None):
    """``a @ b`` with float32 MXU accumulation.

    Inputs may be any float dtype (bfloat16 recommended on TPU — the MXU
    natively consumes bf16 and accumulates f32); output defaults to the
    promoted input dtype. Differentiable via a custom VJP whose backward
    matmuls run through the same Pallas kernel. Block sizes default to
    the size-adaptive schedule (``_auto_blocks``); explicit values
    override (benchmarks/matmul_tune.py sweeps them on hardware).
    """
    backend = resolve_backend(backend, "matmul")
    if backend == "xla":
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
            out_dtype or jnp.promote_types(a.dtype, b.dtype))
    out_dtype = out_dtype or jnp.promote_types(a.dtype, b.dtype)
    cfg = (block_m, block_n, block_k, out_dtype,
           backend == "pallas_interpret")
    return _mm(a, b, cfg)
