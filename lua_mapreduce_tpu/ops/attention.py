"""Fused flash attention — MXU matmuls with an online softmax in VMEM.

Completes the kernel library (SURVEY.md §2.4's APRIL-ANN-kernel role) for
the transformer family: one `pallas_call` computes softmax(QKᵀ·scale)·V
without ever materializing the (L, L) score matrix in HBM — scores live
in VMEM one (block_q, block_k) tile at a time, folded into running
(max, denominator, output) accumulators in f32 scratch. The ring
schedule (parallel/ring_attention.py) runs THIS kernel as its local
fold — ``return_lse`` exposes the mergeable-softmax state, and partial
attentions over disjoint KV shards combine by logaddexp weights — so
ring = flash with the KV loop distributed over ICI, literally.

Grid: (batch·heads, q-blocks, kv-blocks); the kv axis is the innermost
(sequential) dimension, accumulating into scratch and writing the
normalized output tile on its last step — the accumulator discipline of
ops/matmul.py. Causal masking compares global row/column indices built
from the program ids; padded tail rows/columns are masked the same way.

Backward: fused too (FlashAttention-2 shape). The forward saves only
(q, k, v, o, per-row logsumexp); the backward re-materializes each
(block_q, block_k) probability tile in VMEM from those — p = exp(s −
lse) — and accumulates dq in one kernel (kv innermost) and dk/dv in a
second (q innermost). No (L, L) matrix ever touches HBM in EITHER
direction, so training through the kernel is O(L·d) memory like
inference — previously the custom VJP re-ran the XLA composition,
paying the O(L²) HBM the forward existed to avoid.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend

_NEG_INF = -1e30

# Row-state arrays (running max / denominator / logsumexp / Δ) are
# lane-REPLICATED inside kernels. Mosaic requires every block's trailing
# two dims to be (divisible by 8, divisible by 128) or equal to the
# array's — a (1, block_q) row block fails that whenever b·h > 1, so
# per-row scalars ride as (block_q, _LANES) tiles whose lanes all hold
# the same value. Reads collapse lanes with a max (exact: all lanes
# equal); writes broadcast. 8 lanes, not 128: the block's lane dim then
# EQUALS the array's lane dim (the same legality clause head_dim < 128
# q/k/v blocks use), at 1/16th the HBM of full-width replication. CPU
# interpret mode never enforces any of this — round 3's suite was green
# while the kernel could not lower on the chip, which is exactly what
# the round-4 hardware window exposed.
_LANES = 8


def _row_read(ref):
    """(1, block_q, _LANES) lane-replicated ref → (block_q, 1) value."""
    return jnp.max(ref[0], axis=-1, keepdims=True)


def _lane_rep(x):
    """(bh, l) row array → (bh, l, _LANES) lane-replicated operand."""
    return jnp.broadcast_to(x[:, :, None], (*x.shape, _LANES))


def _tile_mask(rows, cols, causal: bool, window: int, seq_len: int,
               q_offset: int = 0):
    """Visibility of (row, col) score entries — THE mask definition,
    shared by the forward kernel, the backward tile re-materialization,
    and the XLA oracle so the three can never drift. ``window`` > 0
    additionally hides keys more than window-1 positions behind the
    query (sliding-window attention; implies causal). ``q_offset``
    shifts the query rows globally relative to the key columns — the
    banded-ring case where this call's q block sits q_offset positions
    AFTER its kv block (ring step i → offset i·L_loc, a STATIC value
    because the windowed ring unrolls its steps)."""
    rows = rows + q_offset
    valid = cols < seq_len
    if causal:
        valid = valid & (rows >= cols)
    if window:
        valid = valid & (rows - cols < window)
    return valid


def _tile_live(qi, ki, block_q: int, block_k: int, causal: bool,
               window: int, q_offset: int = 0):
    """Whether tile (qi, ki) contains ANY visible score — the block-skip
    predicate (None = statically always live). Causal prunes tiles
    wholly above the diagonal; a window additionally prunes tiles wholly
    behind it (~L/window of the causal work at long L)."""
    row0 = qi * block_q + q_offset
    conds = []
    if causal:
        conds.append(ki * block_k <= row0 + block_q - 1)
    if window:
        conds.append(row0 - (ki * block_k + block_k - 1) < window)
    if not conds:
        return None
    live = conds[0]
    for c in conds[1:]:
        live = jnp.logical_and(live, c)
    return live


def _kv_clamp(qi, ki, *, block_q, block_k, causal, window, q_offset,
              n_kv):
    """Clamp a kv block index into q-block ``qi``'s LIVE range — the
    dead-tile DMA elision. ``pl.when`` skips the masked COMPUTE, but the
    pipeline still fetches every tile the index map names; re-mapping a
    dead step onto the nearest live block makes consecutive indices
    equal, and Pallas skips the copy when the index does not change.
    Causal halves kv traffic; a sliding window cuts it to O(window/L).
    Exactly _tile_live's algebra: live ⟹ clamp is the identity, so live
    steps always see their own tile (pinned by the interpret-mode parity
    suite across causal/window/offset/GQA)."""
    if not (causal or window):
        return ki
    row0 = qi * block_q + q_offset
    hi = ((row0 + block_q - 1) // block_k) if causal else n_kv - 1
    lo = ((row0 - window + 1) // block_k) if window else 0
    # bounds sanitization: a fully-dead geometry (every tile of this
    # grid row pruned) may cross the bounds or push them out of range;
    # the clamp must still emit an IN-RANGE index (any one — compute is
    # skipped), never a negative or overflowing DMA offset
    lo = jnp.clip(lo, 0, n_kv - 1)
    hi = jnp.clip(hi, lo, n_kv - 1)
    return jnp.clip(ki, lo, hi)


def _q_clamp(qi, ki, *, block_q, block_k, causal, window, q_offset,
             n_q):
    """The dkv-kernel twin of _kv_clamp: clamp a q block index into kv
    block ``ki``'s live range (q innermost there). Same liveness
    algebra transposed: causal gives the LOWER bound (q blocks above
    the diagonal are dead), the window gives the UPPER bound (q rows
    too far past the kv block see nothing)."""
    if not (causal or window):
        return qi
    lo = ((ki * block_k - q_offset) // block_q) if causal else 0
    # strict inequality: row0 < ki·bk + bk - 1 + window - q_offset,
    # so the last live block is (T - 1) // bq
    hi = (((ki * block_k + block_k - 2 + window - q_offset) // block_q)
          if window else n_q - 1)
    # same bounds sanitization as _kv_clamp (hi can go NEGATIVE here
    # when the kv block sits wholly behind the window — the banded
    # ring's far hop): crossed bounds must still yield in-range indices
    lo = jnp.clip(lo, 0, n_q - 1)
    hi = jnp.clip(hi, lo, n_q - 1)
    return jnp.clip(qi, lo, hi)


def _attn_reference_xla(q, k, v, causal: bool, scale: float,
                        with_lse: bool = False, window: int = 0,
                        q_offset: int = 0):
    group = q.shape[2] // k.shape[2]
    if group > 1:                   # GQA: each kv head serves a group
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = None
    if causal or window:
        lq, lk = s.shape[-2], s.shape[-1]
        rows = jnp.arange(lq)[:, None]
        cols = jnp.arange(lk)[None, :]
        mask = _tile_mask(rows, cols, causal, window, lk, q_offset)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        # a row with NO visible column (q_offset pushes it more than
        # `window` past every key — the banded-ring far block) must emit
        # ZERO, matching the kernel's convention (out 0, lse ≈ -inf, so
        # ring merges weight it out); softmax over an all-masked row
        # would otherwise return a meaningless uniform average
        p = jnp.where(jnp.any(mask, axis=-1)[None, None, :, None],
                      p, 0.0)
    out32 = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    if not with_lse:
        return out32.astype(q.dtype)
    lse = jax.scipy.special.logsumexp(s, axis=-1)       # (B, H, L)
    # f32 out, matching the pallas lse path's partial-merge contract
    return out32, jnp.transpose(lse, (0, 2, 1))         # (B, L, H)


def _flash_kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
                        acc_scr, **kw):
    """Inference variant: no lse output allocated or written at all —
    the plain forward (return_lse=False, outside any vjp) should not
    pay HBM for softmax state nobody reads."""
    _flash_kernel(q_ref, k_ref, v_ref, o_ref, None, m_scr, l_scr,
                  acc_scr, **kw)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, scale: float, causal: bool, seq_len: int,
                  block_q: int, block_k: int, n_kv: int,
                  window: int = 0, q_offset: int = 0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def fold():
        # dots take the INPUT dtype (bf16×bf16→f32 is the MXU's native
        # mode — upcasting operands to f32 first quarters matmul
        # throughput); only the softmax bookkeeping runs in f32
        q = q_ref[0]                                    # (bq, d)
        k = k_ref[0]                                    # (bk, d)
        v = v_ref[0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        # global positions: mask padded tail columns always, the upper
        # triangle when causal (padded q rows give garbage, sliced off)
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = _tile_mask(rows, cols, causal, window, seq_len,
                           q_offset)
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = jnp.max(m_scr[:], axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_prev = jnp.max(l_scr[:], axis=-1, keepdims=True)
        l_scr[:] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape)
        # p folds back to the value dtype for the MXU; the f32 denominator
        # (summed above, BEFORE the downcast) keeps normalization exact
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _tile_live(qi, ki, block_q, block_k, causal, window,
                      q_offset)
    if live is None:
        fold()
    else:
        # skip kv blocks with no visible scores (above the causal
        # diagonal / behind the sliding window) — folding them is pure
        # wasted MXU time
        pl.when(live)(fold)

    @pl.when(ki == n_kv - 1)
    def _():
        l_fin = jnp.maximum(jnp.max(l_scr[:], axis=-1, keepdims=True),
                            1e-30)                      # (bq, 1)
        o_ref[0] = (acc_scr[:] / l_fin).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row logsumexp: the ONLY softmax state the fused
            # backward needs (p re-materializes as exp(s - lse))
            lse = (jnp.max(m_scr[:], axis=-1, keepdims=True)
                   + jnp.log(l_fin))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


# Tuned defaults from the on-chip sweep (benchmarks/flash_tune.py →
# results/flash_tune.json, second-round sweep, v5e 2026-07-31
# 11:32-11:38 UTC): (512, 512) is the decisive winner at every swept
# shape — fwd 0.501 ms at L=2048 (vs 2.077 ms at the original
# (128, 128), 0.778 at (256, 256)) and 1.80× the (256, 256) schedule
# on the L=4096 training path (6.545 vs 11.756 ms fwdbwd). Bigger
# tiles amortize the per-tile online-softmax state updates and halve
# the number of VMEM-refill boundaries; the f32 score tile at 512² is
# 1 MB, q/kv tiles 128 KB each at d=128 — comfortably inside VMEM
# with double buffering. Short sequences clamp down in _clamp_blocks;
# explicit callers (tiny windows, odd geometries) can still override.
_DEFAULT_BLOCK_Q = 512
_DEFAULT_BLOCK_K = 512


def _resolve_blocks(block_q, block_k):
    for nm, v in (("block_q", block_q), ("block_k", block_k)):
        if v is not None and v <= 0:  # match ops/matmul.py's validation
            raise ValueError(f"{nm} must be positive, got {v}")
    return (_DEFAULT_BLOCK_Q if block_q is None else block_q,
            _DEFAULT_BLOCK_K if block_k is None else block_k)


def _clamp_blocks(l: int, block_q: int, block_k: int):
    """Shared fwd/bwd block clamping — the backward re-derives the
    forward's padded geometry from (l, block_q, block_k) and the two
    must agree exactly (the saved lse is laid out in these blocks)."""
    return (min(block_q, max(8, -(-l // 8) * 8)),
            min(block_k, max(128, -(-l // 128) * 128)))


def _pad_seq(x, block: int):
    p = -x.shape[1] % block
    return jnp.pad(x, ((0, 0), (0, p), (0, 0))) if p else x


def _to_bh(x):
    """(B, L, H, D) → (B·H, L, D): one grid row per (batch, head)."""
    b, l, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)


def _kv_row(bh, h: int, hkv: int):
    """Grid row of the kv head serving q-grid-row ``bh`` (GQA): q head
    ``hq`` reads kv head ``hq // (h//hkv)``; identity when h == hkv."""
    if h == hkv:
        return bh
    group = h // hkv
    return (bh // h) * hkv + (bh % h) // group


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret",
                              "with_lse", "window", "q_offset"))
def _flash_pallas(q, k, v, causal, block_q=None, block_k=None,
                  interpret=False, with_lse=False, window=0,
                  q_offset=0):
    b, l, h, d = q.shape
    hkv = k.shape[2]
    scale = 1.0 / float(d) ** 0.5

    block_q, block_k = _resolve_blocks(block_q, block_k)
    block_q, block_k = _clamp_blocks(l, block_q, block_k)
    qb = _pad_seq(_to_bh(q), block_q)
    kb = _pad_seq(_to_bh(k), block_k)
    vb = _pad_seq(_to_bh(v), block_k)
    n_q = qb.shape[1] // block_q
    n_kv = kb.shape[1] // block_k

    kern = _flash_kernel if with_lse else _flash_kernel_nolse
    clamp = functools.partial(_kv_clamp, block_q=block_q,
                              block_k=block_k, causal=causal,
                              window=window, q_offset=q_offset,
                              n_kv=n_kv)
    spec_o = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0),
                          memory_space=pltpu.VMEM)
    spec_lse = pl.BlockSpec((1, block_q, _LANES),
                            lambda bh, qi, ki: (bh, qi, 0),
                            memory_space=pltpu.VMEM)
    # the lse path serves partial-merge callers (ring folds): its out
    # stays f32 so P merged partials round ONCE at the caller's final
    # cast, not once per ring step
    shape_o = out_struct(
        qb.shape, jnp.float32 if with_lse else q.dtype, qb, kb, vb)
    shape_lse = out_struct((b * h, qb.shape[1], _LANES), jnp.float32,
                           qb, kb, vb)
    res = pl.pallas_call(
        functools.partial(kern, scale=scale, causal=causal,
                          seq_len=l, block_q=block_q, block_k=block_k,
                          n_kv=n_kv, window=window,
                          q_offset=q_offset),
        grid=(b * h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, ki: (_kv_row(bh, h, hkv),
                                             clamp(qi, ki), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, ki: (_kv_row(bh, h, hkv),
                                             clamp(qi, ki), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[spec_o, spec_lse] if with_lse else [spec_o],
        out_shape=[shape_o, shape_lse] if with_lse else [shape_o],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),       # running output
        ],
        # (bh, qi) carry no cross-iteration state (scratch re-inits at
        # ki == 0); only the kv axis accumulates — telling Mosaic lets
        # it parallelize/pipeline across the first two grid axes
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb)

    out = res[0]
    out = jnp.transpose(out[:, :l, :].reshape(b, h, l, d), (0, 2, 1, 3))
    if not with_lse:
        return out
    return out, res[1][:, :, 0]        # collapse the replicated lanes


def _bwd_tile(q, k, v, do, lse_ref, delta_ref, qi, ki, *, scale, causal,
              seq_len, block_q, block_k, window=0, q_offset=0):
    """Re-materialize one (block_q, block_k) tile's p and ds in VMEM —
    the shared core of both backward kernels. Returns (p, ds) in f32.

    ds = p ∘ (do·vᵀ − Δ) · scale, with Δ_i = Σ_d do_id·o_id computed
    once outside (the standard FlashAttention-2 identity: the softmax
    jacobian term Σ_j p_ij dp_ij equals Δ_i because o = p·v)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = _tile_mask(rows, cols, causal, window, seq_len, q_offset)
    lse = _row_read(lse_ref)                            # (bq, 1)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    delta = _row_read(delta_ref)                        # (bq, 1)
    ds = p * (dp - delta) * scale
    return p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, scale, causal, seq_len,
                         block_q, block_k, n_kv, window=0, q_offset=0):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def fold():
        k = k_ref[0]
        _, ds = _bwd_tile(q_ref[0], k, v_ref[0], do_ref[0], lse_ref,
                          delta_ref, qi, ki, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q,
                          block_k=block_k, window=window,
                          q_offset=q_offset)
        # dq_i += ds_ij · k_j  (scale already folded into ds)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _tile_live(qi, ki, block_q, block_k, causal, window,
                      q_offset)
    if live is None:
        fold()
    else:
        pl.when(live)(fold)          # same tile pruning as the forward

    @pl.when(ki == n_kv - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                          causal, seq_len, block_q, block_k, n_q,
                          n_inner, window=0, q_offset=0):
    """Grid: (b·h_kv, n_kv, n_inner) with n_inner = group·n_q — the
    innermost axis walks every (q-head-in-group, q-block) pair whose
    gradients land in THIS kv head's (dk, dv) tile, so GQA's
    sum-over-group falls out of the same scratch accumulation that
    already summed over q blocks (group = 1 reduces to plain MHA)."""
    ki, inner = pl.program_id(1), pl.program_id(2)
    qi = inner % n_q

    @pl.when(inner == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def fold():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _bwd_tile(q, k_ref[0], v_ref[0], do, lse_ref, delta_ref,
                          qi, ki, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q,
                          block_k=block_k, window=window,
                          q_offset=q_offset)
        # dv_j += p_ijᵀ · do_i ; dk_j += ds_ijᵀ · q_i
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _tile_live(qi, ki, block_q, block_k, causal, window,
                      q_offset)
    if live is None:
        fold()
    else:
        pl.when(live)(fold)

    @pl.when(inner == n_inner - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret",
                              "window", "q_offset"))
def _flash_bwd_pallas(q, k, v, o, lse, g, causal, block_q=None,
                      block_k=None, interpret=False, g_lse=None,
                      window=0, q_offset=0):
    """Fused backward: (dq, dk, dv) with only O(L·d) HBM traffic.

    ``lse`` is the forward's saved per-row logsumexp, already in the
    padded (B·H, Lq_pad) layout. Δ = Σ_d do∘o is computed here in one
    fused XLA elementwise pass (O(L·d), not worth a kernel).

    ``g_lse`` (B, L, H), when given, is the cotangent of the lse OUTPUT
    (callers like the ring fold differentiate through it): since
    ∂lse_i/∂s_ij = p_ij, its whole contribution is ds += g_lse∘p — the
    same rank-1 row term as Δ with the opposite sign, so it folds into
    the delta operand and the kernels need no change at all."""
    b, l, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    scale = 1.0 / float(d) ** 0.5

    block_q, block_k = _resolve_blocks(block_q, block_k)
    block_q, block_k = _clamp_blocks(l, block_q, block_k)
    qb = _pad_seq(_to_bh(q), block_q)
    kb = _pad_seq(_to_bh(k), block_k)
    vb = _pad_seq(_to_bh(v), block_k)
    dob = _pad_seq(_to_bh(g), block_q)
    ob = _pad_seq(_to_bh(o), block_q)
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)                        # (B·H, Lq_pad)
    # kernel dots need matching operand dtypes: the lse path's cotangent
    # arrives f32 (its out is f32); Δ above already banked the f32
    # precision, so the per-tile dp/dv dots run MXU-native in q.dtype
    dob = dob.astype(q.dtype)
    if g_lse is not None:
        gl = jnp.transpose(g_lse, (0, 2, 1)).reshape(b * h, l)
        pad = delta.shape[1] - l
        if pad:
            gl = jnp.pad(gl, ((0, 0), (0, pad)))
        delta = delta - gl.astype(jnp.float32)
    n_q = qb.shape[1] // block_q
    n_kv = kb.shape[1] // block_k
    kw = dict(scale=scale, causal=causal, seq_len=l,
              block_q=block_q, block_k=block_k, window=window,
              q_offset=q_offset)

    # row operands (lse, Δ) ride lane-replicated — see _LANES
    lse_r = _lane_rep(lse)
    delta_r = _lane_rep(delta)
    # dead-tile DMA elision (see _kv_clamp/_q_clamp): dq walks kv
    # innermost, dkv walks q innermost — each clamps its innermost
    # operand maps onto the live band
    kvc = functools.partial(_kv_clamp, block_q=block_q, block_k=block_k,
                            causal=causal, window=window,
                            q_offset=q_offset, n_kv=n_kv)
    qc = functools.partial(_q_clamp, block_q=block_q, block_k=block_k,
                           causal=causal, window=window,
                           q_offset=q_offset, n_q=n_q)
    spec_q = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                          memory_space=pltpu.VMEM)
    spec_row = pl.BlockSpec((1, block_q, _LANES),
                            lambda bh, i, j: (bh, i, 0),
                            memory_space=pltpu.VMEM)
    spec_kv = pl.BlockSpec(
        (1, block_k, d),
        lambda bh, i, j: (_kv_row(bh, h, hkv), kvc(i, j), 0),
        memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_kv=n_kv, **kw),
        grid=(b * h, n_q, n_kv),
        in_specs=[spec_q, spec_kv, spec_kv, spec_q, spec_row, spec_row],
        out_specs=spec_q,
        out_shape=out_struct(qb.shape, q.dtype, qb, kb, vb, dob),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb, dob, lse_r, delta_r)

    # dkv grid: one row per KV head, kv-block outer, and the innermost
    # axis walks (q-head-in-group × q-block) — the q-side index maps
    # recover the q grid row from (bhkv, inner // n_q)
    def q_row(bhkv, i):
        return (bhkv // hkv) * h + (bhkv % hkv) * group + i // n_q

    spec_q2 = pl.BlockSpec(
        (1, block_q, d),
        lambda bh, j, i: (q_row(bh, i), qc(i % n_q, j), 0),
        memory_space=pltpu.VMEM)
    spec_row2 = pl.BlockSpec(
        (1, block_q, _LANES),
        lambda bh, j, i: (q_row(bh, i), qc(i % n_q, j), 0),
        memory_space=pltpu.VMEM)
    spec_kv2 = pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0),
                            memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, n_q=n_q,
                          n_inner=group * n_q, **kw),
        grid=(b * hkv, n_kv, group * n_q),
        in_specs=[spec_q2, spec_kv2, spec_kv2, spec_q2, spec_row2,
                  spec_row2],
        out_specs=[spec_kv2, spec_kv2],
        out_shape=[out_struct(kb.shape, k.dtype, qb, kb, vb, dob),
                   out_struct(vb.shape, v.dtype, qb, kb, vb, dob)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb, dob, lse_r, delta_r)

    def from_bh(x, ln, heads):
        return jnp.transpose(x[:, :ln, :].reshape(b, heads, ln, d),
                             (0, 2, 1, 3))

    return (from_bh(dq, l, h), from_bh(dk, l, hkv),
            from_bh(dv, l, hkv))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_p(q, k, v, cfg):
    causal, block_q, block_k, interpret, window, q_off = cfg
    return _flash_pallas(q, k, v, causal, block_q=block_q,
                         block_k=block_k, interpret=interpret,
                         window=window, q_offset=q_off)


def _flash_fwd(q, k, v, cfg):
    causal, block_q, block_k, interpret, window, q_off = cfg
    o, lse = _flash_pallas(q, k, v, causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           with_lse=True, window=window,
                           q_offset=q_off)
    # primal must match _flash_p's eval dtype (q.dtype) — the with_lse
    # kernel emits f32; keep THAT in the residuals (sharper delta)
    return o.astype(q.dtype), (q, k, v, o, lse)


def _flash_bwd(cfg, res, g):
    causal, block_q, block_k, interpret, window, q_off = cfg
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret, window=window,
                             q_offset=q_off)


_flash_p.defvjp(_flash_fwd, _flash_bwd)


def _lse_public(lse, b, l, h):
    """Padded (B·H, Lq_pad) → public (B, L, H) f32."""
    return jnp.transpose(lse[:, :l].reshape(b, h, l), (0, 2, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_p_lse(q, k, v, cfg):
    """(out, lse (B, L, H)) — the two-output form ring folds consume;
    gradients flow through BOTH outputs (see _flash_bwd_pallas g_lse)."""
    causal, block_q, block_k, interpret, window, q_off = cfg
    b, l, h, _ = q.shape
    o, lse = _flash_pallas(q, k, v, causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           with_lse=True, window=window,
                           q_offset=q_off)
    return o, _lse_public(lse, b, l, h)


def _flash_lse_fwd(q, k, v, cfg):
    causal, block_q, block_k, interpret, window, q_off = cfg
    b, l, h, _ = q.shape
    o, lse = _flash_pallas(q, k, v, causal, block_q=block_q,
                           block_k=block_k, interpret=interpret,
                           with_lse=True, window=window,
                           q_offset=q_off)
    return (o, _lse_public(lse, b, l, h)), (q, k, v, o, lse)


def _flash_lse_bwd(cfg, res, g):
    causal, block_q, block_k, interpret, window, q_off = cfg
    g_out, g_lse = g
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g_out, causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret, g_lse=g_lse,
                             window=window, q_offset=q_off)


_flash_p_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    backend: str = "auto",
                    block_q: int | None = None,
                    block_k: int | None = None,
                    return_lse: bool = False,
                    window: int = 0, q_offset: int = 0):
    """Exact softmax attention, (B, L, H, D) → (B, L, H, D).

    ``backend="pallas"``/``"pallas_interpret"`` runs the fused VMEM
    kernel; ``"xla"`` is the reference composition (correctness oracle,
    non-TPU platforms).

    Grouped-query attention: k/v may carry FEWER heads than q (H_kv
    dividing H) — q head ``h`` attends kv head ``h // (H/H_kv)``. The
    kernels regroup via index maps (kv tiles re-read per group member;
    the dkv backward walks each kv head's whole q group in its scratch
    accumulation), so GQA costs no extra HBM materialization either.

    ``return_lse=True`` also returns the per-row logsumexp of the
    masked scores, shape (B, L, H) f32 — the mergeable-softmax state
    that lets callers combine partial attentions over disjoint KV sets
    (the ring fold's contract). The out is then f32 too (partials must
    round once at the caller's final cast, not per merge step).
    Differentiable through BOTH outputs.

    Backward-precision note (return_lse path): the out-cotangent
    arrives f32 but the backward's dp/dv dots run in q.dtype — at bf16
    the gradients round there, so training grads are slightly less
    precise than the forward's round-once f32 merge contract. This is
    the standard MXU tradeoff (bf16 dots are what make the kernel
    fast); validate grad error vs the XLA oracle at bf16 if a new
    recipe is sensitive to it."""
    backend = resolve_backend(backend, "flash_attention")
    if window:
        if not causal:
            raise ValueError("sliding window (window > 0) implies "
                             "causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if q_offset:
        if not window:
            raise ValueError("q_offset only applies to windowed "
                             "attention (the banded-ring case)")
        if q_offset < 0:
            raise ValueError(f"q_offset must be >= 0, got {q_offset}")
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    if (q.shape[0], q.shape[1], q.shape[3]) != \
            (k.shape[0], k.shape[1], k.shape[3]):
        raise ValueError(f"q/k shapes incompatible: {q.shape} vs "
                         f"{k.shape} (batch, seq, head_dim must match)")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"GQA needs q heads divisible by kv heads: {q.shape[2]} "
            f"vs {k.shape[2]}")
    # the kernel's dots run in the operand dtype (MXU-native bf16 path),
    # so mixed q/k/v dtypes are promoted HERE — otherwise dot_general
    # fails deep inside the pallas trace with no user-facing cause
    dt = jnp.promote_types(q.dtype, jnp.promote_types(k.dtype, v.dtype))
    q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
    if backend == "xla":
        scale = 1.0 / float(q.shape[-1]) ** 0.5
        return _attn_reference_xla(q, k, v, causal, scale,
                                   with_lse=return_lse, window=window,
                                   q_offset=q_offset)
    cfg = (causal, block_q, block_k, backend == "pallas_interpret",
           window, q_offset)
    if return_lse:
        return _flash_p_lse(q, k, v, cfg)
    return _flash_p(q, k, v, cfg)
