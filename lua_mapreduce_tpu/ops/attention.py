"""Fused flash attention — MXU matmuls with an online softmax in VMEM.

Completes the kernel library (SURVEY.md §2.4's APRIL-ANN-kernel role) for
the transformer family: one `pallas_call` computes softmax(QKᵀ·scale)·V
without ever materializing the (L, L) score matrix in HBM — scores live
in VMEM one (block_q, block_k) tile at a time, folded into running
(max, denominator, output) accumulators in f32 scratch. This is the
single-device form of the SAME online-softmax fold the ring schedule runs
across chips (parallel/ring_attention.py::_block_fold): ring = flash with
the KV loop distributed over ICI.

Grid: (batch·heads, q-blocks, kv-blocks); the kv axis is the innermost
(sequential) dimension, accumulating into scratch and writing the
normalized output tile on its last step — the accumulator discipline of
ops/matmul.py. Causal masking compares global row/column indices built
from the program ids; padded tail rows/columns are masked the same way.

Backward: Pallas calls carry no JVP; the custom VJP differentiates the
XLA reference (O(L²) memory — fine at the L this kernel targets for
training on one chip; gradient-heavy long-context training should use the
ring form, whose backward is blockwise by construction).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lua_mapreduce_tpu.ops import resolve_backend

_NEG_INF = -1e30


def _attn_reference_xla(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, causal: bool, seq_len: int,
                  block_q: int, block_k: int, n_kv: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def fold():
        # dots take the INPUT dtype (bf16×bf16→f32 is the MXU's native
        # mode — upcasting operands to f32 first quarters matmul
        # throughput); only the softmax bookkeeping runs in f32
        q = q_ref[0]                                    # (bq, d)
        k = k_ref[0]                                    # (bk, d)
        v = v_ref[0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        # global positions: mask padded tail columns always, the upper
        # triangle when causal (padded q rows give garbage, sliced off)
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < seq_len
        if causal:
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_scr[:]                               # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p folds back to the value dtype for the MXU; the f32 denominator
        # (summed above, BEFORE the downcast) keeps normalization exact
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip kv blocks entirely above the diagonal — their scores are
        # wholly masked, so folding them is pure wasted MXU time (~2x for
        # long sequences)
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(fold)
    else:
        fold()

    @pl.when(ki == n_kv - 1)
    def _():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_pallas(q, k, v, causal, block_q=128, block_k=128,
                  interpret=False):
    b, l, h, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    # (B, L, H, D) → (B·H, L, D): one grid row per (batch, head)
    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    block_q = min(block_q, max(8, -(-l // 8) * 8))
    block_k = min(block_k, max(128, -(-l // 128) * 128))
    pl_q = -l % block_q
    pl_k = -l % block_k
    if pl_q:
        qb = jnp.pad(qb, ((0, 0), (0, pl_q), (0, 0)))
    if pl_k:
        kb = jnp.pad(kb, ((0, 0), (0, pl_k), (0, 0)))
        vb = jnp.pad(vb, ((0, 0), (0, pl_k), (0, 0)))
    n_q = qb.shape[1] // block_q
    n_kv = kb.shape[1] // block_k

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          seq_len=l, block_q=block_q, block_k=block_k,
                          n_kv=n_kv),
        grid=(b * h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(qb.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),      # running max
            pltpu.VMEM((block_q, 1), jnp.float32),      # running denom
            pltpu.VMEM((block_q, d), jnp.float32),      # running output
        ],
        interpret=interpret,
    )(qb, kb, vb)

    out = out[:, :l, :].reshape(b, h, l, d)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_p(q, k, v, cfg):
    causal, block_q, block_k, interpret = cfg
    return _flash_pallas(q, k, v, causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)


def _flash_fwd(q, k, v, cfg):
    return _flash_p(q, k, v, cfg), (q, k, v)


def _flash_bwd(cfg, res, g):
    causal = cfg[0]
    q, k, v = res
    scale = 1.0 / float(q.shape[-1]) ** 0.5
    _, vjp = jax.vjp(
        lambda q, k, v: _attn_reference_xla(q, k, v, causal, scale),
        q, k, v)
    return vjp(g)


_flash_p.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    backend: str = "auto", block_q: int = 128,
                    block_k: int = 128):
    """Exact softmax attention, (B, L, H, D) → (B, L, H, D).

    ``backend="pallas"``/``"pallas_interpret"`` runs the fused VMEM
    kernel; ``"xla"`` is the reference composition (correctness oracle,
    non-TPU platforms)."""
    backend = resolve_backend(backend, "flash_attention")
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} "
                         f"{v.shape}")
    # the kernel's dots run in the operand dtype (MXU-native bf16 path),
    # so mixed q/k/v dtypes are promoted HERE — otherwise dot_general
    # fails deep inside the pallas trace with no user-facing cause
    dt = jnp.promote_types(q.dtype, jnp.promote_types(k.dtype, v.dtype))
    q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
    if backend == "xla":
        scale = 1.0 / float(q.shape[-1]) ** 0.5
        return _attn_reference_xla(q, k, v, causal, scale)
    return _flash_p(q, k, v,
                    (causal, block_q, block_k,
                     backend == "pallas_interpret"))
