"""Fused row-wise softmax / log_softmax.

The reference's flagship model ends in ``log_softmax``
(examples/APRIL-ANN/init.lua:12, kernel provided by the external APRIL-ANN
toolkit — SURVEY.md §2.4). Here it is one VPU pass per row block: max,
exp, sum, and normalization fused in VMEM, so logits make a single round
trip to HBM instead of the four a naive composition would cost (the op is
bandwidth-bound; fusion is the whole win on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lua_mapreduce_tpu.utils.jax_compat import tpu_compiler_params

from lua_mapreduce_tpu.ops import out_struct, resolve_backend


def _log_softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    shifted = x - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True))
    o_ref[:] = (shifted - lse).astype(o_ref.dtype)


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("kernel", "block_rows", "interpret"))
def _rowwise_pallas(x, kernel, block_rows=256, interpret=False):
    orig_shape = x.shape
    n = orig_shape[-1]
    x2 = x.reshape(-1, n)
    m = x2.shape[0]

    block_rows = min(block_rows, max(8, -(-m // 8) * 8))
    # VMEM guard: the kernel holds the block in f32 plus temps (~7 B/elem
    # with a ~11MB fixed overhead against the 16MB scoped budget, measured
    # on v5e at widths 8k-32k) — clamp rows so vocab-wide inputs (32k
    # logits) compile instead of OOMing scoped vmem
    fit = (5_000_000 // (7 * n)) // 8 * 8
    block_rows = max(8, min(block_rows, fit))
    pm, pn = -m % block_rows, -n % 128
    # column padding must not perturb the row max/sum → pad with -inf
    if pm or pn:
        x2 = jnp.pad(x2, ((0, pm), (0, pn)),
                     constant_values=jnp.finfo(x2.dtype).min)

    out = pl.pallas_call(
        kernel,
        grid=(x2.shape[0] // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, x2.shape[1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block_rows, x2.shape[1]), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct(x2.shape, x.dtype, x2),
        # each row block is independent — let Mosaic parallelize
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x2)
    return out[:m, :n].reshape(orig_shape)


# Pallas calls have no JVP rule; training differentiates through these, so
# each op carries its analytic VJP (elementwise — the VPU/XLA backward is
# already optimal, no kernel needed):
#   y = log_softmax(x):  dx = g − softmax(x)·Σg
#   y = softmax(x):      dx = y·(g − Σ(g·y))

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _log_softmax_p(x, cfg):
    block_rows, interpret = cfg
    return _rowwise_pallas(x, _log_softmax_kernel, block_rows=block_rows,
                           interpret=interpret)


def _log_softmax_fwd(x, cfg):
    y = _log_softmax_p(x, cfg)
    return y, y


def _log_softmax_bwd(cfg, y, g):
    return (g - jnp.exp(y) * jnp.sum(g, axis=-1, keepdims=True),)


_log_softmax_p.defvjp(_log_softmax_fwd, _log_softmax_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _softmax_p(x, cfg):
    block_rows, interpret = cfg
    return _rowwise_pallas(x, _softmax_kernel, block_rows=block_rows,
                           interpret=interpret)


def _softmax_fwd(x, cfg):
    y = _softmax_p(x, cfg)
    return y, y


def _softmax_bwd(cfg, y, g):
    return (y * (g - jnp.sum(g * y, axis=-1, keepdims=True)),)


_softmax_p.defvjp(_softmax_fwd, _softmax_bwd)


def log_softmax(x, *, backend: str = "auto", block_rows: int = 256):
    """Numerically-stable log-softmax over the last axis."""
    backend = resolve_backend(backend, "softmax")
    if backend == "xla":
        return jax.nn.log_softmax(x, axis=-1)
    return _log_softmax_p(x, (block_rows, backend == "pallas_interpret"))


def softmax(x, *, backend: str = "auto", block_rows: int = 256):
    """Numerically-stable softmax over the last axis."""
    backend = resolve_backend(backend, "softmax")
    if backend == "xla":
        return jax.nn.softmax(x, axis=-1)
    return _softmax_p(x, (block_rows, backend == "pallas_interpret"))
