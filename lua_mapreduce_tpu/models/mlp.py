"""Digits MLP — the flagship DP-training model.

The reference trains "256 inputs 128 tanh 10 log_softmax"
(examples/APRIL-ANN/init.lua:12) with SGD + momentum + weight decay
(init.lua:16-20). Pure-jax pytree params; bfloat16-friendly matmuls hit the
MXU when the batch is big enough.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]

DIGITS_SIZES = (256, 128, 10)   # init.lua:12


def init_mlp(key, sizes: Sequence[int] = DIGITS_SIZES,
             dtype=jnp.float32) -> Params:
    """Glorot-uniform weights, zero biases; keys W0/b0, W1/b1, …
    (the per-parameter-name key space the example's mapfn emits,
    common.lua:85-104)."""
    params: Params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        bound = jnp.sqrt(6.0 / (fan_in + fan_out))
        params[f"W{i}"] = jax.random.uniform(
            keys[i], (fan_in, fan_out), dtype, -bound, bound)
        params[f"b{i}"] = jnp.zeros((fan_out,), dtype)
    return params


def n_layers(params: Params) -> int:
    return sum(1 for k in params if k.startswith("W"))


def mlp_apply(params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """tanh hidden layers, log_softmax output (init.lua:12)."""
    L = n_layers(params)
    for i in range(L - 1):
        x = jnp.tanh(x @ params[f"W{i}"] + params[f"b{i}"])
    logits = x @ params[f"W{L-1}"] + params[f"b{L-1}"]
    return jax.nn.log_softmax(logits)


def nll_loss(params: Params, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Mean negative log-likelihood over a batch (labels are int classes)."""
    logp = mlp_apply(params, x)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def accuracy(params: Params, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.argmax(mlp_apply(params, x), axis=1) == y)


def flops_per_example(sizes: Sequence[int] = DIGITS_SIZES) -> int:
    """Forward+backward matmul FLOPs per example (for MFU accounting:
    ≈ 3 × 2 × Σ fan_in·fan_out)."""
    fwd = sum(2 * a * b for a, b in zip(sizes[:-1], sizes[1:]))
    return 3 * fwd
