"""ALS matrix factorization — the second iterative-state workload
(BASELINE.json config 5).

Alternating least squares on an observed ratings matrix R ≈ U Vᵀ with an
observation mask. Expressed in the reference's looping-MapReduce shape
(SURVEY.md §3.5): each iteration the "map" solves user factors on a shard
of users given replicated item factors V (embarrassingly parallel — the
map phase), then folds that shard's contribution to every item's normal
equations; the "reduce" sums those (k×k, k) partials across shards — on
TPU a ``psum`` over ICI; the "final" solves all item systems and loops.
The whole fit is one jitted SPMD program with users sharded over ``dp``
for its entire lifetime: the per-row solves are batched ``vmap``s over
MXU-shaped normal equations, iterations ride ``lax.scan``, and the only
cross-device traffic is the psum. The six-function-engine packaging of
the same algorithm lives in examples/als/.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from lua_mapreduce_tpu.utils.jax_compat import shard_map


class ALSResult(NamedTuple):
    user_factors: jnp.ndarray   # (n_users, k)
    item_factors: jnp.ndarray   # (n_items, k)
    rmse: jnp.ndarray           # scalar: final masked train RMSE
    history: jnp.ndarray        # (n_iters,) RMSE per iteration


def init_item_factors(key, n_items: int, rank: int,
                      scale: float = 0.1) -> jnp.ndarray:
    return scale * jax.random.normal(key, (n_items, rank))


def _solve_users(r, w, v, reg):
    """Per-user ridge solve, batched: for each user u,
    (Vᵀ W_u V + λI) x = Vᵀ W_u r_u. r/w are this shard's (n_u, n_items)."""
    k = v.shape[1]
    eye = reg * jnp.eye(k, dtype=v.dtype)

    def solve_one(r_u, w_u):
        vw = v * w_u[:, None]               # (n_items, k)
        a = vw.T @ v + eye                  # (k, k) MXU
        b = vw.T @ r_u                      # (k,)
        return jnp.linalg.solve(a, b)

    return jax.vmap(solve_one)(r, w)        # (n_u, k)


def _item_partials(r, w, u):
    """This shard's contribution to every item's normal equations:
    A_i += Σ_u w_ui u_u u_uᵀ, b_i += Σ_u w_ui r_ui u_u — the quantity the
    reduce phase sums (it is associative+commutative, the combiner
    contract of SURVEY.md §2.5)."""
    a = jnp.einsum("ui,uk,ul->ikl", w, u, u)        # (n_items, k, k)
    b = jnp.einsum("ui,ui,uk->ik", w, r, u)         # (n_items, k)
    return a, b


def als_fit(ratings, mask, item_factors0, *, n_iters: int = 10,
            reg: float = 0.1, mesh: Optional[object] = None,
            axis: str = "dp") -> ALSResult:
    """Run ``n_iters`` ALS rounds from item factors ``item_factors0``.

    With a ``mesh``, ratings/mask are sharded row-wise (users) over
    ``axis``; item factors stay replicated and the item-step normal
    equations are psum'd. ``history[i]`` is the masked RMSE measured with
    the factors produced by round i.
    """
    ratings = jnp.asarray(ratings, jnp.float32)
    mask = jnp.asarray(mask, jnp.float32)
    item_factors0 = jnp.asarray(item_factors0, jnp.float32)
    k = item_factors0.shape[1]

    def fit(r, w, v0):
        eye = reg * jnp.eye(k, dtype=v0.dtype)

        def one_iter(v, _):
            u = _solve_users(r, w, v, reg)              # map: user shard
            a, b = _item_partials(r, w, u)              # combine
            if mesh is not None:
                a = lax.psum(a, axis)                   # reduce over ICI
                b = lax.psum(b, axis)
            v_new = jax.vmap(
                lambda ai, bi: jnp.linalg.solve(ai + eye, bi))(a, b)
            err = w * (u @ v_new.T - r)
            sq, cnt = jnp.sum(err ** 2), jnp.sum(w)
            if mesh is not None:
                sq = lax.psum(sq, axis)
                cnt = lax.psum(cnt, axis)
            rmse = jnp.sqrt(sq / jnp.maximum(cnt, 1.0))
            return v_new, rmse

        v, hist = lax.scan(one_iter, v0, None, length=n_iters)
        u = _solve_users(r, w, v, reg)
        return u, v, hist

    if mesh is None:
        u, v, hist = jax.jit(fit)(ratings, mask, item_factors0)
        return ALSResult(u, v, hist[-1], hist)

    shard = shard_map(
        fit, mesh=mesh, in_specs=(P(axis), P(axis), P()),
        out_specs=(P(axis), P(), P()))
    ratings = jax.device_put(ratings, NamedSharding(mesh, P(axis)))
    mask = jax.device_put(mask, NamedSharding(mesh, P(axis)))
    item_factors0 = jax.device_put(item_factors0,
                                   NamedSharding(mesh, P()))
    u, v, hist = jax.jit(shard)(ratings, mask, item_factors0)
    return ALSResult(u, v, hist[-1], hist)
