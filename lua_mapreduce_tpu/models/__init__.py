"""Model zoo.

The "models" of the reference are its example workloads (SURVEY.md §2.3);
the BASELINE.json configs name the targets: digits MLP (the APRIL-ANN
example's 256→128 tanh→10 log_softmax, examples/APRIL-ANN/init.lua:12),
LeNet-5, ResNet-18, and the iterative k-means / ALS state workloads.
"""
