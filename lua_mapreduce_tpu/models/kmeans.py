"""k-means — the iterative-state workload, TPU-native (BASELINE.json
config 5: "Iterative k-means / ALS … persistent_table.lua state across
MapReduce iters on TPU").

The reference expresses iterative algorithms as looping MapReduce with
cross-iteration state in a persistent_table (SURVEY.md §3.5, §5). Lloyd's
algorithm has exactly that shape — map = assign each point shard to its
nearest centroid and fold per-cluster partial sums, reduce = sum partials
across shards, final = recompute centroids and loop. Here the whole loop
is ONE jitted SPMD program: points stay sharded over the ``dp`` axis for
the entire fit, the assign step is a distance matmul on the MXU, the
reduce is a ``psum`` over ICI, and iterations run inside ``lax.scan`` with
zero host round-trips (the hot-path rule of BASELINE.md). The
six-function-engine packaging of the same algorithm lives in
examples/kmeans/ — both paths must agree (golden-diff discipline,
SURVEY.md §4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from lua_mapreduce_tpu.utils.jax_compat import shard_map


class KMeansResult(NamedTuple):
    centroids: jnp.ndarray      # (k, d)
    inertia: jnp.ndarray        # scalar: sum of squared distances
    history: jnp.ndarray        # (n_iters,) inertia per iteration


def _assign_fold(x, centroids):
    """Per-shard map+combine: nearest-centroid one-hot fold.

    Distances via the expanded form — the x·cᵀ term is one (shard, k)
    matmul on the MXU; ‖x‖² is constant in the argmin and omitted.
    Returns (per-cluster sums (k, d), counts (k,), inertia scalar).
    """
    xc = x @ centroids.T                                    # (n, k) MXU
    d2 = jnp.sum(centroids ** 2, axis=1)[None, :] - 2.0 * xc
    nearest = jnp.argmin(d2, axis=1)                        # (n,)
    one_hot = jax.nn.one_hot(nearest, centroids.shape[0],
                             dtype=x.dtype)                 # (n, k)
    sums = one_hot.T @ x                                    # (k, d) MXU
    counts = jnp.sum(one_hot, axis=0)                       # (k,)
    inertia = (jnp.sum(x ** 2)
               + jnp.sum(one_hot * d2))    # Σ‖x‖² + Σ(‖c‖² − 2x·c)
    return sums, counts, inertia


def _update(centroids, sums, counts):
    """New centroid = cluster mean; empty clusters keep their centroid
    (the reference engine's empty-partition tolerance, SURVEY.md §6)."""
    safe = jnp.maximum(counts, 1.0)[:, None]
    return jnp.where(counts[:, None] > 0, sums / safe, centroids)


def init_centroids(key, x: np.ndarray, k: int,
                   method: str = "kmeans++") -> jnp.ndarray:
    """Seed centroids, deterministic in ``key``. ``"kmeans++"`` (default)
    does D²-weighted sampling — sequential over k, so it runs host-side
    (seeding is a once-per-fit cost, not the hot loop); ``"random"``
    picks k distinct points uniformly."""
    x = np.asarray(x)
    rng = np.random.RandomState(
        int(jax.random.randint(key, (), 0, 2 ** 31 - 1)))
    if method == "random":
        return jnp.asarray(x[rng.choice(len(x), k, replace=False)])
    if method != "kmeans++":
        raise ValueError(f"unknown init method {method!r}")
    # greedy k-means++: each step samples a few D²-weighted candidates
    # and keeps the one that lowers the potential most (the standard
    # robustification against seeding two centroids into one cluster)
    n_trials = 2 + int(np.log(max(k, 2)))
    chosen = x[rng.randint(len(x))][None, :]
    d2 = np.sum((x - chosen[0]) ** 2, axis=-1)
    for _ in range(k - 1):
        cand = rng.choice(len(x), size=n_trials, p=d2 / d2.sum())
        cand_d2 = np.minimum(
            d2[None, :],
            np.sum((x[None, :, :] - x[cand][:, None, :]) ** 2, axis=-1))
        best = int(np.argmin(cand_d2.sum(axis=1)))
        chosen = np.concatenate([chosen, x[cand[best]][None, :]])
        d2 = cand_d2[best]
    return jnp.asarray(chosen)


def kmeans_fit(x, centroids0, *, n_iters: int = 20,
               mesh: Optional[object] = None, axis: str = "dp"
               ) -> KMeansResult:
    """Run ``n_iters`` Lloyd iterations from ``centroids0``.

    With a ``mesh``, ``x`` is sharded on its leading axis over ``axis``
    and the fold is psum'd over ICI; without one it is a single-device
    jit. The iteration count is static (lax.scan) so the whole fit is one
    compiled program. ``history[i]`` is the inertia of the assignment
    computed against the iteration-i centroids — history[-1] lags the
    returned final centroids by one update, matching the classic
    assign-then-update bookkeeping.
    """
    x = jnp.asarray(x)
    centroids0 = jnp.asarray(centroids0)

    def fit(x_in, c0):
        def one_iter(centroids, _):
            sums, counts, inertia = _assign_fold(x_in, centroids)
            if mesh is not None:
                sums = lax.psum(sums, axis)
                counts = lax.psum(counts, axis)
                inertia = lax.psum(inertia, axis)
            return _update(centroids, sums, counts), inertia

        c, hist = lax.scan(one_iter, c0, None, length=n_iters)
        return KMeansResult(c, hist[-1], hist)

    if mesh is None:
        return jax.jit(fit)(x, centroids0)
    shard = shard_map(
        fit, mesh=mesh, in_specs=(P(axis), P()),
        out_specs=KMeansResult(P(), P(), P()))
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    centroids0 = jax.device_put(centroids0, NamedSharding(mesh, P()))
    return jax.jit(shard)(x, centroids0)


def assign(x, centroids) -> jnp.ndarray:
    """Nearest-centroid labels for ``x`` (single device)."""
    xc = jnp.asarray(x) @ jnp.asarray(centroids).T
    d2 = jnp.sum(jnp.asarray(centroids) ** 2, axis=1)[None, :] - 2.0 * xc
    return jnp.argmin(d2, axis=1)
