"""Decoder-only transformer LM — the long-context model family.

The reference's model zoo is its example workloads (SURVEY.md §2.3); this
family extends the zoo to sequences, the capability the sequence-parallel
layer (parallel/ring_attention.py) exists for. One model, three execution
forms that must agree (golden-diff discipline, SURVEY.md §4):

- :func:`transformer_apply` — single-device oracle (full attention).
- :func:`make_sharded_apply` — the same forward inside ``shard_map`` over
  a (dp, sp) mesh: batch sharded on ``dp``, sequence sharded on ``sp``,
  attention via the ring (KV shards rotating over ICI) or Ulysses
  (all_to_all head reshard). No device ever holds a full sequence —
  context length scales with the sp axis.
- :func:`make_train_step` — jitted SPMD LM training step over the mesh:
  per-device loss on its (batch, seq) tile, gradient pmean over BOTH axes
  fused into the backward pass (the reference's reducefn-sum shape,
  common.lua:112-137).

Params are a flat name→array dict (the grad-shuffle key space, like every
model in this zoo). Layout: activations (B, L, D); attention heads split
D as (H, D/H). Weights stay f32; matmul FLOPs ride the MXU.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from lua_mapreduce_tpu.ops.attention import flash_attention
from lua_mapreduce_tpu.ops.decode import decode_attention, quantize_kv
from lua_mapreduce_tpu.ops.q8 import q8_matmul, quantize_q8
from lua_mapreduce_tpu.parallel import moe as _moe
from lua_mapreduce_tpu.parallel import zero1 as _z1
from lua_mapreduce_tpu.parallel.pipeline import pipeline_apply
from lua_mapreduce_tpu.parallel.ring_attention import (
    _NEG_INF, _ring_shard, _ring_shard_zigzag, _ulysses_shard,
    _zigzag_check, _zigzag_perm, attention_reference)
from lua_mapreduce_tpu.train.accum import accum_value_and_grad
from lua_mapreduce_tpu.utils.jax_compat import (shard_map, spec_axes,
                                                stamp_replicated)

Params = Dict[str, jnp.ndarray]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 512
    # grouped-query attention: 0 (default) = n_heads (plain MHA); a
    # divisor of n_heads shares each kv head across n_heads/n_kv_heads
    # query heads — the KV cache and the kv projection shrink by that
    # factor (the modern long-context serving lever; the flash kernels
    # regroup via index maps, ops/attention.py)
    n_kv_heads: int = 0
    # rotary position embeddings: q/k rotated by their GLOBAL position
    # before attention (relative-position encoding, no pos_emb table —
    # the standard long-context scheme; composes with every sequence-
    # parallel form because _shard_pos already hands each device its
    # global positions). head_dim must be even.
    rope: bool = False
    rope_base: float = 10000.0
    # "ln" (pre-LN with bias) or "rms" (RMSNorm, scale only)
    norm: str = "ln"
    # "gelu" (2-matmul MLP with biases) or "swiglu" (gate/up/down,
    # no biases — the llama-style FFN)
    ffn: str = "gelu"
    # sliding-window attention: each position sees at most the last
    # ``window`` positions (0 = full causal). Oracle, KV-cached decode
    # (rolling O(window) cache), prefill, pipeline, and the BANDED
    # contiguous ring (attn="ring") speak it; zigzag/ulysses reject.
    window: int = 0
    # mixture-of-experts: >0 replaces every block's dense FFN with a
    # switch-routed expert FFN (parallel/moe.py); 0 = dense. capacity is
    # REQUIRED with experts and is per routing group (the device tile in
    # sharded runs, the whole batch in the oracle) — an auto-derived
    # default would differ between the two and break their golden-diff.
    moe_experts: int = 0
    moe_capacity: int = 0
    moe_aux_weight: float = 0.01
    # experts each token is routed to: 1 = switch, >1 = Mixtral-style
    # top-k with combine weights renormalized over the selected k
    moe_top_k: int = 1
    # rematerialization: recompute each block in the backward pass
    # instead of saving its activations — trades ~1/3 more FLOPs for
    # O(n_layers) less activation HBM, the standard long-context lever
    # (activations dominate HBM at large L; the MXU has FLOPs to spare).
    # Applies to every execution form (oracle, sp, 3-D) since they share
    # _forward.
    remat: bool = False

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                 n_layers=2, d_ff=64, max_seq=128)

    @staticmethod
    def llama_style(**kw) -> "TransformerConfig":
        """The modern decoder recipe: RoPE + RMSNorm + SwiGLU + GQA
        (pass ``n_kv_heads``); any field overridable via ``kw``."""
        base = dict(rope=True, norm="rms", ffn="swiglu")
        base.update(kw)
        return TransformerConfig(**base)


def flops_per_token(cfg: TransformerConfig, seq_len: int,
                    causal: bool = True) -> float:
    """Matmul FLOPs per token for one TRAIN step (fwd + bwd ≈ 3× fwd) —
    the MFU numerator (same accounting role as models/mlp.py
    ``flops_per_example``). Counted: qkv+out projections
    (2·d·(H+2H_kv)·hd + 2d² per token — 8d² at MHA, less under GQA),
    attention score+value contractions (4·L·d, halved when causal;
    unchanged by GQA — every QUERY head still contracts), dense FFN
    (4·d·d_ff), tied LM head (2·d·V). Uncounted (understates
    utilization): layernorms, softmax, embeddings, and the extra block
    forward under ``cfg.remat``. MoE FFN FLOPs follow the per-token
    routed expert (same as dense for top-1 switch routing)."""
    d, dff = cfg.d_model, cfg.d_ff
    hd = d // cfg.n_heads
    qkv_proj = 2.0 * d * (cfg.n_heads + 2 * kv_heads(cfg)) * hd
    if cfg.window and causal:
        # sliding window: mean visible keys per token is
        # (Σ_{i=1..L} min(i, w)) / L — the kernel prunes the rest,
        # so counting full-causal work would inflate MFU
        we = min(cfg.window, seq_len)
        visible = (we * (we + 1) / 2 + (seq_len - we) * we) / seq_len
        attn = 4.0 * d * visible
    else:
        attn = 4.0 * seq_len * d * (0.5 if causal else 1.0)
    ffn = (6.0 if cfg.ffn == "swiglu" else 4.0) * d * dff
    per_layer = qkv_proj + 2.0 * d * d + attn + ffn
    fwd = cfg.n_layers * per_layer + 2.0 * d * cfg.vocab
    return 3.0 * fwd


def kv_heads(cfg: TransformerConfig) -> int:
    """Effective kv head count (n_kv_heads, defaulting to n_heads)."""
    hkv = cfg.n_kv_heads or cfg.n_heads
    if cfg.n_heads % hkv:
        raise ValueError(f"n_kv_heads={hkv} must divide "
                         f"n_heads={cfg.n_heads}")
    return hkv


def _check_arch(cfg: TransformerConfig) -> None:
    """Architecture-knob validation shared by init and every factory."""
    if cfg.norm not in ("ln", "rms"):
        raise ValueError(f"unknown norm {cfg.norm!r} (want 'ln'|'rms')")
    if cfg.ffn not in ("gelu", "swiglu"):
        raise ValueError(f"unknown ffn {cfg.ffn!r} "
                         f"(want 'gelu'|'swiglu')")
    if cfg.rope and (cfg.d_model // cfg.n_heads) % 2:
        raise ValueError("rope needs an even head_dim; got "
                         f"{cfg.d_model // cfg.n_heads}")
    if cfg.moe_experts and cfg.ffn != "gelu":
        raise ValueError("MoE blocks use the switch-gelu expert FFN; "
                         "ffn='swiglu' applies to dense blocks only")
    if cfg.window < 0:
        raise ValueError(f"window must be >= 0, got {cfg.window}")


def _check_moe(cfg: TransformerConfig, n_ep: Optional[int] = None) -> None:
    if cfg.moe_experts and cfg.moe_capacity <= 0:
        raise ValueError(
            "moe_experts > 0 requires an explicit moe_capacity (it is "
            "per routing group; see TransformerConfig)")
    if cfg.moe_top_k < 1:
        raise ValueError(f"moe_top_k must be >= 1, got {cfg.moe_top_k}")
    if cfg.moe_experts and cfg.moe_top_k > cfg.moe_experts:
        raise ValueError(f"moe_top_k={cfg.moe_top_k} exceeds "
                         f"moe_experts={cfg.moe_experts}")
    if n_ep is not None and cfg.moe_experts % n_ep:
        raise ValueError(f"moe_experts={cfg.moe_experts} not divisible "
                         f"by the expert-parallel axis size {n_ep}")


def init_transformer(key, cfg: TransformerConfig = TransformerConfig(),
                     dtype=jnp.float32) -> Params:
    """Flat params: tok/pos embeddings, per layer fused qkv + out proj +
    2-layer MLP + 2 layernorms, final layernorm; the LM head is tied to
    the token embedding (standard weight tying)."""
    _check_moe(cfg)
    _check_arch(cfg)
    d, ff = cfg.d_model, cfg.d_ff
    hd = d // cfg.n_heads
    qkv_cols = (cfg.n_heads + 2 * kv_heads(cfg)) * hd
    params: Params = {}
    keys = iter(jax.random.split(key, 2 + 5 * cfg.n_layers))
    params["tok_emb"] = 0.02 * jax.random.normal(
        next(keys), (cfg.vocab, d), dtype)
    if not cfg.rope:        # rope needs no position table
        params["pos_emb"] = 0.02 * jax.random.normal(
            next(keys), (cfg.max_seq, d), dtype)
    for i in range(cfg.n_layers):
        p = f"L{i}"
        params[f"{p}_qkv_W"] = jax.random.normal(
            next(keys), (d, qkv_cols), dtype) / np.sqrt(d)
        params[f"{p}_out_W"] = jax.random.normal(
            next(keys), (d, d), dtype) / np.sqrt(d)
        if cfg.moe_experts:
            params.update(_moe.init_moe(
                next(keys), d, ff, cfg.moe_experts, dtype,
                prefix=f"{p}_moe"))
        elif cfg.ffn == "swiglu":
            params[f"{p}_ff1_W"] = jax.random.normal(     # gate
                next(keys), (d, ff), dtype) / np.sqrt(d)
            params[f"{p}_ff3_W"] = jax.random.normal(     # up
                next(keys), (d, ff), dtype) / np.sqrt(d)
            params[f"{p}_ff2_W"] = jax.random.normal(     # down
                next(keys), (ff, d), dtype) / np.sqrt(ff)
        else:
            params[f"{p}_ff1_W"] = jax.random.normal(
                next(keys), (d, ff), dtype) / np.sqrt(d)
            params[f"{p}_ff1_b"] = jnp.zeros((ff,), dtype)
            params[f"{p}_ff2_W"] = jax.random.normal(
                next(keys), (ff, d), dtype) / np.sqrt(ff)
            params[f"{p}_ff2_b"] = jnp.zeros((d,), dtype)
        for ln in ("ln1", "ln2"):
            params[f"{p}_{ln}_g"] = jnp.ones((d,), dtype)
            if cfg.norm == "ln":
                params[f"{p}_{ln}_b"] = jnp.zeros((d,), dtype)
    params["lnf_g"] = jnp.ones((d,), dtype)
    if cfg.norm == "ln":
        params["lnf_b"] = jnp.zeros((d,), dtype)
    return params


def _head(params: Params, x):
    """The tied LM head ``x @ tok_emb.T`` — through the int8 kernel when
    the serving dict carries ``head::q8`` (quantize_lm). At production
    vocab sizes this is THE decode-bandwidth matmul; the embedding
    GATHER keeps the full-precision tok_emb (it reads only B rows per
    step, negligible traffic)."""
    if "head::q8" in params:
        return _mm(params, "head", x)       # one q8 dispatch path only
    return x @ params["tok_emb"].T


def _mm(params: Params, key: str, y):
    """``y @ params[key]`` — through the weight-only int8 kernel when
    the param dict carries a quantized entry (``key::q8`` +
    ``key::scale``, see :func:`quantize_lm`). The branch is on dict
    STRUCTURE, so it is resolved at trace time and costs nothing."""
    qk = key + "::q8"
    if qk in params:
        shp = y.shape
        out = q8_matmul(y.reshape(-1, shp[-1]), params[qk],
                        params[key + "::scale"])
        return out.reshape(*shp[:-1], out.shape[-1])
    return y @ params[key]


def quantize_lm(params: Params) -> Params:
    """Weight-only int8 SERVING copy of an LM's DENSE projection
    weights: every per-block 2-D projection (qkv / out / ff*) is
    replaced by
    ``name::q8`` (int8) + ``name::scale`` (f32 per output channel),
    and the tied head gets an int8 copy (``head::q8``) while tok_emb
    stays full precision for the embedding gather; biases and norms
    are untouched.
    Use with the single-device inference paths (``greedy_decode``,
    ``prefill``) — training and the sharded forward reject quantized
    dicts loudly (the original keys are gone). Dense PROJECTIONS are
    4× smaller than f32; the embedding table itself grows 1.25×
    (f32 gather copy + int8 head copy) — the head quantization buys
    decode BANDWIDTH (int8 streamed per step), not footprint, so at
    embedding-dominated sizes the dict shrinks less than 4× overall.
    MoE expert stacks (3-D, einsum-dispatched) and embeddings stay full
    precision — for dense models the quantized projections are the
    decode-bandwidth bulk."""
    out = {}
    for k, v in params.items():
        if (k.endswith("_W") and v.ndim == 2
                and ("_qkv_" in k or "_out_" in k or "_ff" in k)):
            q, s = quantize_q8(v)
            out[k + "::q8"] = q
            out[k + "::scale"] = s.reshape(-1)
        else:
            out[k] = v
    # the tied head gets an int8 COPY (tok_emb stays for the gather):
    # at production vocab the head is the decode-bandwidth matmul
    qh, sh = quantize_q8(jnp.transpose(params["tok_emb"]))
    out["head::q8"] = qh
    out["head::scale"] = sh.reshape(-1)
    return out


def _layer_norm(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _norm(params: Params, name: str, x, cfg: TransformerConfig,
          eps=1e-5):
    """The block norm: pre-LN (scale+bias) or RMSNorm (scale only)."""
    g = params[f"{name}_g"]
    if cfg.norm == "rms":
        ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x * lax.rsqrt(ms + eps) * g
    return _layer_norm(x, g, params[f"{name}_b"], eps)


def _rope(x, pos, base: float):
    """Rotary embedding: rotate each (i, i+hd/2) pair of head dims by
    pos·base^(-2i/hd). x (B, L, H*, hd) — broadcasts over ANY head
    count (q and GQA's smaller k alike); pos (L,) global positions.
    Rotation-half convention; angles in f32, result in x.dtype."""
    half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]  # (L, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), \
        x[..., half:].astype(jnp.float32)
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _ffn(params: Params, p: str, y, cfg: TransformerConfig,
         moe_axis: Optional[str]):
    """The block's FFN: dense, or switch-MoE when cfg.moe_experts > 0
    (expert-parallel over ``moe_axis`` inside shard_map, single-device
    reference routing when ``moe_axis`` is None). Returns (out, aux)."""
    if not cfg.moe_experts:
        if cfg.ffn == "swiglu":
            gate = jax.nn.silu(_mm(params, f"{p}_ff1_W", y))
            up = _mm(params, f"{p}_ff3_W", y)
            return _mm(params, f"{p}_ff2_W", gate * up), 0.0
        h = jax.nn.gelu(_mm(params, f"{p}_ff1_W", y)
                        + params[f"{p}_ff1_b"])
        return _mm(params, f"{p}_ff2_W", h) + params[f"{p}_ff2_b"], 0.0
    b, l, d = y.shape
    t = b * l
    cap = cfg.moe_capacity
    flat = y.reshape(t, d)
    if moe_axis is None:
        out, aux = _moe.moe_ffn_reference(params, flat, capacity=cap,
                                          prefix=f"{p}_moe",
                                          top_k=cfg.moe_top_k)
    else:
        out, aux = _moe.moe_ffn_shard(params, flat, capacity=cap,
                                      ep_axis=moe_axis,
                                      prefix=f"{p}_moe",
                                      top_k=cfg.moe_top_k)
    return out.reshape(b, l, d), aux


def _block(params: Params, i: int, x, cfg: TransformerConfig, attn_fn,
           pos, moe_axis: Optional[str] = None,
           kv_sink: Optional[list] = None):
    """One pre-norm decoder block; ``attn_fn(q, k, v) -> out`` supplies
    the (possibly sequence-parallel) attention; ``pos`` are the GLOBAL
    positions of the L rows (rope consumes them; ignored otherwise).
    Returns (x, moe_aux).

    ``kv_sink`` (a list) captures this block's (k, v) projections —
    the prefill path harvests them as the decode KV cache. With rope
    the captured k is the ROTATED one (what attention consumes and
    what the decode cache stores)."""
    p = f"L{i}"
    b, l, d = x.shape
    h, hd = cfg.n_heads, d // cfg.n_heads
    hkv = kv_heads(cfg)
    y = _norm(params, f"{p}_ln1", x, cfg)
    qkv = _mm(params, f"{p}_qkv_W", y)          # (B, L, (H+2Hkv)·hd) MXU
    q = qkv[..., :h * hd].reshape(b, l, h, hd)
    k = qkv[..., h * hd:(h + hkv) * hd].reshape(b, l, hkv, hd)
    v = qkv[..., (h + hkv) * hd:].reshape(b, l, hkv, hd)
    if cfg.rope:
        q = _rope(q, pos, cfg.rope_base)
        k = _rope(k, pos, cfg.rope_base)
    if kv_sink is not None:
        kv_sink.append((k, v))
    a = attn_fn(q, k, v).reshape(b, l, d)
    x = x + _mm(params, f"{p}_out_W", a)
    y = _norm(params, f"{p}_ln2", x, cfg)
    out, aux = _ffn(params, p, y, cfg, moe_axis)
    return x + out, aux


def _check_seq(global_len: int, cfg: TransformerConfig) -> None:
    """Static-shape guard: out-of-range position gathers would silently
    CLAMP to pos_emb's last row under jit, not raise."""
    if global_len > cfg.max_seq:
        raise ValueError(
            f"sequence length {global_len} exceeds max_seq={cfg.max_seq}")


def _forward(params: Params, tokens, pos, cfg: TransformerConfig,
             attn_fn, block=None):
    """Shared body: tokens (B, L) int32, pos (L,) global positions;
    ``block`` swaps the decoder-block implementation (the 3-D form
    passes its tensor-parallel block) — one forward for every path.
    Returns (logits, summed moe aux loss; 0.0 for dense blocks)."""
    block = block or _block
    x = params["tok_emb"][tokens]
    if not cfg.rope:
        x = x + params["pos_emb"][pos]   # rope positions live in-block
    aux_total = 0.0
    for i in range(cfg.n_layers):
        if cfg.remat:
            # checkpoint boundary = one decoder block (collectives inside
            # sp/tp blocks are re-executed in the backward — the usual
            # ring-attention remat shape)
            def run_block(p, xx, _i=i):
                return block(p, _i, xx, cfg, attn_fn, pos)
            x, aux = jax.checkpoint(run_block)(params, x)
        else:
            x, aux = block(params, i, x, cfg, attn_fn, pos)
        aux_total = aux_total + aux
    x = _norm(params, "lnf", x, cfg)
    return _head(params, x), aux_total                  # tied head


def prefill(params: Params, prompt, *,
            cfg: TransformerConfig = TransformerConfig(),
            total: Optional[int] = None, mesh=None, attn: str = "ring",
            dp_axis: str = "dp", sp_axis: str = "sp"):
    """Parallel prompt ingestion: ONE causal forward over the (B, P)
    prompt yields every layer's (k, v) projections — the decode KV
    cache — plus the last position's logits, instead of the O(P)
    sequential scan the from-scratch decode pays. With ``mesh``, the
    forward runs SEQUENCE-PARALLEL (ring/zigzag/ulysses over
    ``sp_axis``), so prompts longer than one device's memory prefill
    across the mesh — the long-context inference counterpart of the
    sharded train step.

    Returns ``(caches, last_logits)``: caches is the
    ``L{i}_{k,v} -> (B, total, H_kv, Dh)`` dict :func:`greedy_decode`
    uses (H_kv = ``kv_heads(cfg)``, which is where GQA's group-factor
    cache shrink shows up; zero-padded to ``total``, default P),
    last_logits is (B, vocab). Dense and MoE configs single-device; the
    sharded path is dense-only (expert sharding composes with
    training's dp, not with replicated-param prefill)."""
    b, p_len = prompt.shape
    if p_len < 1:
        raise ValueError("prompt must contain at least one token")
    _check_arch(cfg)
    total = p_len if total is None else total
    if total < p_len:
        raise ValueError(f"total={total} shorter than the prompt {p_len}")
    _check_seq(total, cfg)
    cfg_fwd = dataclasses.replace(cfg, remat=False)  # capture ≠ remat
    tokens = prompt.astype(jnp.int32)
    h, hd = cfg.n_heads, cfg.d_model // cfg.n_heads

    if mesh is None:
        sink: list = []
        # backend="auto": the fused flash kernel on TPU — prefilling a
        # long prompt is exactly the workload whose (P, P) score matrix
        # must not land in HBM; off-TPU this resolves to the XLA oracle
        logits, _ = _forward(
            params, tokens, jnp.arange(p_len), cfg_fwd,
            lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            backend="auto",
                                            window=cfg.window),
            block=functools.partial(_block, kv_sink=sink))
        kvs = sink
    else:
        if cfg.moe_experts:
            raise ValueError("sequence-parallel prefill supports dense "
                             "configs; MoE prefills single-device")
        if cfg.window and attn != "ring":
            raise ValueError("sequence-parallel sliding-window prefill "
                             "runs the banded ring (attn='ring')")
        n_sp = mesh.shape[sp_axis]
        attn_shard = _attn_shard_fn(attn, sp_axis, n_sp, cfg)

        def shard_fwd(params, toks):
            l_loc = toks.shape[1]
            pos = _shard_pos(attn, sp_axis, n_sp, l_loc)
            sink: list = []
            logits, _ = _forward(
                params, toks, pos, cfg_fwd, attn_shard,
                block=functools.partial(_block, kv_sink=sink))
            ks = jnp.stack([kk for kk, _ in sink])  # (nl, B, Lloc, Hkv, hd)
            vs = jnp.stack([vv for _, vv in sink])
            return logits, ks, vs

        tokens_z, perm = _maybe_zigzag(attn, n_sp, tokens)
        # inference batches are often smaller than the training dp
        # size: when B doesn't divide it, replicate the batch axis and
        # keep only the sequence sharded (the memory that matters at
        # long context is the L axis anyway)
        bspec = dp_axis if b % mesh.shape[dp_axis] == 0 else None
        fn = shard_map(
            shard_fwd, mesh=mesh,
            in_specs=(P(), P(bspec, sp_axis)),
            out_specs=(P(bspec, sp_axis),
                       P(None, bspec, sp_axis),
                       P(None, bspec, sp_axis)))
        logits, ks, vs = fn(params, tokens_z)
        if perm is not None:                 # back to standard order
            inv = perm.argsort()
            logits = logits[:, inv]
            ks, vs = ks[:, :, inv], vs[:, :, inv]
        kvs = [(ks[i], vs[i]) for i in range(cfg.n_layers)]

    caches = {}
    for i, (k, v) in enumerate(kvs):
        pad = total - p_len
        caches[f"L{i}_k"] = jnp.pad(
            k, ((0, 0), (0, pad), (0, 0), (0, 0))).astype(
                params["tok_emb"].dtype)
        caches[f"L{i}_v"] = jnp.pad(
            v, ((0, 0), (0, pad), (0, 0), (0, 0))).astype(
                params["tok_emb"].dtype)
    return caches, logits[:, -1].astype(jnp.float32)


def greedy_decode(params: Params, prompt, n_new: int, *,
                  cfg: TransformerConfig = TransformerConfig(),
                  temperature: float = 0.0,
                  top_k: Optional[int] = None,
                  key=None, use_prefill: bool = False, mesh=None,
                  attn: str = "ring", dp_axis: str = "dp",
                  sp_axis: str = "sp",
                  kv_q8: bool = False) -> jnp.ndarray:
    """KV-cached decoding: (B, P) int32 prompt → (B, P+n_new).

    The inference half of the LM family (training: make_train_step).
    One ``lax.scan`` over positions with per-layer (B, L, H_kv, Dh)
    caches in the carry (H_kv < H under GQA — the cache shrinks by the
    group factor) — static shapes throughout, so the whole decode is one
    compiled program; each step attends its single query against the
    cache under an iota≤t mask. Inside the prompt the next input is the
    given token (prefill and generation share one code path); after it,
    the selected token: argmax when ``temperature`` is 0 (greedy — the
    default, pinned token-exact against re-running the FULL forward at
    every prefix), otherwise a categorical sample of logits/temperature
    (requires ``key``), optionally truncated to the ``top_k`` highest
    logits. Sampling is deterministic per (key, position).

    MoE configs decode with capacity-bounded switch routing per STEP:
    the routing group at position t is that step's B tokens (one per
    batch row), with the effective capacity ``min(moe_capacity, B)`` —
    a bucket can never hold more than B tokens, so the clamp changes
    no drop decision, only the dispatch shapes. When no bucket
    overflows anywhere (capacity ≥ its worst-case load), decode is
    token-exact against the full-forward oracle; under overflow the
    drop ORDER differs (the oracle's cumulative token order runs over
    the whole (B, L) tile, a step's over its B tokens), matching the
    train-time rule that capacity semantics follow the routing group.

    ``kv_q8=True`` stores the KV caches int8 with per-row f32 scales
    (ops/decode.quantize_kv): the cache is the dominant decode byte
    stream, so its HBM traffic halves. Rows quantize as they are
    written (prefill caches quantize once at the boundary); the fused
    decode kernel folds the scales into its contractions without ever
    materializing a dequantized cache. A serving knob, orthogonal to
    ``quantize_lm`` (int8 weights) — the two compose into the full
    int8 serving story.

    ``use_prefill=True`` ingests the prompt with :func:`prefill` — one
    parallel causal forward instead of P sequential steps — then scans
    only the ``n_new`` generation positions. With ``mesh`` the prefill
    runs sequence-parallel (``attn`` selects ring/zigzag/ulysses over
    ``dp_axis``/``sp_axis``), so prompts at training-scale context
    lengths decode without ever holding full attention on one device.
    Dense configs produce the same tokens either way (the prompt caches
    are the same projections computed batched); MoE configs match as
    long as no routing bucket overflows — prefill routes the whole
    (B, P) prompt as one group (the oracle grouping) while the scan
    routes B tokens per step, so under overflow the two drop DIFFERENT
    tokens and may diverge, the same caveat as decode-vs-oracle."""
    _check_arch(cfg)
    if cfg.moe_experts:
        _check_moe(cfg)
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    b, p_len = prompt.shape
    if p_len < 1:
        raise ValueError("prompt must contain at least one token "
                         "(an empty prompt would silently return an "
                         "empty continuation)")
    total = p_len + n_new
    _check_seq(total, cfg)
    h, hd = cfg.n_heads, cfg.d_model // cfg.n_heads
    hkv = kv_heads(cfg)
    g = h // hkv            # query heads per kv head (1 = plain MHA)
    # per-step routing group = B tokens; clamp dispatch capacity to it
    step_cfg = (dataclasses.replace(cfg, moe_capacity=min(cfg.moe_capacity,
                                                          b))
                if cfg.moe_experts else cfg)

    # GQA: the cache holds H_kv heads — the group-factor cache shrink
    # is the point of n_kv_heads at decode time. A sliding window
    # additionally makes the cache a ROLLING buffer of `window` slots
    # (position p lives in slot p mod window): the scan carry is O(w)
    # instead of O(total), the serving memory the window exists for.
    # Rolling containment IS the window mask — slot contents are
    # exactly the positions (t-w, t], so the only masking left is
    # "slot not yet filled" during the first w steps.
    roll = bool(cfg.window) and cfg.window < total
    cache_len = cfg.window if roll else total
    # caches ride the scan carry as (B, H_kv, S, D) — per-(batch, head)
    # rows contiguous, the ops/decode.py layout contract (no per-step
    # transpose for the fused kernel OR the XLA einsums). ``kv_q8``
    # stores them int8 with per-row f32 scales (ops/decode.quantize_kv)
    # — half the dominant decode byte stream; serving accuracy, not
    # training semantics (the scan quantizes each row as it is written)
    cache_dtype = jnp.int8 if kv_q8 else params["tok_emb"].dtype
    caches = {
        f"L{i}_{kv}": jnp.zeros((b, hkv, cache_len, hd), cache_dtype)
        for i in range(cfg.n_layers) for kv in ("k", "v")
    }
    if kv_q8:
        caches.update({
            f"L{i}_{kv}s": jnp.zeros((b, hkv, cache_len), jnp.float32)
            for i in range(cfg.n_layers) for kv in ("k", "v")
        })
    # position t reads its input from `prompt` while t < p_len, else the
    # previously generated token riding the carry
    pad = jnp.zeros((b, total - p_len), jnp.int32)
    given = jnp.concatenate([prompt.astype(jnp.int32), pad], axis=1)

    def step(carry, t):
        caches, cur = carry
        tok = jnp.where(t < p_len, given[:, t], cur)    # (B,)
        x = params["tok_emb"][tok]                      # (B, D)
        if not cfg.rope:
            x = x + params["pos_emb"][t]
        x = x[:, None, :]                               # (B, 1, D)
        for i in range(cfg.n_layers):
            pfx = f"L{i}"
            y = _norm(params, f"{pfx}_ln1", x, cfg)
            qkv = _mm(params, f"{pfx}_qkv_W", y)
            q = qkv[..., :h * hd].reshape(b, 1, h, hd)
            k = qkv[..., h * hd:(h + hkv) * hd].reshape(b, 1, hkv, hd)
            v = qkv[..., (h + hkv) * hd:].reshape(b, 1, hkv, hd)
            if cfg.rope:
                # rotate THIS position; cache stores rotated keys (the
                # same convention the prefill capture uses)
                q = _rope(q, t[None], cfg.rope_base)
                k = _rope(k, t[None], cfg.rope_base)
            # (B, 1, Hkv, D) → (B, Hkv, 1, D) cache-layout row
            k = jnp.transpose(k, (0, 2, 1, 3))
            v = jnp.transpose(v, (0, 2, 1, 3))
            # head index = (kv head, group member), kv-head major —
            # the grouping decode_attention's (B, Hkv, G, D) q expects
            q = q.reshape(b, hkv, g, hd)
            slot = t % cache_len if roll else t
            scales = {}
            if kv_q8:
                k, ks_row = quantize_kv(k)
                v, vs_row = quantize_kv(v)
                cks = lax.dynamic_update_slice(
                    caches[f"{pfx}_ks"], ks_row, (0, 0, slot))
                cvs = lax.dynamic_update_slice(
                    caches[f"{pfx}_vs"], vs_row, (0, 0, slot))
                caches = {**caches, f"{pfx}_ks": cks, f"{pfx}_vs": cvs}
                scales = {"k_scale": cks, "v_scale": cvs}
            ck = lax.dynamic_update_slice(
                caches[f"{pfx}_k"], k, (0, 0, slot, 0))
            cv = lax.dynamic_update_slice(
                caches[f"{pfx}_v"], v, (0, 0, slot, 0))
            caches = {**caches, f"{pfx}_k": ck, f"{pfx}_v": cv}
            # fused decode attention (ops/decode.py): flash-decode
            # kernel on TPU, the identical einsum+mask+softmax
            # composition elsewhere. Non-roll windows are total-length
            # (roll covers window < total), so slot<=t IS the mask.
            a = decode_attention(q, ck, cv, t, roll=roll,
                                 backend="auto", **scales)
            a = a.astype(x.dtype).reshape(b, 1, cfg.d_model)
            x = x + _mm(params, f"{pfx}_out_W", a)
            y = _norm(params, f"{pfx}_ln2", x, cfg)
            ff, _ = _ffn(params, pfx, y, step_cfg, None)
            x = x + ff
        x = _norm(params, "lnf", x, cfg)
        logits = _head(params, x)[:, 0]                 # (B, vocab)
        nxt = select(logits, t)
        return (caches, nxt), nxt

    def select(logits, t):
        """Next token from (B, vocab) logits at position t — shared by
        the scan step and the prefill fast path (same fold_in(key, t)
        stream, so both paths sample identical tokens)."""
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lg = logits.astype(jnp.float32) / temperature
        if top_k is not None and top_k < cfg.vocab:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg >= kth, lg, _NEG_INF)
        return jax.random.categorical(
            jax.random.fold_in(key, t), lg, axis=-1).astype(jnp.int32)

    if use_prefill:
        if n_new == 0:
            return prompt.astype(jnp.int32)
        caches, last_logits = prefill(params, prompt, cfg=cfg,
                                      total=total, mesh=mesh, attn=attn,
                                      dp_axis=dp_axis, sp_axis=sp_axis)
        # prefill's public contract is (B, S, H_kv, D); the decode scan
        # holds (B, H_kv, S, D) — one transpose at the boundary, not
        # one per step
        caches = {n: jnp.transpose(c, (0, 2, 1, 3))
                  for n, c in caches.items()}
        if kv_q8:
            quant = {}
            for n, c in caches.items():
                quant[n], quant[n + "s"] = quantize_kv(c)
            caches = quant
        if roll:
            # fold the prompt cache into the rolling layout: slot j
            # holds the LAST prompt position ≡ j (mod w). Scale entries
            # (kv_q8) are (B, H_kv, S) — same slot axis, same fold.
            if p_len >= cache_len:
                j = jnp.arange(cache_len)
                src = p_len - 1 - ((p_len - 1 - j) % cache_len)
                caches = {n: c[:, :, src] for n, c in caches.items()}
            else:
                # positions 0..p_len-1 land in slots 0..p_len-1 and the
                # prefill cache is already zero-padded beyond them —
                # a plain truncation IS the rolling layout
                caches = {n: c[:, :, :cache_len]
                          for n, c in caches.items()}
        tok1 = select(last_logits, p_len - 1)
        # remaining n_new - 1 positions ride the ordinary step scan
        (_, _), emitted = lax.scan(step, (caches, tok1),
                                   jnp.arange(p_len, total - 1))
        gen = jnp.concatenate(
            [tok1[:, None], jnp.transpose(emitted, (1, 0))], axis=1)
        return jnp.concatenate([prompt.astype(jnp.int32), gen], axis=1)

    (_, _), emitted = lax.scan(step, (caches, given[:, 0]),
                               jnp.arange(total))
    # emitted[t] is the model's prediction AFTER seeing position t;
    # output = prompt ‖ generated continuation
    gen = jnp.transpose(emitted, (1, 0))[:, p_len - 1:total - 1]
    return jnp.concatenate([prompt.astype(jnp.int32), gen], axis=1)


def transformer_apply(params: Params, tokens, *,
                      cfg: TransformerConfig = TransformerConfig()
                      ) -> jnp.ndarray:
    """Single-device oracle: (B, L) tokens → (B, L, vocab) logits."""
    _check_seq(tokens.shape[1], cfg)
    pos = jnp.arange(tokens.shape[1])
    logits, _ = _forward(params, tokens, pos, cfg,
                         functools.partial(attention_reference,
                                           causal=True,
                                           window=cfg.window))
    return logits


def _attn_shard_fn(attn: str, sp_axis: str, n_sp: int,
                   cfg: TransformerConfig, n_heads: Optional[int] = None):
    """Resolve the sequence-parallel attention body; strict — a typo'd
    name or an infeasible head split must fail at factory time, never as
    a shape error deep inside a collective. ``n_heads`` overrides the
    head count the divisibility check sees (the 3-D form passes its
    per-tp-slice count)."""
    n_heads = cfg.n_heads if n_heads is None else n_heads
    if cfg.window and attn != "ring":
        raise ValueError(
            "sliding-window attention (cfg.window > 0) runs "
            "sequence-parallel as the BANDED contiguous ring "
            "(attn='ring'); zigzag balances full-causal work a window "
            "already bounds, and ulysses materializes full-sequence "
            "heads per device")
    if attn == "ring":
        return functools.partial(_ring_shard, axis=sp_axis,
                                 n_shards=n_sp, causal=True,
                                 window=cfg.window)
    if attn == "zigzag":
        return functools.partial(_ring_shard_zigzag, axis=sp_axis,
                                 n_shards=n_sp, causal=True)
    if attn == "ulysses":
        if n_heads % n_sp:
            raise ValueError(
                f"ulysses needs n_heads divisible by the {sp_axis} axis: "
                f"{n_heads} heads over {n_sp} devices")
        if kv_heads(cfg) % n_sp:
            raise ValueError(
                f"ulysses needs n_kv_heads divisible by the {sp_axis} "
                f"axis: {kv_heads(cfg)} kv heads over {n_sp} devices "
                f"(ring/zigzag have no such constraint)")
        return functools.partial(_ulysses_shard, axis=sp_axis,
                                 n_shards=n_sp, causal=True)
    raise ValueError(f"unknown attn {attn!r} "
                     f"(want 'ring', 'zigzag' or 'ulysses')")


def _shard_pos(attn: str, sp_axis: str, n_sp: int, l_loc: int):
    """This device's global positions: contiguous for ring/ulysses, the
    two-stripe layout for zigzag (parallel/ring_attention._zigzag_perm)
    — shared by every shard_step/shard_fwd body."""
    if attn == "zigzag":
        h = l_loc // 2
        my = lax.axis_index(sp_axis)
        return jnp.concatenate([my * h + jnp.arange(h),
                                (2 * n_sp - 1 - my) * h + jnp.arange(h)])
    return lax.axis_index(sp_axis) * l_loc + jnp.arange(l_loc)


def _maybe_zigzag(attn: str, n_sp: int, *seqs, pre_permuted: bool = False):
    """Apply the internal zigzag permutation to (B, L) sequence arrays
    at a step/apply boundary; identity for other schedules. Returns the
    permuted arrays plus the permutation (None when not zigzag) so a
    forward can un-permute its outputs.

    ``pre_permuted=True`` (zigzag only) declares the arrays already in
    zigzag layout — validated, not re-permuted (the caller permuted
    host-side via ``shard_batch(..., schedule="zigzag")``, avoiding the
    per-step cross-shard gather of sharded arrays)."""
    if attn != "zigzag":
        return (*seqs, None)
    _zigzag_check(seqs[0].shape[1], n_sp)
    perm = _zigzag_perm(seqs[0].shape[1], n_sp)
    if pre_permuted:
        return (*seqs, perm)
    return (*(s[:, perm] for s in seqs), perm)


def make_sharded_apply(cfg: TransformerConfig, mesh, *,
                       attn: str = "ring", dp_axis: str = "dp",
                       sp_axis: str = "sp"):
    """Jitted forward over the mesh: tokens P(dp, sp), attention
    sequence-parallel over ``sp``. Dense params are replicated; with
    ``cfg.moe_experts`` > 0 the expert stacks shard over dp and params
    must come from :func:`shard_params_moe`."""
    _check_arch(cfg)
    n_sp = mesh.shape[sp_axis]
    attn_shard = _attn_shard_fn(attn, sp_axis, n_sp, cfg)
    moe_axis = dp_axis if cfg.moe_experts else None
    if cfg.moe_experts:
        _check_moe(cfg, mesh.shape[dp_axis])
    block = functools.partial(_block, moe_axis=moe_axis)
    suffix = param_specs_moe(dp_axis)

    def shard_fwd(params, tokens):
        l_loc = tokens.shape[1]
        _check_seq(l_loc * n_sp, cfg)
        pos = _shard_pos(attn, sp_axis, n_sp, l_loc)
        return _forward(params, tokens, pos, cfg, attn_shard,
                        block=block)[0]

    def apply(params, tokens):
        # specs derive from the ACTUAL param keys so the tree can never
        # drift from init_transformer's key set
        specs = {k: _spec_for(k, suffix) for k in params} \
            if cfg.moe_experts else P()
        # zigzag: permute in, un-permute out — callers see
        # standard order (perm is None otherwise)
        tokens, perm = _maybe_zigzag(attn, n_sp, tokens)
        fn = shard_map(shard_fwd, mesh=mesh,
                           in_specs=(specs, P(dp_axis, sp_axis)),
                           out_specs=P(dp_axis, sp_axis))
        out = fn(params, tokens)
        return out if perm is None else out[:, perm.argsort()]

    return jax.jit(apply)


def _mean_nll(logits, targets):
    """Mean next-token NLL — the ONE loss tail every execution form
    shares (a loss change here reaches dp/sp/tp/ep/pp alike)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def lm_loss_local(params, tokens, targets, cfg, attn_fn, pos, block=None):
    """Mean next-token NLL (+ weighted MoE aux loss) on this device's
    tile (targets pre-shifted by the caller — with a sharded sequence
    the shift crosses shard edges, so it happens host-side before
    sharding)."""
    logits, aux = _forward(params, tokens, pos, cfg, attn_fn, block=block)
    return _mean_nll(logits, targets) + cfg.moe_aux_weight * aux


def param_specs_moe(ep_axis: str = "dp") -> Dict[str, object]:
    """Suffix→PartitionSpec for expert-parallel params: expert FFN
    stacks shard on their leading experts axis; the router replicates."""
    return {
        "_moe_w1": P(ep_axis), "_moe_b1": P(ep_axis),
        "_moe_w2": P(ep_axis), "_moe_b2": P(ep_axis),
    }


def shard_params_moe(params: Params, mesh, *, ep_axis: str = "dp"
                     ) -> Params:
    """device_put params with expert stacks sharded over ``ep_axis``."""
    specs = param_specs_moe(ep_axis)
    return {k: jax.device_put(v, NamedSharding(mesh, _spec_for(k, specs)))
            for k, v in params.items()}


def make_train_step(cfg: TransformerConfig, mesh, optimizer, *,
                    attn: str = "ring", dp_axis: str = "dp",
                    sp_axis: str = "sp", grad_accum: int = 1,
                    zigzag_layout: bool = False, zero1: bool = False):
    """Jitted SPMD LM train step: ``step(params, opt_state, tokens,
    targets) -> (params, opt_state, loss)`` with tokens/targets sharded
    P(dp, sp) and the gradient all-reduce (pmean over dp AND sp) fused
    into the backward pass.

    ``grad_accum`` > 1 folds that many microbatches (split along each
    device's batch rows) in a lax.scan before the single optimizer
    update — activation memory ÷ grad_accum, numbers identical to the
    whole tile (the long-context lever that composes with cfg.remat:
    remat bounds per-layer activations, accumulation bounds the batch).

    With ``cfg.moe_experts`` > 0 the block FFNs are switch-MoE with
    experts sharded over the dp axis (the standard ep ≡ dp grouping:
    expert buckets ride all_to_all between data-parallel peers); params
    must then come from :func:`shard_params_moe`.

    ``zigzag_layout=True`` (``attn="zigzag"`` only) declares tokens and
    targets ALREADY in zigzag order — feed batches through
    ``shard_batch(..., schedule="zigzag")``, which permutes host-side
    before device_put. The default path permutes inside the jitted step,
    which on P(dp, sp)-sharded arrays is a per-step cross-shard gather
    (ADVICE r2); the pre-permuted path removes it from steady state.

    ``zero1=True`` shards the OPTIMIZER STATE over the dp axis
    (parallel/zero1.py): gradients reduce-scatter instead of
    all-reducing, each dp rank updates only its 1/n_dp chunk of every
    parameter (Adam's m/v shrink by n_dp), and the updated chunks
    all-gather back — same wire traffic as the all-reduce, optimizer
    memory ÷ n_dp. The opt_state must come from
    :func:`parallel.zero1.init_state`. Elementwise optimizers only;
    dense configs (MoE already spends the dp axis on experts).
    Composes with ``grad_accum`` (the microbatch fold feeds the same
    reduce-scatter) and every ``attn`` schedule."""
    if zigzag_layout and attn != "zigzag":
        raise ValueError("zigzag_layout=True requires attn='zigzag'")
    if zero1 and cfg.moe_experts:
        raise ValueError("zero1 shards optimizer state over dp, "
                         "which MoE already spends on experts")
    _check_arch(cfg)
    n_sp = mesh.shape[sp_axis]
    attn_shard = _attn_shard_fn(attn, sp_axis, n_sp, cfg)
    moe_axis = None
    if cfg.moe_experts:
        if grad_accum > 1:
            raise ValueError(
                "grad_accum > 1 with moe_experts > 0 would silently "
                "change the numbers: MoE capacity and the aux loss are "
                "defined per device tile, so quarter-size microbatches "
                "drop/route tokens differently than the whole tile")
        _check_moe(cfg, mesh.shape[dp_axis])
        moe_axis = dp_axis
    block = functools.partial(_block, moe_axis=moe_axis)
    suffix = param_specs_moe(dp_axis)

    def shard_step(params, tokens, targets):
        l_loc = tokens.shape[1]
        _check_seq(l_loc * n_sp, cfg)
        pos = _shard_pos(attn, sp_axis, n_sp, l_loc)

        def global_loss(p, tok, tgt):
            local = lm_loss_local(p, tok, tgt, cfg, attn_shard,
                                  pos, block=block)
            return lax.pmean(lax.pmean(local, sp_axis), dp_axis)

        if grad_accum == 1:
            loss, grads = jax.value_and_grad(global_loss)(
                params, tokens, targets)
        else:
            # MoE composes with accum is rejected above, so every leaf
            # here is replicated over both data axes and the uniform
            # scan-carry stamp is an identity
            loss, grads = accum_value_and_grad(
                global_loss, params, (tokens, targets), grad_accum,
                stamp=lambda l, g: (
                    stamp_replicated(l, (dp_axis, sp_axis)),
                    stamp_replicated(g, (dp_axis, sp_axis))))
        # per-leaf replication stamp (utils/jax_compat.py): each grad
        # is replicated over the data axes its out_spec omits (the
        # transpose machinery psums replicated-param cotangents; MoE
        # expert grads keep their dp-local slice and stamp over sp
        # only) — the pmean identity makes that statically inferable
        # so the rep/vma check stays ON

        def _stamp(k, g):
            have = spec_axes(_spec_for(k, suffix)) if cfg.moe_experts \
                else set()
            return stamp_replicated(
                g, tuple(a for a in (dp_axis, sp_axis)
                         if a not in have))

        return loss, {k: _stamp(k, g) for k, g in grads.items()}

    def shard_step_zero1(params, opt_state, tokens, targets):
        """The ZeRO-1 body: loss/grad per rank, dp-mean via
        reduce-scatter, chunk update, all-gather (parallel/zero1.py).
        Lives INSIDE shard_map so the optimizer runs on per-rank
        chunks; the replicated path keeps its update outside."""
        l_loc = tokens.shape[1]
        _check_seq(l_loc * n_sp, cfg)
        pos = _shard_pos(attn, sp_axis, n_sp, l_loc)
        n_dp = mesh.shape[dp_axis]

        def local_loss(p, tok, tgt):
            return lm_loss_local(p, tok, tgt, cfg, attn_shard,
                                 pos, block=block)

        if grad_accum == 1:
            loss, grads = jax.value_and_grad(local_loss)(
                params, tokens, targets)
        else:
            # the microbatch fold composes: it returns the tile-mean
            # LOCAL loss/grads, which then ride the same sp-pmean +
            # dp reduce-scatter as the unaccumulated path
            loss, grads = accum_value_and_grad(
                local_loss, params, (tokens, targets), grad_accum)
        # sp first: grads must be identical along every non-dp axis
        # before the dp reduce-scatter
        grads = jax.tree.map(lambda g: lax.pmean(g, sp_axis), grads)
        params, opt_state = _z1.update_chunks(
            optimizer, params, grads, opt_state, dp_axis, n_dp)
        return params, opt_state, lax.pmean(
            lax.pmean(loss, sp_axis), dp_axis)

    def step(params, opt_state, tokens, targets):
        # specs derive from the ACTUAL param keys (cannot drift from
        # init_transformer; same pattern as the 3-D step)
        specs = {k: _spec_for(k, suffix) for k in params} \
            if cfg.moe_experts else P()
        # zigzag: tokens AND targets ride the same internal
        # permutation; the loss is a token mean, so no
        # un-permutation is needed — drop-in for the ring
        tokens, targets, _ = _maybe_zigzag(attn, n_sp, tokens, targets,
                                           pre_permuted=zigzag_layout)
        if zero1:
            st_specs = _z1.state_specs(opt_state, dp_axis)
            # check_vma off: the all_gather'd params ARE replicated
            # (chunks updated from dp-invariant inputs), but the static
            # varying-axes checker cannot prove it through all_gather
            mapped = shard_map(
                shard_step_zero1, mesh=mesh,
                in_specs=(P(), st_specs, P(dp_axis, sp_axis),
                          P(dp_axis, sp_axis)),
                out_specs=(P(), st_specs, P()), check_vma=False)
            return mapped(params, opt_state, tokens, targets)
        mapped = shard_map(
            shard_step, mesh=mesh,
            in_specs=(specs, P(dp_axis, sp_axis), P(dp_axis, sp_axis)),
            out_specs=(P(), specs))
        loss, grads = mapped(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def shard_batch(mesh, tokens, targets, dp_axis="dp", sp_axis="sp",
                schedule="contiguous"):
    """Place a (B, L) batch with batch over dp, sequence over sp.

    ``schedule="zigzag"`` permutes both arrays into zigzag sequence
    order HOST-SIDE before device_put (cheap numpy indexing, not a
    cross-shard collective) — the data path for train steps built with
    ``zigzag_layout=True``. Tokens and targets ride the same
    permutation, so the next-token pairing is preserved row-wise."""
    if schedule == "zigzag":
        n_sp = mesh.shape[sp_axis]
        _zigzag_check(np.shape(tokens)[1], n_sp)
        perm = _zigzag_perm(np.shape(tokens)[1], n_sp)
        tokens = np.asarray(tokens)[:, perm]
        targets = np.asarray(targets)[:, perm]
    elif schedule != "contiguous":
        raise ValueError(f"unknown schedule {schedule!r}")
    sharding = NamedSharding(mesh, P(dp_axis, sp_axis))
    return (jax.device_put(tokens, sharding),
            jax.device_put(targets, sharding))


# ---------------------------------------------------------------------------
# 3-D parallel form: data x sequence x tensor (Megatron-style tp)
# ---------------------------------------------------------------------------
#
# Attention heads and MLP hidden units shard over ``mp``; activations stay
# replicated across mp at block boundaries via one psum after the
# attention out-projection and one after the second MLP matmul (the
# Megatron pattern). Composes with the sp ring: each mp slice runs the
# ring over ITS heads. Gradient flow needs no hand-written collectives —
# the loss is pmean'd over the data axes (dp, sp) ONLY; shard_map's
# transpose machinery then psums replicated-param cotangents over every
# axis they were broadcast to (including mp), while mp-sharded params
# keep their local slice gradients.
#
# tp weights use head-structured layouts so a PartitionSpec can split
# them per head rather than per raw column: qkv (d, 3, H, hd) sharded on
# H; out-proj (H, hd, d) sharded on H; MLP (d, ff)/(ff, d) sharded on ff.

def param_specs_3d(mp_axis: str = "mp") -> Dict[str, object]:
    """PartitionSpec per parameter-name PATTERN (suffix match)."""
    return {
        "_qkv_W": P(None, None, mp_axis, None),
        "_out_W": P(mp_axis, None, None),
        "_ff1_W": P(None, mp_axis),
        "_ff1_b": P(mp_axis),
        "_ff3_W": P(None, mp_axis),     # swiglu up (columns, like gate)
        "_ff2_W": P(mp_axis, None),
    }


def _spec_for(name: str, specs: Dict[str, object]):
    for suffix, spec in specs.items():
        if name.endswith(suffix):
            return spec
    return P()


def shard_params_3d(params: Params, mesh, cfg: TransformerConfig, *,
                    mp_axis: str = "mp") -> Params:
    """Reshape tp weights to head-structured layouts and device_put every
    param with its 3-D sharding (inverse: :func:`unshard_params_3d`)."""
    d, h = cfg.d_model, cfg.n_heads
    if kv_heads(cfg) != h:
        raise ValueError("the 3-D tp path shards the fused qkv by head "
                         "and supports MHA only; GQA composes with "
                         "dp/sp (make_train_step) in the current build")
    hd = d // h
    specs = param_specs_3d(mp_axis)
    out: Params = {}
    for name, w in params.items():
        if name.endswith("_qkv_W"):
            w = w.reshape(d, 3, h, hd)
        elif name.endswith("_out_W"):
            w = w.reshape(h, hd, d)
        out[name] = jax.device_put(
            w, NamedSharding(mesh, _spec_for(name, specs)))
    return out


def unshard_params_3d(params: Params, cfg: TransformerConfig) -> Params:
    """Back to the canonical 2-D layouts (for checkpoints / the oracle)."""
    d = cfg.d_model
    out: Params = {}
    for name, w in params.items():
        if name.endswith("_qkv_W"):
            w = jnp.asarray(w).reshape(d, 3 * d)
        elif name.endswith("_out_W"):
            w = jnp.asarray(w).reshape(d, d)
        out[name] = w
    return out


def _block_tp(params: Params, i: int, x, cfg: TransformerConfig, attn_fn,
              pos, mp_axis: str):
    """One decoder block on LOCAL tp slices; x enters and leaves
    replicated across mp. Rope rotates this slice's heads by the same
    global ``pos`` (per-head independent, so head sharding is free);
    swiglu shards gate/up columns and down rows like gelu's ff1/ff2."""
    p = f"L{i}"
    y = _norm(params, f"{p}_ln1", x, cfg)
    w_qkv = params[f"{p}_qkv_W"]                # (d, 3, H/mp, hd) local
    q, k, v = (jnp.einsum("bld,dhk->blhk", y, w_qkv[:, t])
               for t in range(3))               # (B, L, H/mp, hd)
    if cfg.rope:
        q = _rope(q, pos, cfg.rope_base)
        k = _rope(k, pos, cfg.rope_base)
    a = attn_fn(q, k, v)                        # this mp slice's heads
    partial = jnp.einsum("blhk,hkd->bld", a, params[f"{p}_out_W"])
    x = x + lax.psum(partial, mp_axis)          # Megatron sync point 1
    y = _norm(params, f"{p}_ln2", x, cfg)
    if cfg.ffn == "swiglu":
        h = jax.nn.silu(y @ params[f"{p}_ff1_W"]) * (y @ params[f"{p}_ff3_W"])
        return x + lax.psum(h @ params[f"{p}_ff2_W"], mp_axis), 0.0
    y = jax.nn.gelu(y @ params[f"{p}_ff1_W"] + params[f"{p}_ff1_b"])
    partial = y @ params[f"{p}_ff2_W"]
    return x + lax.psum(partial, mp_axis) + params[f"{p}_ff2_b"], 0.0


def make_train_step_3d(cfg: TransformerConfig, mesh, optimizer, *,
                       attn: str = "ring", dp_axis: str = "dp",
                       sp_axis: str = "sp", mp_axis: str = "mp",
                       grad_accum: int = 1, zigzag_layout: bool = False):
    """Jitted LM train step over a (dp, sp, mp) mesh. ``params`` must
    come from :func:`shard_params_3d`; tokens/targets are P(dp, sp).
    ``grad_accum`` and ``zigzag_layout`` as in :func:`make_train_step` —
    microbatch fold before the single optimizer update; host-side
    pre-permuted zigzag batches via ``shard_batch(schedule="zigzag")``."""
    if zigzag_layout and attn != "zigzag":
        raise ValueError("zigzag_layout=True requires attn='zigzag'")
    _check_arch(cfg)
    n_sp = mesh.shape[sp_axis]
    n_mp = mesh.shape[mp_axis]
    if cfg.n_heads % n_mp:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by "
                         f"{mp_axis}={n_mp}")
    if kv_heads(cfg) != cfg.n_heads:
        raise ValueError("the 3-D tp path shards the fused qkv by head "
                         "and supports MHA only; GQA composes with "
                         "dp/sp (make_train_step) in the current build")
    if cfg.moe_experts:
        raise ValueError("MoE blocks are not supported on the 3-D tp "
                         "path; use make_train_step (experts over dp)")
    # the ulysses divisibility check sees the PER-TP-SLICE head count
    attn_shard = _attn_shard_fn(attn, sp_axis, n_sp, cfg,
                                n_heads=cfg.n_heads // n_mp)
    tp_block = functools.partial(_block_tp, mp_axis=mp_axis)
    specs = param_specs_3d(mp_axis)

    def shard_step(params, tokens, targets):
        l_loc = tokens.shape[1]
        _check_seq(l_loc * n_sp, cfg)
        pos = _shard_pos(attn, sp_axis, n_sp, l_loc)

        def global_loss(p, tok, tgt):
            local = lm_loss_local(p, tok, tgt, cfg, attn_shard,
                                  pos, block=tp_block)
            # pmean over the DATA axes only: the mp axis carries the
            # same loss replicated, and omitting it keeps the
            # backward-pass psum of replicated-param cotangents at the
            # right scale (sum of per-slice contributions, unscaled)
            return lax.pmean(lax.pmean(local, sp_axis), dp_axis)

        if grad_accum == 1:
            return jax.value_and_grad(global_loss)(params, tokens,
                                                   targets)
        return accum_value_and_grad(global_loss, params,
                                    (tokens, targets), grad_accum)

    def specs_tree(params_like):
        return {k: _spec_for(k, specs) for k in params_like}

    def step(params, opt_state, tokens, targets):
        # same internal zigzag permutation as the 2-D step
        tokens, targets, _ = _maybe_zigzag(attn, n_sp, tokens, targets,
                                           pre_permuted=zigzag_layout)
        mapped = shard_map(
            shard_step, mesh=mesh,
            in_specs=(specs_tree(params), P(dp_axis, sp_axis),
                      P(dp_axis, sp_axis)),
            out_specs=(P(), specs_tree(params)))
        loss, grads = mapped(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# ---------------------------------------------------------------------------
# Pipeline-parallel form: layer groups over a ``pp`` axis (GPipe schedule)
# ---------------------------------------------------------------------------
#
# The decoder blocks are homogeneous, so their weights stack on a leading
# layer axis and shard over ``pp`` — each stage owns n_layers/pp
# consecutive layers and scans over its local slice. Embedding + LM head
# (the tied tok_emb) and the final layernorm are replicated: every device
# embeds the microbatches identically and scores the (psum-broadcast)
# last-stage outputs identically, so only the block stack actually rides
# the pipeline (parallel/pipeline.py). Dense FFN blocks only — tp/MoE
# compose with dp/sp, not with this axis, in the current build.

def _layer_weight_names(params: Params) -> list:
    """Per-layer weight suffixes, derived from the ACTUAL keys (the set
    varies with cfg.ffn / cfg.norm — a fixed list would silently drop
    swiglu's ff3 or crash on rms's missing biases)."""
    return sorted(k[len("L0_"):] for k in params if k.startswith("L0_"))


def stack_params_pp(params: Params, cfg: TransformerConfig) -> Params:
    """Per-layer weights → one leading-layer-axis stack per weight name
    (``layers_<name>``); embeddings/final-ln keys pass through."""
    if cfg.moe_experts:
        raise ValueError("pipeline form supports dense blocks only")
    out: Params = {k: v for k, v in params.items()
                   if not k.startswith("L")}
    for name in _layer_weight_names(params):
        out[f"layers_{name}"] = jnp.stack(
            [params[f"L{i}_{name}"] for i in range(cfg.n_layers)])
    return out


def unstack_params_pp(stacked: Params, cfg: TransformerConfig) -> Params:
    """Inverse of :func:`stack_params_pp` (canonical per-layer names)."""
    out: Params = {k: jnp.asarray(v) for k, v in stacked.items()
                   if not k.startswith("layers_")}
    for k, v in stacked.items():
        if k.startswith("layers_"):
            name = k[len("layers_"):]
            w = jnp.asarray(v)
            for i in range(cfg.n_layers):
                out[f"L{i}_{name}"] = w[i]
    return out


def shard_params_pp(params: Params, mesh, cfg: TransformerConfig, *,
                    pp_axis: str = "pp") -> Params:
    """Stack and device_put: layer stacks split over ``pp``, rest
    replicated."""
    stacked = stack_params_pp(params, cfg)
    return {k: jax.device_put(
        v, NamedSharding(mesh, P(pp_axis) if k.startswith("layers_")
                         else P()))
        for k, v in stacked.items()}


def _block_stacked(w: Params, x, cfg: TransformerConfig, pos):
    """One dense decoder block from a single layer's weight dict (no
    name prefixes) with full local attention — the pipeline stage body.
    Delegates to _block so the pipeline computes EXACTLY the model the
    oracle it is golden-diffed against computes."""
    prefixed = {f"L0_{k}": v for k, v in w.items()}
    out, _aux = _block(prefixed, 0, x, cfg,
                       functools.partial(attention_reference,
                                         causal=True,
                                         window=cfg.window), pos)
    return out


def make_train_step_pp(cfg: TransformerConfig, mesh, optimizer, *,
                       n_micro: int, pp_axis: str = "pp"):
    """Jitted pipeline-parallel LM train step over a 1-D (pp,) mesh:
    ``step(params, opt_state, tokens, targets)`` with params from
    :func:`shard_params_pp` and tokens/targets replicated (B must divide
    by ``n_micro``). Reverse-mode AD transposes the GPipe scan into the
    backward pipeline — no hand-written schedule."""
    _check_arch(cfg)
    n_pp = mesh.shape[pp_axis]
    if cfg.n_layers % n_pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"{pp_axis}={n_pp}")
    if cfg.moe_experts:
        raise ValueError("pipeline form supports dense blocks only")

    def shard_step(params, tokens, targets):
        _check_seq(tokens.shape[1], cfg)
        b, l = tokens.shape
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by "
                             f"n_micro={n_micro}")
        mb = b // n_micro
        tok_m = tokens.reshape(n_micro, mb, l)
        tgt_m = targets.reshape(n_micro, mb, l)

        def global_loss(p):
            local_layers = {k[len("layers_"):]: v for k, v in p.items()
                            if k.startswith("layers_")}
            pos = jnp.arange(l)
            x_micro = p["tok_emb"][tok_m]
            if not cfg.rope:
                x_micro = x_micro + p["pos_emb"][pos]

            def stage(x):
                def body(x, w):
                    return _block_stacked(w, x, cfg, pos), None
                x, _ = lax.scan(body, x, local_layers)
                return x

            outs = pipeline_apply(stage, x_micro, pp_axis=pp_axis,
                                  n_stages=n_pp)       # (M, mb, l, d)
            x = _norm(p, "lnf", outs, cfg)
            logits = x @ p["tok_emb"].T
            return _mean_nll(logits, tgt_m)

        return jax.value_and_grad(global_loss)(params)

    def specs_for(params):
        return {k: (P(pp_axis) if k.startswith("layers_") else P())
                for k in params}

    def step(params, opt_state, tokens, targets):
        specs = specs_for(params)
        mapped = shard_map(
            shard_step, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs))
        loss, grads = mapped(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
