"""LeNet-5 — the CIFAR-10 conv model (BASELINE.json config 3).

The reference's NN capability delegates all tensor kernels to the external
APRIL-ANN toolkit (SURVEY.md §2.4: conv/pool/softmax live there, not in the
repo); BASELINE.json names "LeNet-5 CIFAR-10 (Pallas conv2d/maxpool
kernels)" as the target config. This module expresses LeNet-5 with this
framework's own TPU ops: ``ops.conv2d`` (im2col → MXU matmul),
``ops.maxpool2d`` and ``ops.log_softmax`` (Pallas kernels), so the whole
forward pass is conv-as-matmul on the systolic array.

Layouts are TPU-native: activations NHWC, weights HWIO (channel = lane
dim). Params are a flat name→array dict — the same per-parameter-name key
space the MapReduce grad shuffle partitions on (the APRIL-ANN example
emits gradients keyed by parameter name, common.lua:85-104), so the model
drops into both the TPU-native trainer and the six-function engine path.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from lua_mapreduce_tpu.ops.conv import conv2d
from lua_mapreduce_tpu.ops.pool import maxpool2d
from lua_mapreduce_tpu.ops.softmax import log_softmax

Params = Dict[str, jnp.ndarray]

CIFAR_SHAPE = (32, 32, 3)
N_CLASSES = 10

# (name, kind, shape-spec). LeNet-5 adapted to 32x32x3 inputs:
# conv 5x5x6 → pool → conv 5x5x16 → pool → fc120 → fc84 → fc10.
_CONVS = (("c1", 5, 6), ("c2", 5, 16))
_FCS = (("f1", 120), ("f2", 84), ("f3", N_CLASSES))


def _flat_dim(input_shape: Sequence[int]) -> int:
    h, w, _ = input_shape
    for (_, k, _c) in _CONVS:
        h, w = (h - k + 1) // 2, (w - k + 1) // 2   # VALID conv, 2x2 pool
    return h * w * _CONVS[-1][2]


def init_lenet(key, input_shape: Sequence[int] = CIFAR_SHAPE,
               dtype=jnp.float32) -> Params:
    """Glorot-uniform weights, zero biases; conv weights HWIO."""
    params: Params = {}
    n_params = len(_CONVS) + len(_FCS)
    keys = jax.random.split(key, n_params)
    c_in = input_shape[-1]
    i = 0
    for name, k, c_out in _CONVS:
        fan_in, fan_out = k * k * c_in, k * k * c_out
        bound = jnp.sqrt(6.0 / (fan_in + fan_out))
        params[f"{name}_W"] = jax.random.uniform(
            keys[i], (k, k, c_in, c_out), dtype, -bound, bound)
        params[f"{name}_b"] = jnp.zeros((c_out,), dtype)
        c_in = c_out
        i += 1
    d_in = _flat_dim(input_shape)
    for name, d_out in _FCS:
        bound = jnp.sqrt(6.0 / (d_in + d_out))
        params[f"{name}_W"] = jax.random.uniform(
            keys[i], (d_in, d_out), dtype, -bound, bound)
        params[f"{name}_b"] = jnp.zeros((d_out,), dtype)
        d_in = d_out
        i += 1
    return params


def lenet_apply(params: Params, x: jnp.ndarray, *,
                backend: str = "auto") -> jnp.ndarray:
    """(N,32,32,3) → (N,10) log-probabilities."""
    for name, _k, _c in _CONVS:
        x = conv2d(x, params[f"{name}_W"], params[f"{name}_b"],
                   padding="VALID", backend=backend)
        x = jnp.tanh(x)
        x = maxpool2d(x, window=2, backend=backend)
    x = x.reshape(x.shape[0], -1)
    for name, _d in _FCS[:-1]:
        x = jnp.tanh(x @ params[f"{name}_W"] + params[f"{name}_b"])
    name = _FCS[-1][0]
    logits = x @ params[f"{name}_W"] + params[f"{name}_b"]
    return log_softmax(logits, backend=backend)


def nll_loss(params: Params, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    logp = lenet_apply(params, x)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def accuracy(params: Params, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean(jnp.argmax(lenet_apply(params, x), axis=1) == y)


def flops_per_example(input_shape: Sequence[int] = CIFAR_SHAPE) -> int:
    """Fwd+bwd matmul-equivalent FLOPs per example (MFU accounting)."""
    h, w, c_in = input_shape
    fwd = 0
    for _name, k, c_out in _CONVS:
        ho, wo = h - k + 1, w - k + 1
        fwd += 2 * ho * wo * k * k * c_in * c_out
        h, w, c_in = ho // 2, wo // 2, c_out
    d_in = h * w * c_in
    for _name, d_out in _FCS:
        fwd += 2 * d_in * d_out
        d_in = d_out
    return 3 * fwd
