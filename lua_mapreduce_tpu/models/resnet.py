"""ResNet-18 — the ImageNet-1k conv model (BASELINE.json config 4).

Like LeNet-5 (models/lenet.py), every FLOP goes through this framework's
own TPU ops: ``ops.conv2d`` (im2col → MXU matmul), ``ops.maxpool2d`` /
``ops.avgpool2d`` (Pallas window reductions). The reference delegated its
conv kernels to the external APRIL-ANN toolkit (SURVEY.md §2.4); this is
the TPU-native stand-in at ImageNet scale, fed by the sharded input
pipeline (train/sharding.py, the misc/make_sharded.lua analog named by
BASELINE.json: "misc/make_sharded.lua → GCS shards, 197-split map").

Normalization is GroupNorm rather than BatchNorm: it is stateless (no
running statistics threaded through the trainer or psum'd across the dp
axis), batch-size independent, and keeps params a flat name→array dict —
the per-parameter-name key space the MapReduce grad shuffle partitions on
(the APRIL-ANN example emits gradients keyed by parameter name,
common.lua:85-104). Layouts are TPU-native: activations NHWC, weights
HWIO.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from lua_mapreduce_tpu.ops.conv import conv2d
from lua_mapreduce_tpu.ops.pool import maxpool2d
from lua_mapreduce_tpu.ops.softmax import log_softmax

Params = Dict[str, jnp.ndarray]

IMAGENET_SHAPE = (224, 224, 3)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    """Architecture knobs. Hashable/frozen so it can ride through jit as a
    static argument. ``imagenet18()`` is the BASELINE.json config;
    ``tiny()`` is the same topology at test scale."""
    input_shape: Tuple[int, int, int] = IMAGENET_SHAPE
    n_classes: int = 1000
    widths: Tuple[int, ...] = (64, 128, 256, 512)
    blocks_per_stage: Tuple[int, ...] = (2, 2, 2, 2)
    imagenet_stem: bool = True      # 7x7/2 conv + 3x3/2 maxpool; else 3x3/1
    norm_groups: int = 32

    @staticmethod
    def imagenet18() -> "ResNetConfig":
        return ResNetConfig()

    @staticmethod
    def cifar18() -> "ResNetConfig":
        """ResNet-18 with the standard CIFAR stem (3x3/1, no maxpool)."""
        return ResNetConfig(input_shape=(32, 32, 3), n_classes=10,
                            imagenet_stem=False)

    @staticmethod
    def tiny() -> "ResNetConfig":
        """Full block structure at test scale (fast on CPU)."""
        return ResNetConfig(input_shape=(16, 16, 3), n_classes=10,
                            widths=(8, 16), blocks_per_stage=(1, 1),
                            imagenet_stem=False, norm_groups=4)


def _block_plan(cfg: ResNetConfig):
    """Yield (name, stride, needs_proj, c_in, c_out) for every residual
    block — the single source of the downsampling/projection topology;
    init, apply, and the FLOPs accounting all consume this plan so the
    three can never drift apart."""
    c_in = cfg.widths[0]
    for s, (c_out, n_blocks) in enumerate(
            zip(cfg.widths, cfg.blocks_per_stage)):
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            yield (f"s{s}b{b}", stride, stride != 1 or c_in != c_out,
                   c_in, c_out)
            c_in = c_out


def _groups(cfg: ResNetConfig, c: int) -> int:
    g = min(cfg.norm_groups, c)
    while c % g:
        g -= 1
    return g


def _conv_init(key, k: int, c_in: int, c_out: int, dtype) -> jnp.ndarray:
    """He-normal HWIO weights (relu networks)."""
    std = jnp.sqrt(2.0 / (k * k * c_in))
    return std * jax.random.normal(key, (k, k, c_in, c_out), dtype)


def init_resnet(key, cfg: ResNetConfig = ResNetConfig(),
                dtype=jnp.float32) -> Params:
    """Flat name→array params for the full network.

    Names: ``stem_W``, per block ``s<stage>b<block>_{conv1,conv2,proj}_W``
    plus GroupNorm ``*_g``/``*_be`` scale/bias pairs, final ``fc_W/fc_b``.
    Convs feeding a norm carry no bias. All norm scales init to 1 — a
    zero-init residual scale would leave the branch convs with exactly
    zero gradient at init, breaking the invariant that every parameter
    name carries a live gradient shard through the MapReduce shuffle.
    """
    params: Params = {}
    keys = iter(jax.random.split(key, 4 * sum(cfg.blocks_per_stage) + 2))

    def norm(name: str, c: int):
        params[f"{name}_g"] = jnp.ones((c,), dtype)
        params[f"{name}_be"] = jnp.zeros((c,), dtype)

    c_in = cfg.input_shape[-1]
    k_stem = 7 if cfg.imagenet_stem else 3
    params["stem_W"] = _conv_init(next(keys), k_stem, c_in, cfg.widths[0],
                                  dtype)
    norm("stem_n", cfg.widths[0])

    for p, _stride, needs_proj, c_in, c_out in _block_plan(cfg):
        params[f"{p}_conv1_W"] = _conv_init(next(keys), 3, c_in, c_out,
                                            dtype)
        norm(f"{p}_n1", c_out)
        params[f"{p}_conv2_W"] = _conv_init(next(keys), 3, c_out, c_out,
                                            dtype)
        norm(f"{p}_n2", c_out)
        if needs_proj:
            params[f"{p}_proj_W"] = _conv_init(next(keys), 1, c_in,
                                               c_out, dtype)
            norm(f"{p}_np", c_out)

    c_in = cfg.widths[-1]
    bound = jnp.sqrt(6.0 / (c_in + cfg.n_classes))
    params["fc_W"] = jax.random.uniform(next(keys), (c_in, cfg.n_classes),
                                        dtype, -bound, bound)
    params["fc_b"] = jnp.zeros((cfg.n_classes,), dtype)
    return params


def _group_norm(params: Params, name: str, x: jnp.ndarray,
                groups: int) -> jnp.ndarray:
    n, h, w, c = x.shape
    xg = x.reshape(n, h, w, groups, c // groups)
    mean = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xg, axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + 1e-5)
    return xg.reshape(n, h, w, c) * params[f"{name}_g"] \
        + params[f"{name}_be"]


def _stem(params: Params, x: jnp.ndarray, cfg: ResNetConfig,
          backend: str) -> jnp.ndarray:
    if cfg.imagenet_stem:
        x = conv2d(x, params["stem_W"], stride=2, padding="SAME",
                   backend=backend)
        x = jax.nn.relu(_group_norm(params, "stem_n", x,
                                    _groups(cfg, x.shape[-1])))
        # SAME 3x3/2 maxpool = pad 1 with -inf, then VALID window
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)),
                    constant_values=-jnp.inf)
        return maxpool2d(x, window=3, stride=2, backend=backend)
    x = conv2d(x, params["stem_W"], stride=1, padding="SAME",
               backend=backend)
    return jax.nn.relu(_group_norm(params, "stem_n", x,
                                   _groups(cfg, x.shape[-1])))


def resnet_apply(params: Params, x: jnp.ndarray, *,
                 cfg: ResNetConfig = ResNetConfig(),
                 backend: str = "auto") -> jnp.ndarray:
    """(N, H, W, C) → (N, n_classes) log-probabilities."""
    x = _stem(params, x, cfg, backend)
    for p, stride, needs_proj, _c_in, c_out in _block_plan(cfg):
        g = _groups(cfg, c_out)
        h = conv2d(x, params[f"{p}_conv1_W"], stride=stride,
                   padding="SAME", backend=backend)
        h = jax.nn.relu(_group_norm(params, f"{p}_n1", h, g))
        h = conv2d(h, params[f"{p}_conv2_W"], stride=1, padding="SAME",
                   backend=backend)
        h = _group_norm(params, f"{p}_n2", h, g)
        if needs_proj:
            x = conv2d(x, params[f"{p}_proj_W"], stride=stride,
                       padding="SAME", backend=backend)
            x = _group_norm(params, f"{p}_np", x, g)
        x = jax.nn.relu(x + h)
    # global average pool: a full-map mean has no window structure for the
    # pooling kernels to exploit — one XLA reduction is the right lowering
    x = jnp.mean(x, axis=(1, 2))
    logits = x @ params["fc_W"] + params["fc_b"]
    return log_softmax(logits, backend=backend)


def make_loss(cfg: ResNetConfig, backend: str = "auto"):
    """``loss_fn(params, x, y)`` closure for the DP trainer (mean NLL)."""
    def nll_loss(params, x, y):
        logp = resnet_apply(params, x, cfg=cfg, backend=backend)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
    return nll_loss


def accuracy(params: Params, x, y, *, cfg: ResNetConfig = ResNetConfig(),
             backend: str = "auto") -> jnp.ndarray:
    return jnp.mean(
        jnp.argmax(resnet_apply(params, x, cfg=cfg, backend=backend),
                   axis=1) == y)


def flops_per_example(cfg: ResNetConfig = ResNetConfig()) -> int:
    """Fwd+bwd matmul-equivalent FLOPs per example (MFU accounting)."""
    h, w, c_in = cfg.input_shape

    def conv_flops(h, w, k, s, c_in, c_out):
        ho, wo = -(-h // s), -(-w // s)     # SAME
        return ho, wo, 2 * ho * wo * k * k * c_in * c_out

    fwd = 0
    if cfg.imagenet_stem:
        h, w, f = conv_flops(h, w, 7, 2, c_in, cfg.widths[0])
        fwd += f
        h, w = -(-h // 2), -(-w // 2)       # 3x3/2 SAME maxpool
    else:
        h, w, f = conv_flops(h, w, 3, 1, c_in, cfg.widths[0])
        fwd += f
    for _p, stride, needs_proj, c_in, c_out in _block_plan(cfg):
        ho, wo, f1 = conv_flops(h, w, 3, stride, c_in, c_out)
        _, _, f2 = conv_flops(ho, wo, 3, 1, c_out, c_out)
        fwd += f1 + f2
        if needs_proj:
            fwd += conv_flops(h, w, 1, stride, c_in, c_out)[2]
        h, w = ho, wo
    fwd += 2 * cfg.widths[-1] * cfg.n_classes
    return 3 * fwd
