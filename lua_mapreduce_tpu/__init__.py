"""lua_mapreduce_tpu — a TPU-native MapReduce framework.

A brand-new framework with the capabilities of pakozm/lua-mapreduce
(reference: /root/reference, see SURVEY.md): a fault-tolerant, iterative
MapReduce engine with six pluggable user functions, an elastic worker pool,
pluggable intermediate storage, and a data-parallel training harness —
re-designed TPU-first:

- map phases compile to pjit-sharded computations over a ``jax.sharding.Mesh``
- combiners/reducers lower to ``psum`` / ``reduce_scatter`` / ``all_to_all``
  collectives over ICI instead of a shuffle through a database
- intermediate data lives in host DRAM with shared-dir / object-store spill
- a single-controller coordinator owns job state, fault tolerance and
  checkpoint/resume, entirely off the jitted hot path

Public API (parity with reference mapreduce/init.lua:25-38):
    server, worker, utils, tuples (interned tuples), persistent_table, utest
"""

from lua_mapreduce_tpu.core import tuples
from lua_mapreduce_tpu.engine.contract import TaskSpec
from lua_mapreduce_tpu.engine.local import LocalExecutor

__version__ = "0.1.0"

_LAZY = {
    "Server": ("lua_mapreduce_tpu.engine.server", "Server"),
    "PhaseFailed": ("lua_mapreduce_tpu.engine.server", "PhaseFailed"),
    "Worker": ("lua_mapreduce_tpu.engine.worker", "Worker"),
    "MemJobStore": ("lua_mapreduce_tpu.coord.jobstore", "MemJobStore"),
    "FileJobStore": ("lua_mapreduce_tpu.coord.filestore", "FileJobStore"),
    "PersistentTable": ("lua_mapreduce_tpu.coord.persistent_table",
                        "PersistentTable"),
    # fault subsystem (DESIGN §19)
    "StoreError": ("lua_mapreduce_tpu.faults.errors", "StoreError"),
    "TransientStoreError": ("lua_mapreduce_tpu.faults.errors",
                            "TransientStoreError"),
    "PermanentStoreError": ("lua_mapreduce_tpu.faults.errors",
                            "PermanentStoreError"),
    "RetryPolicy": ("lua_mapreduce_tpu.faults.retry", "RetryPolicy"),
    "FaultPlan": ("lua_mapreduce_tpu.faults.plan", "FaultPlan"),
    # in-graph engine (DESIGN §26)
    "InGraphEngine": ("lua_mapreduce_tpu.engine.ingraph", "InGraphEngine"),
    "LoweringError": ("lua_mapreduce_tpu.engine.ingraph", "LoweringError"),
    # lmr-trace (DESIGN §22)
    "Tracer": ("lua_mapreduce_tpu.trace.span", "Tracer"),
    "TraceCollection": ("lua_mapreduce_tpu.trace.collect",
                        "TraceCollection"),
    # lmr-sched (DESIGN §23)
    "Tenant": ("lua_mapreduce_tpu.sched.tenancy", "Tenant"),
    "TenantView": ("lua_mapreduce_tpu.sched.tenancy", "TenantView"),
    "FairWorker": ("lua_mapreduce_tpu.sched.tenancy", "FairWorker"),
    "FairScheduler": ("lua_mapreduce_tpu.sched.tenancy", "FairScheduler"),
    "AdmissionError": ("lua_mapreduce_tpu.sched.tenancy",
                       "AdmissionError"),
    "Waiter": ("lua_mapreduce_tpu.sched.waiter", "Waiter"),
    # lmr-ha (DESIGN §31)
    "LeaderLease": ("lua_mapreduce_tpu.sched.lease", "LeaderLease"),
    "FencedJobStore": ("lua_mapreduce_tpu.sched.lease", "FencedJobStore"),
    "StaleLeaderError": ("lua_mapreduce_tpu.faults.errors",
                         "StaleLeaderError"),
}


def __getattr__(name):
    """Lazy exports — the distributed engine pulls in the coordinator; the
    contract/local layers stay importable on their own."""
    try:
        modname, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib
    return getattr(importlib.import_module(modname), attr)

__all__ = [
    "TaskSpec",
    "LocalExecutor",
    "Server",
    "PhaseFailed",
    "Worker",
    "MemJobStore",
    "FileJobStore",
    "PersistentTable",
    "InGraphEngine",
    "LoweringError",
    "StoreError",
    "TransientStoreError",
    "PermanentStoreError",
    "RetryPolicy",
    "FaultPlan",
    "Tracer",
    "TraceCollection",
    "Tenant",
    "TenantView",
    "FairWorker",
    "FairScheduler",
    "AdmissionError",
    "Waiter",
    "LeaderLease",
    "FencedJobStore",
    "StaleLeaderError",
    "tuples",
    "utest",
]


def utest():
    """Run every module's self-test (reference mapreduce/test.lua:30-39)."""
    from lua_mapreduce_tpu import analysis, faults, sched, trace
    from lua_mapreduce_tpu.core import heap, merge, segment, serialize
    from lua_mapreduce_tpu.coord import jobstore, persistent_table
    from lua_mapreduce_tpu.engine import (contract, ingraph, placement,
                                          premerge, push, server, worker)
    from lua_mapreduce_tpu.store import memfs, router
    from lua_mapreduce_tpu.utils import lockcheck, stats

    # host-path modules ONLY: the sweep runs in the ambient env (test.sh)
    # where any jax compute would initialize — and hang on — a wedged
    # accelerator tunnel; jax-computing modules (ops/*) self-test under
    # the cpu-pinned pytest conftest instead (tests/test_q8.py etc.)
    # ingraph's utest is host-only by design (knob resolution + the
    # static oracle consult); its compiled tiers live in
    # tests/test_ingraph.py under the cpu-pinned conftest
    for mod in (tuples, heap, serialize, segment, merge, jobstore, memfs,
                contract, router, persistent_table, stats, placement,
                premerge, push, worker, server, ingraph, analysis, faults,
                trace, sched, lockcheck):
        if hasattr(mod, "utest"):
            mod.utest()
