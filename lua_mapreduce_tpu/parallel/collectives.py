"""Collective primitives over the mesh — the shuffle's transport.

The reference moves intermediate data through GridFS/sharedfs/sshfs files
(SURVEY.md §2.6); here the equivalent bytes ride ICI as XLA collectives.
These wrappers operate on pytrees and keep the mapping to the reference
explicit:

- ``psum_tree``            — reducefn with assoc+commut flags ≈ all-reduce
- ``reduce_scatter_tree``  — same, but each reducer keeps only its
                             partition (one reduce job per partition,
                             server.lua:300-325)
- ``all_to_all_buckets``   — partitionfn bucketing: every mapper sends
                             bucket p to reducer p (the shuffle itself)
- ``all_gather_tree``      — result collection (server_final's pair
                             iterator over all partitions)
- ``ppermute_ring``        — neighbor exchange; building block for ring
                             schedules (long-context sequence parallelism)
"""

from __future__ import annotations

import jax
from jax import lax


def psum_tree(tree, axis: str):
    """Sum every leaf across ``axis`` (full all-reduce on ICI)."""
    return jax.tree.map(lambda x: lax.psum(x, axis), tree)


def pmean_tree(tree, axis: str):
    return jax.tree.map(lambda x: lax.pmean(x, axis), tree)


def reduce_scatter_tree(tree, axis: str, scatter_dimension: int = 0,
                        tiled: bool = True):
    """Sum across ``axis`` but scatter the result: device i keeps slice i
    along ``scatter_dimension``. Halves the wire bytes of psum when each
    reducer only needs its own partition."""
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis,
                                   scatter_dimension=scatter_dimension,
                                   tiled=tiled),
        tree)


def all_to_all_buckets(x, axis: str, bucket_dim: int = 0):
    """The shuffle: ``x`` has a leading bucket dimension of size
    ``mesh.shape[axis]`` (one bucket per partition, built by the caller's
    partitionfn); after the exchange, device p holds every mapper's bucket
    p, concatenated along ``bucket_dim``.

    Shape: [P, ...] → [P, ...] where the leading axis switches meaning from
    "destination partition" to "source mapper" — exactly the
    map-output-files → reduce-job-input relabeling of server_prepare_reduce
    (server.lua:291-312).
    """
    return lax.all_to_all(x, axis, split_axis=bucket_dim,
                          concat_axis=bucket_dim, tiled=False)


def all_gather_tree(tree, axis: str, gather_dimension: int = 0):
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis, axis=gather_dimension, tiled=True),
        tree)


def ppermute_ring(x, axis: str, mesh_size: int, shift: int = 1):
    """Rotate shards around the ring: device i → device (i+shift) % N.
    The building block for ring-based schedules (ring attention / ring
    all-reduce) where each step overlaps compute with neighbor DMA."""
    perm = [(i, (i + shift) % mesh_size) for i in range(mesh_size)]
    return lax.ppermute(x, axis, perm)
