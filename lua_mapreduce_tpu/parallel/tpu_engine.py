"""The SPMD MapReduce executor.

Compiles an :class:`ArrayTaskSpec` to a single jitted program over a mesh
(SURVEY.md §7 step 5). Two shuffle shapes cover the reference's reduce
topologies (SURVEY.md §2.5-2.6):

- **keyed** (:meth:`TpuExecutor.run_keyed`): mapfn's output pytree keys are
  the key space; reduction is an associative collective across the ``dp``
  axis (psum & friends). This is the APRIL-ANN DP-SGD shape: map = shard
  gradient, combine = local batch fold, reduce = all-reduce over ICI.

- **bucketed** (:meth:`TpuExecutor.run_bucketed`): the user partitionfn
  buckets each shard's output into a leading axis of NUM_PARTITIONS;
  ``all_to_all`` redistributes buckets so device p holds every mapper's
  bucket p; a local fold finishes the reduce. This is the general
  partitionfn → per-partition reduce-job shape with the shuffle riding ICI
  instead of intermediate storage files.

Everything under jit is traced once: no data-dependent Python control flow,
static shapes, XLA-fused combiners (the MAX_MAP_RESULT streaming threshold
of the host path, job.lua:92-96, has no device analog — on TPU the combine
is a register/VMEM-level fusion, which is the whole point).

This module is the EXPLICIT array-native surface: users hand it an
:class:`ArrayTaskSpec` already written as a traceable array program.
Since the fusion of the repo's two halves (DESIGN §26), ordinary
six-function tasks (engine/contract.TaskSpec) whose data plane the
static oracle verdicts ``in-graph`` reach this plane AUTOMATICALLY:
engine/ingraph.py lowers them to the same shard_map-over-mesh shapes,
reusing this module's ``_CROSS`` collective table and parallel/mesh.py
rather than reimplementing them — TpuExecutor stays the right tool
when you want to write the array program yourself.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from lua_mapreduce_tpu.parallel.array_task import ArrayTaskSpec
from lua_mapreduce_tpu.utils.jax_compat import shard_map

_CROSS = {
    "sum": lax.psum,
    "mean": lax.pmean,
    "max": lax.pmax,
    "min": lax.pmin,
}

_LOCAL = {
    "sum": jnp.sum,
    "mean": jnp.mean,
    "max": jnp.max,
    "min": jnp.min,
}


class TpuExecutor:
    """Execute a traceable MapReduce over a mesh.

    ``axis`` names the mesh axis that plays the map-shard role (default
    ``dp``). Compiled programs are cached per (mode, scatter) — repeated
    runs (the "loop" protocol) pay zero retrace.
    """

    def __init__(self, spec: ArrayTaskSpec, mesh, axis: str = "dp"):
        self.spec = spec
        self.mesh = mesh
        self.axis = axis
        self.n_shards = mesh.shape[axis]

    # -- input placement ----------------------------------------------------

    def shard_inputs(self, batch):
        """Place a global batch with the leading axis sharded over the map
        axis — the taskfn role: each device's slice is its map job."""
        sharding = NamedSharding(self.mesh, P(self.axis))
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), batch)

    # -- keyed reduction (psum shape) ---------------------------------------

    @functools.cached_property
    def _keyed_fn(self):
        spec, axis = self.spec, self.axis
        cross = _CROSS[spec.reduce_op]

        def per_shard(batch):
            out = spec.mapfn(batch)
            if spec.combinerfn is not None:
                out = spec.combinerfn(out)
            return jax.tree.map(lambda x: cross(x, axis), out)

        shard_spec = P(self.axis)
        mapped = shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(shard_spec,), out_specs=P())
        return jax.jit(mapped)

    def run_keyed(self, batch) -> Any:
        """map → combine → all-reduce. Returns the replicated reduced
        pytree (every device holds the full result, like every reference
        worker seeing the final reduce output in GridFS)."""
        result = self._keyed_fn(self.shard_inputs(batch))
        if self.spec.finalfn is not None:
            return self.spec.finalfn(result)
        return result

    # -- bucketed shuffle (all_to_all shape) --------------------------------

    @functools.cached_property
    def _bucketed_fn(self):
        spec, axis, n = self.spec, self.axis, self.n_shards
        if spec.partitionfn is None:
            raise ValueError("bucketed mode needs spec.partitionfn")
        num_p = spec.num_partitions
        if num_p % n:
            raise ValueError(
                f"num_partitions={num_p} must be a multiple of the mesh "
                f"axis size {n} (pad partitions; empty ones are cheap)")
        per_dev = num_p // n
        local = _LOCAL[spec.reduce_op]

        def per_shard(batch):
            out = spec.mapfn(batch)
            if spec.combinerfn is not None:
                out = spec.combinerfn(out)
            buckets = spec.partitionfn(out)      # [num_p, ...] per mapper

            def shuffle_reduce(b):
                # [num_p, ...] → [n, per_dev, ...]: outer = destination
                b = b.reshape((n, per_dev) + b.shape[1:])
                # exchange: device p receives every mapper's buckets for
                # its per_dev partitions → [n(mappers), per_dev, ...]
                b = lax.all_to_all(b, axis, split_axis=0, concat_axis=0,
                                   tiled=False)
                # fold over the mapper axis — the k-way merge + reducefn
                return local(b, axis=0)          # [per_dev, ...]

            return jax.tree.map(shuffle_reduce, buckets)

        mapped = shard_map(
            per_shard, mesh=self.mesh,
            in_specs=(P(self.axis),), out_specs=P(self.axis))
        return jax.jit(mapped)

    def run_bucketed(self, batch) -> Any:
        """map → combine → partition → all_to_all shuffle → local reduce.
        Returns the pytree with the partition axis sharded over the mesh
        (device p owns partitions [p*per_dev, (p+1)*per_dev) — one "reduce
        job per partition", server.lua:300-325)."""
        result = self._bucketed_fn(self.shard_inputs(batch))
        if self.spec.finalfn is not None:
            return self.spec.finalfn(result)
        return result

    # -- iterative loop (the "loop" protocol, on device) --------------------

    def run_loop(self, init_state, step_fn, n_steps: int):
        """Run ``state = step_fn(state, executor-reduced-result)`` for
        ``n_steps`` iterations entirely inside one jitted ``lax.scan`` —
        the zero-coordination-round-trips hot loop (BASELINE.md north
        star). ``step_fn(state) -> (state, aux)`` must itself invoke this
        executor's keyed pipeline via closures over mapfn; provided here
        as the generic scan harness used by train/.
        """
        def body(state, _):
            return step_fn(state)

        @jax.jit
        def scan_all(state):
            return lax.scan(body, state, None, length=n_steps)

        return scan_all(init_state)


def differentiable_keyed(mapfn, mesh, axis: str = "dp",
                         reduce_op: str = "mean"):
    """A DIFFERENTIABLE keyed MapReduce primitive (the DrJAX shape:
    arXiv:2403.07128 exposes map/reduce as primitives grads flow
    through).

    ``mapfn(params, shard) -> pytree`` runs per device on its shard of
    the batch; the returned ``f(params, batch) -> reduced`` replicates
    the cross-device reduction's result and is traceable INSIDE user jit
    / grad / vmap. The backward pass is automatic: psum/pmean transpose
    to broadcast (+scale), so ``jax.grad(lambda p: loss(f(p, batch)))``
    differentiates through both the map and the collective — this is
    exactly how the DP trainer's gradient all-reduce arises, exposed as
    a reusable primitive for custom aggregation programs (federated
    means, per-key statistics, distributed EM steps).

    Only ``sum`` and ``mean`` are permitted: pmax/pmin have no JAX
    differentiation rule, which would break this primitive's one
    advertised contract at grad time (use TpuExecutor for forward-only
    max/min reductions).
    """
    if reduce_op not in ("sum", "mean"):
        raise ValueError(
            f"differentiable_keyed needs reduce_op 'sum' or 'mean', got "
            f"{reduce_op!r} — pmax/pmin are not differentiable; use "
            "TpuExecutor.run_keyed for forward-only max/min")
    cross = _CROSS[reduce_op]

    def per_shard(params, batch):
        out = mapfn(params, batch)
        return jax.tree.map(lambda x: cross(x, axis), out)

    return shard_map(per_shard, mesh=mesh,
                         in_specs=(P(), P(axis)), out_specs=P())
