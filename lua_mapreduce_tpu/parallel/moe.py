"""Expert parallelism: switch-style mixture-of-experts with all_to_all
token routing.

The third shuffle topology of the framework (after the keyed psum and the
partitionfn-bucketed all_to_all of parallel/tpu_engine.py): the ROUTER is
a learned partitionfn — each token picks an expert, tokens are bucketed
per expert under a fixed capacity (static shapes: XLA cannot trace
data-dependent bucket sizes), and one ``all_to_all`` over the ``ep`` mesh
axis carries every device's buckets to the devices owning those experts,
exactly how the reference's map outputs travel to their partition's
reducer (SURVEY.md §2.6). A second all_to_all brings expert outputs home,
where the gate's combine weights merge them.

Capacity semantics are the standard switch-transformer ones: per device
tile, expert e keeps the first ``capacity`` tokens routed to it (position
by cumulative count in token order); overflow tokens are DROPPED — their
combine weight is zero, so they pass through the residual connection
unchanged. The load-balancing auxiliary loss (fraction-routed ×
mean-gate-probability, scaled by E) keeps the router from collapsing onto
few experts.

Two forms, golden-diffed in tests: :func:`moe_ffn_reference` (one device,
all experts local) and :func:`moe_ffn_shard` (inside shard_map, experts
sharded over ``ep``) — identical routing, identical outputs.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, jnp.ndarray]


def init_moe(key, d_model: int, d_ff: int, n_experts: int,
             dtype=jnp.float32, prefix: str = "moe") -> Params:
    """Router + per-expert FFN weights (E stacked), flat name→array."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(jnp.asarray(d_model, jnp.float32))
    s2 = 1.0 / jnp.sqrt(jnp.asarray(d_ff, jnp.float32))
    return {
        f"{prefix}_router_W": s1 * jax.random.normal(
            k1, (d_model, n_experts), dtype),
        f"{prefix}_w1": s1 * jax.random.normal(
            k2, (n_experts, d_model, d_ff), dtype),
        f"{prefix}_b1": jnp.zeros((n_experts, d_ff), dtype),
        f"{prefix}_w2": s2 * jax.random.normal(
            k3, (n_experts, d_ff, d_model), dtype),
        f"{prefix}_b2": jnp.zeros((n_experts, d_model), dtype),
    }


def _route(x, router_w, n_experts: int, capacity: int, top_k: int = 1):
    """Top-k routing with capacity: returns (dispatch (T,E,C) one-hot,
    combine (T,E,C) gate-weighted, aux_loss scalar). x is the flat
    (T, d) token tile of ONE device.

    ``top_k=1`` is the switch transformer; ``top_k>1`` is the
    Mixtral-style generalization: each token is dispatched to its k
    highest-gated experts, combine weights RENORMALIZED over the
    selected k (pre-drop, so a capacity-dropped expert's share is lost
    through the residual rather than silently inflating the survivor).
    Capacity is per (expert, tile) across ALL k rounds — round j's
    tokens take slots after rounds < j's, so total bucket occupancy
    never exceeds C and the dispatch einsum shapes stay static."""
    gates = jax.nn.softmax(x.astype(jnp.float32) @ router_w.astype(
        jnp.float32), axis=-1)                          # (T, E)
    t = x.shape[0]
    counts = jnp.zeros((n_experts,), jnp.float32)       # slots used
    dispatch = jnp.zeros((t, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, n_experts, capacity), jnp.float32)
    sel_sum = jnp.zeros((t,), jnp.float32)              # renorm denom
    frac = jnp.zeros((n_experts,), jnp.float32)
    # all k choices in ONE top_k call — iterated argmax-and-mask over
    # softmax probs re-picks expert 0 when non-selected gates underflow
    # to exactly 0.0 (router margin > ~103 nats), silently consuming a
    # foreign expert's capacity slot
    _, topk_idx = jax.lax.top_k(gates, top_k)           # (T, k)
    for j in range(top_k):
        expert = topk_idx[:, j]                         # (T,)
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
        # position of each token within its expert's bucket: this
        # round's token order, offset by earlier rounds' occupancy
        pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
               + counts[None, :]) * onehot              # (T, E)
        kept = onehot * (pos < capacity)                # drop overflow
        counts = counts + jnp.sum(kept, axis=0)
        pos_c = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32),
                               capacity, dtype=jnp.float32)  # (T, C)
        disp_j = kept[:, :, None] * pos_c[:, None, :]   # (T, E, C)
        gate_j = jnp.sum(gates * kept, axis=-1)         # (T,) kept gate
        dispatch = dispatch + disp_j
        combine = combine + disp_j * gate_j[:, None, None]
        sel_sum = sel_sum + jnp.sum(gates * onehot, axis=-1)
        frac = frac + jnp.mean(onehot, axis=0)
    if top_k > 1:
        combine = combine / jnp.maximum(sel_sum, 1e-9)[:, None, None]
    # switch aux loss generalized: E * Σ_e (fraction routed_e / k) ×
    # mean_prob_e (reduces to the switch loss at k = 1)
    prob = jnp.mean(gates, axis=0)
    aux = n_experts * jnp.sum((frac / top_k) * prob)
    return dispatch, combine, aux


def _expert_ffn(w1, b1, w2, b2, x):
    """Batched expert FFN: x (E, C, d) → (E, C, d), one einsum pair on
    the MXU per layer."""
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", x, w1) + b1[:, None, :])
    return jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]


def _moe_ffn(params: Params, x, capacity: int, prefix: str,
             ep_axis, top_k: int = 1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One body for both forms — ``ep_axis=None`` keeps everything local
    (the oracle); a mesh axis inserts the two all_to_all shuffles. The
    two forms are contractually golden-diffed, so they MUST share this
    routing/compute path."""
    w = {k[len(prefix) + 1:]: v for k, v in params.items()
         if k.startswith(prefix + "_")}
    n_experts = w["router_W"].shape[1]          # GLOBAL expert count
    dispatch, combine, aux = _route(x, w["router_W"], n_experts, capacity,
                                    top_k=top_k)
    xe = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    if ep_axis is not None:
        # (E, C, d) → (E/ep, ep·C, d): device p receives every peer's
        # bucket for its local experts — the shuffle
        xe = lax.all_to_all(xe, ep_axis, split_axis=0, concat_axis=1,
                            tiled=True)
    ye = _expert_ffn(w["w1"].astype(jnp.float32),
                     w["b1"].astype(jnp.float32),
                     w["w2"].astype(jnp.float32),
                     w["b2"].astype(jnp.float32), xe)
    if ep_axis is not None:
        # inverse shuffle: outputs return to their source devices
        ye = lax.all_to_all(ye, ep_axis, split_axis=1, concat_axis=0,
                            tiled=True)
    out = jnp.einsum("tec,ecd->td", combine, ye)
    if ep_axis is not None:
        # aux is per-tile; average across the ep group so every device
        # carries the same scalar (replicated, ready for the loss)
        aux = lax.pmean(aux, ep_axis)
    return out.astype(x.dtype), aux


def moe_ffn_reference(params: Params, x, *, capacity: int,
                      prefix: str = "moe", top_k: int = 1
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-device oracle: (T, d) tokens → ((T, d) out, aux loss)."""
    return _moe_ffn(params, x, capacity, prefix, None, top_k=top_k)


def moe_ffn_shard(params: Params, x, *, capacity: int, ep_axis: str,
                  prefix: str = "moe", top_k: int = 1
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel form (inside shard_map): router weights are
    replicated, expert weights are LOCAL slices (E/ep experts per
    device); two all_to_alls move token buckets out and back.

    Equivalent to the reference with the same capacity per (device,
    expert) bucket: each device's tile routes independently, so a
    reference run over the concatenated tiles with per-tile routing
    produces identical outputs (the golden-diff in tests).
    """
    return _moe_ffn(params, x, capacity, prefix, ep_axis, top_k=top_k)
